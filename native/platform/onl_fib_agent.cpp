// openr-tpu standalone FIB agent — native equivalent of the reference's
// platform_linux binary (openr/platform/LinuxPlatformMain.cpp, target
// CMakeLists.txt:410): a FibService server that programs the Linux kernel
// FIB through the native netlink library (../nl/onl_netlink.h) so the
// kernel-facing agent runs without a Python runtime.
//
// Wire protocol: newline-delimited JSON over TCP, same RPC shape as the
// ctrl server ({"id", "method", "params"} -> {"id", "result"|"error"}),
// methods mirroring openr/if/Platform.thrift FibService:116-204:
//   aliveSince, addUnicastRoutes, deleteUnicastRoutes, syncFib,
//   addMplsRoutes, deleteMplsRoutes, syncMplsFib,
//   getRouteTableByClient, getMplsRouteTableByClient
//
// --dryrun keeps the route table in memory only (no kernel writes), which
// is how tests exercise the full binary + wire protocol without privileges.
// --port 0 binds an ephemeral port; the agent prints "LISTENING <port>" on
// stdout either way so a supervisor can parse it.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "../nl/onl_netlink.h"

// ---------------------------------------------------------------------------
// Minimal JSON (objects, arrays, strings, ints, bools, null) — enough for
// the FibService wire shapes; no external deps in this image.
// ---------------------------------------------------------------------------

struct Json {
  enum Type { NUL, BOOL, INT, STR, ARR, OBJ } type = NUL;
  bool b = false;
  long long i = 0;
  std::string s;
  std::vector<Json> arr;
  std::vector<std::pair<std::string, Json>> obj;

  const Json* get(const std::string& key) const {
    for (auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
  long long get_int(const std::string& key, long long dflt = 0) const {
    const Json* v = get(key);
    return v && v->type == INT ? v->i : dflt;
  }
  std::string get_str(const std::string& key, const std::string& d = "") const {
    const Json* v = get(key);
    return v && v->type == STR ? v->s : d;
  }
};

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const std::string& text)
      : p(text.data()), end(text.data() + text.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool lit(const char* w) {
    size_t n = strlen(w);
    if (size_t(end - p) >= n && memcmp(p, w, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }
  Json parse() {
    ws();
    Json out;
    if (p >= end) {
      ok = false;
      return out;
    }
    char c = *p;
    if (c == '{') {
      ++p;
      out.type = Json::OBJ;
      ws();
      if (p < end && *p == '}') {
        ++p;
        return out;
      }
      while (ok) {
        ws();
        Json key = parse();
        if (key.type != Json::STR) {
          ok = false;
          break;
        }
        ws();
        if (p >= end || *p != ':') {
          ok = false;
          break;
        }
        ++p;
        Json val = parse();
        out.obj.emplace_back(key.s, std::move(val));
        ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        if (p < end && *p == '}') {
          ++p;
          break;
        }
        ok = false;
      }
    } else if (c == '[') {
      ++p;
      out.type = Json::ARR;
      ws();
      if (p < end && *p == ']') {
        ++p;
        return out;
      }
      while (ok) {
        out.arr.push_back(parse());
        ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        if (p < end && *p == ']') {
          ++p;
          break;
        }
        ok = false;
      }
    } else if (c == '"') {
      ++p;
      out.type = Json::STR;
      while (p < end && *p != '"') {
        if (*p == '\\' && p + 1 < end) {
          ++p;
          switch (*p) {
            case 'n': out.s += '\n'; break;
            case 't': out.s += '\t'; break;
            case 'r': out.s += '\r'; break;
            case '"': out.s += '"'; break;
            case '\\': out.s += '\\'; break;
            case '/': out.s += '/'; break;
            default: out.s += *p;  // \uXXXX unsupported (ASCII protocol)
          }
          ++p;
        } else {
          out.s += *p++;
        }
      }
      if (p < end)
        ++p;
      else
        ok = false;
    } else if (c == 't' && lit("true")) {
      out.type = Json::BOOL;
      out.b = true;
    } else if (c == 'f' && lit("false")) {
      out.type = Json::BOOL;
    } else if (c == 'n' && lit("null")) {
      out.type = Json::NUL;
    } else if (c == '-' || (c >= '0' && c <= '9')) {
      out.type = Json::INT;
      char* e = nullptr;
      out.i = strtoll(p, &e, 10);
      if (e == p)
        ok = false;
      else
        p = e;
      // fractional part: truncate (protocol uses ints only)
      if (p < end && *p == '.') {
        ++p;
        while (p < end && *p >= '0' && *p <= '9') ++p;
      }
    } else {
      ok = false;
    }
    return out;
  }
};

static void dump(const Json& v, std::string& out) {
  char buf[32];
  switch (v.type) {
    case Json::NUL: out += "null"; break;
    case Json::BOOL: out += v.b ? "true" : "false"; break;
    case Json::INT:
      snprintf(buf, sizeof buf, "%lld", v.i);
      out += buf;
      break;
    case Json::STR:
      out += '"';
      for (char c : v.s) {
        if (c == '"' || c == '\\') {
          out += '\\';
          out += c;
        } else if (c == '\n') {
          out += "\\n";
        } else {
          out += c;
        }
      }
      out += '"';
      break;
    case Json::ARR:
      out += '[';
      for (size_t i = 0; i < v.arr.size(); ++i) {
        if (i) out += ',';
        dump(v.arr[i], out);
      }
      out += ']';
      break;
    case Json::OBJ:
      out += '{';
      for (size_t i = 0; i < v.obj.size(); ++i) {
        if (i) out += ',';
        Json k;
        k.type = Json::STR;
        k.s = v.obj[i].first;
        dump(k, out);
        out += ':';
        dump(v.obj[i].second, out);
      }
      out += '}';
      break;
  }
}

static Json jint(long long v) {
  Json j;
  j.type = Json::INT;
  j.i = v;
  return j;
}
static Json jstr(const std::string& v) {
  Json j;
  j.type = Json::STR;
  j.s = v;
  return j;
}
static Json jarr() {
  Json j;
  j.type = Json::ARR;
  return j;
}
static Json jobj() {
  Json j;
  j.type = Json::OBJ;
  return j;
}

// ---------------------------------------------------------------------------
// Agent state: per-client route tables (mirrors NetlinkFibHandler's
// protocol-tagged kernel view; authoritative in dryrun, a cache otherwise).
// ---------------------------------------------------------------------------

struct Nexthop {
  std::string via;
  std::string iface;
  int weight = 0;
  int mpls_action = 0;
  std::vector<int> labels;
};

struct Agent {
  bool dryrun = false;
  void* nl = nullptr;
  long long alive_since = 0;
  // client id -> route tables
  std::map<int, std::map<std::string, std::vector<Nexthop>>> unicast;
  std::map<int, std::map<int, std::vector<Nexthop>>> mpls;
  std::map<std::string, int> if_index;

  std::string err;

  bool refresh_links() {
    if (dryrun) return true;
    onl_link links[512];
    int n = onl_get_links(nl, links, 512);
    if (n < 0) {
      err = onl_strerror(nl);
      return false;
    }
    if_index.clear();
    for (int i = 0; i < n; ++i) if_index[links[i].name] = links[i].ifindex;
    return true;
  }

  bool to_onl(const std::vector<Nexthop>& nhs, std::vector<onl_nexthop>& out) {
    out.clear();
    for (const auto& nh : nhs) {
      onl_nexthop o;
      memset(&o, 0, sizeof o);
      snprintf(o.via, sizeof o.via, "%s", nh.via.c_str());
      if (!nh.iface.empty()) {
        auto it = if_index.find(nh.iface);
        if (it == if_index.end()) {
          refresh_links();
          it = if_index.find(nh.iface);
          if (it == if_index.end()) {
            err = "unknown interface " + nh.iface;
            return false;
          }
        }
        o.ifindex = it->second;
      }
      o.weight = nh.weight;
      o.mpls_action = nh.mpls_action;
      o.num_labels = (int)nh.labels.size() > 8 ? 8 : (int)nh.labels.size();
      for (int i = 0; i < o.num_labels; ++i) o.labels[i] = nh.labels[i];
      out.push_back(o);
    }
    return true;
  }

  bool k_add_unicast(const std::string& dest, const std::vector<Nexthop>& nhs) {
    if (dryrun) return true;
    std::vector<onl_nexthop> o;
    if (!to_onl(nhs, o)) return false;
    if (onl_add_unicast_route(nl, dest.c_str(), 99, 254, o.data(),
                              (int)o.size(), 1) != 0) {
      err = onl_strerror(nl);
      return false;
    }
    return true;
  }
  bool k_del_unicast(const std::string& dest) {
    if (dryrun) return true;
    if (onl_del_unicast_route(nl, dest.c_str(), 99, 254) != 0) {
      err = onl_strerror(nl);
      return false;
    }
    return true;
  }
  bool k_add_mpls(int label, const std::vector<Nexthop>& nhs) {
    if (dryrun) return true;
    std::vector<onl_nexthop> o;
    if (!to_onl(nhs, o)) return false;
    if (onl_add_mpls_route(nl, label, o.data(), (int)o.size(), 1) != 0) {
      err = onl_strerror(nl);
      return false;
    }
    return true;
  }
  bool k_del_mpls(int label) {
    if (dryrun) return true;
    if (onl_del_mpls_route(nl, label) != 0) {
      err = onl_strerror(nl);
      return false;
    }
    return true;
  }
};

static bool parse_nexthops(const Json* nhs, std::vector<Nexthop>& out) {
  out.clear();
  if (!nhs || nhs->type != Json::ARR) return false;
  for (const Json& j : nhs->arr) {
    Nexthop nh;
    nh.via = j.get_str("via");
    nh.iface = j.get_str("iface");
    nh.weight = (int)j.get_int("weight", 0);
    nh.mpls_action = (int)j.get_int("mpls_action", 0);
    const Json* labels = j.get("labels");
    if (labels && labels->type == Json::ARR)
      for (const Json& l : labels->arr)
        if (l.type == Json::INT) nh.labels.push_back((int)l.i);
    out.push_back(std::move(nh));
  }
  return true;
}

static Json dump_nexthops(const std::vector<Nexthop>& nhs) {
  Json arr = jarr();
  for (const auto& nh : nhs) {
    Json o = jobj();
    o.obj.emplace_back("via", jstr(nh.via));
    o.obj.emplace_back("iface", jstr(nh.iface));
    o.obj.emplace_back("weight", jint(nh.weight));
    o.obj.emplace_back("mpls_action", jint(nh.mpls_action));
    Json labels = jarr();
    for (int l : nh.labels) labels.arr.push_back(jint(l));
    o.obj.emplace_back("labels", std::move(labels));
    arr.arr.push_back(std::move(o));
  }
  return arr;
}

static Json handle(Agent& ag, const std::string& method, const Json& params,
                   std::string& err) {
  long long client = params.get_int("client", 786);  // kFibId default

  if (method == "aliveSince") return jint(ag.alive_since);

  if (method == "addUnicastRoutes" || method == "syncFib") {
    const Json* routes = params.get("routes");
    if (!routes || routes->type != Json::ARR) {
      err = "missing routes";
      return Json();
    }
    std::map<std::string, std::vector<Nexthop>> desired;
    for (const Json& r : routes->arr) {
      std::vector<Nexthop> nhs;
      if (!parse_nexthops(r.get("nexthops"), nhs)) {
        err = "bad nexthops";
        return Json();
      }
      desired[r.get_str("dest")] = std::move(nhs);
    }
    auto& table = ag.unicast[(int)client];
    if (method == "syncFib") {
      // diff: delete stale, then add/replace all desired
      for (auto it = table.begin(); it != table.end();) {
        if (!desired.count(it->first)) {
          if (!ag.k_del_unicast(it->first)) {
            err = ag.err;
            return Json();
          }
          it = table.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (auto& kv : desired) {
      if (!ag.k_add_unicast(kv.first, kv.second)) {
        err = ag.err;
        return Json();
      }
      table[kv.first] = kv.second;
    }
    return Json();
  }

  if (method == "deleteUnicastRoutes") {
    const Json* prefixes = params.get("prefixes");
    if (!prefixes || prefixes->type != Json::ARR) {
      err = "missing prefixes";
      return Json();
    }
    auto& table = ag.unicast[(int)client];
    for (const Json& p : prefixes->arr) {
      if (table.erase(p.s) && !ag.k_del_unicast(p.s)) {
        err = ag.err;
        return Json();
      }
    }
    return Json();
  }

  if (method == "addMplsRoutes" || method == "syncMplsFib") {
    const Json* routes = params.get("routes");
    if (!routes || routes->type != Json::ARR) {
      err = "missing routes";
      return Json();
    }
    std::map<int, std::vector<Nexthop>> desired;
    for (const Json& r : routes->arr) {
      std::vector<Nexthop> nhs;
      if (!parse_nexthops(r.get("nexthops"), nhs)) {
        err = "bad nexthops";
        return Json();
      }
      desired[(int)r.get_int("label")] = std::move(nhs);
    }
    auto& table = ag.mpls[(int)client];
    if (method == "syncMplsFib") {
      for (auto it = table.begin(); it != table.end();) {
        if (!desired.count(it->first)) {
          if (!ag.k_del_mpls(it->first)) {
            err = ag.err;
            return Json();
          }
          it = table.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (auto& kv : desired) {
      if (!ag.k_add_mpls(kv.first, kv.second)) {
        err = ag.err;
        return Json();
      }
      table[kv.first] = kv.second;
    }
    return Json();
  }

  if (method == "deleteMplsRoutes") {
    const Json* labels = params.get("labels");
    if (!labels || labels->type != Json::ARR) {
      err = "missing labels";
      return Json();
    }
    auto& table = ag.mpls[(int)client];
    for (const Json& l : labels->arr) {
      if (table.erase((int)l.i) && !ag.k_del_mpls((int)l.i)) {
        err = ag.err;
        return Json();
      }
    }
    return Json();
  }

  if (method == "getRouteTableByClient") {
    Json arr = jarr();
    for (auto& kv : ag.unicast[(int)client]) {
      Json r = jobj();
      r.obj.emplace_back("dest", jstr(kv.first));
      r.obj.emplace_back("nexthops", dump_nexthops(kv.second));
      arr.arr.push_back(std::move(r));
    }
    return arr;
  }

  if (method == "getMplsRouteTableByClient") {
    Json arr = jarr();
    for (auto& kv : ag.mpls[(int)client]) {
      Json r = jobj();
      r.obj.emplace_back("label", jint(kv.first));
      r.obj.emplace_back("nexthops", dump_nexthops(kv.second));
      arr.arr.push_back(std::move(r));
    }
    return arr;
  }

  if (method == "getNeighbors") {
    Json arr = jarr();
    if (!ag.dryrun) {
      std::vector<onl_neigh> ns(8192);
      int n = onl_get_neighbors(ag.nl, (int)params.get_int("family", 0),
                                ns.data(), (int)ns.size());
      if (n < 0) {
        err = onl_strerror(ag.nl);
        return Json();
      }
      for (int i = 0; i < n; ++i) {
        Json o = jobj();
        o.obj.emplace_back("ifindex", jint(ns[i].ifindex));
        o.obj.emplace_back("dest", jstr(ns[i].dest));
        o.obj.emplace_back("lladdr", jstr(ns[i].lladdr));
        o.obj.emplace_back("family", jint(ns[i].family));
        o.obj.emplace_back("state", jint(ns[i].state));
        o.obj.emplace_back("is_reachable", jint(ns[i].is_reachable));
        arr.arr.push_back(std::move(o));
      }
    }
    return arr;
  }

  err = "unknown method " + method;
  return Json();
}

// ---------------------------------------------------------------------------
// Server loop: poll() over listener + clients, newline-framed requests.
// ---------------------------------------------------------------------------

int main(int argc, char** argv) {
  int port = 60100;
  bool dryrun = false;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "--dryrun")) {
      dryrun = true;
    } else if (!strcmp(argv[i], "--port") && i + 1 < argc) {
      port = atoi(argv[++i]);
    } else {
      fprintf(stderr, "usage: %s [--port N] [--dryrun]\n", argv[0]);
      return 2;
    }
  }
  signal(SIGPIPE, SIG_IGN);

  Agent ag;
  ag.dryrun = dryrun;
  ag.alive_since = (long long)time(nullptr);
  if (!dryrun) {
    ag.nl = onl_open();
    if (!ag.nl) {
      fprintf(stderr, "fatal: cannot open netlink socket\n");
      return 1;
    }
    ag.refresh_links();
  }

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0 || listen(lfd, 16) != 0) {
    perror("bind/listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (sockaddr*)&addr, &alen);
  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::map<int, std::string> bufs;  // fd -> pending input
  for (;;) {
    std::vector<pollfd> pfds;
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& kv : bufs) pfds.push_back({kv.first, POLLIN, 0});
    if (poll(pfds.data(), (nfds_t)pfds.size(), -1) < 0) continue;

    if (pfds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) {
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        bufs[cfd];
      }
    }
    for (size_t i = 1; i < pfds.size(); ++i) {
      if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      int fd = pfds[i].fd;
      char chunk[65536];
      ssize_t n = recv(fd, chunk, sizeof chunk, 0);
      if (n <= 0) {
        close(fd);
        bufs.erase(fd);
        continue;
      }
      std::string& buf = bufs[fd];
      buf.append(chunk, (size_t)n);
      size_t pos;
      while ((pos = buf.find('\n')) != std::string::npos) {
        std::string line = buf.substr(0, pos);
        buf.erase(0, pos + 1);
        if (line.empty()) continue;
        Parser parser(line);
        Json req = parser.parse();
        Json resp = jobj();
        const Json* id = req.get("id");
        resp.obj.emplace_back("id", id ? *id : Json());
        if (!parser.ok || req.type != Json::OBJ) {
          resp.obj.emplace_back("error", jstr("parse error"));
        } else {
          std::string err;
          Json params = jobj();
          const Json* p = req.get("params");
          Json result =
              handle(ag, req.get_str("method"), p ? *p : params, err);
          if (!err.empty())
            resp.obj.emplace_back("error", jstr(err));
          else
            resp.obj.emplace_back("result", std::move(result));
        }
        std::string out;
        dump(resp, out);
        out += '\n';
        ssize_t off = 0;
        while (off < (ssize_t)out.size()) {
          ssize_t w = send(fd, out.data() + off, out.size() - off, 0);
          if (w <= 0) break;
          off += w;
        }
      }
    }
  }
}
