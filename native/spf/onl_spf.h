// Native single-source SPF oracle — the small-graph fallback / reference
// baseline solver of openr-tpu (SURVEY.md §7: the C++ SpfSolver stays as
// oracle next to the TPU batched solver).
//
// Semantics match the reference Dijkstra (openr/decision/LinkState.cpp:806-880):
//   - lazy-deletion binary heap keyed (metric, node id); ties pop in node-id
//     order (the Python graph compiler renumbers ids by in-degree, so this
//     is NOT the reference's nodeName order — harmless: Dijkstra's settled
//     metrics and ECMP unions are tie-break independent; only per-path
//     tie-breaking would need name ordering, and that lives host-side)
//   - overloaded nodes are reachable but offer no transit unless they are
//     the source (LinkState.cpp:829-836)
//   - equal-cost relaxations union first-hop (ECMP) sets
//     (LinkState.cpp:855-871); first hops are recorded as bit positions
//     over the source's out-edge slots
//   - edges with weight >= ONL_SPF_INF (down links, padding) never relax
//
// Input arrays are exactly the CompiledGraph layout produced by
// openr_tpu/ops/graph.py (directed edge list, int32 weights, INF = 1<<29).
//
// C ABI, no dependencies beyond the C++17 standard library.
#pragma once

#include <cstdint>

extern "C" {

// int32-safe infinity; must match openr_tpu.ops.graph.INF
#define ONL_SPF_INF (1 << 29)

// Build a solver over a directed edge list. Copies the inputs; the handle
// owns a CSR-by-source adjacency. `e` may include INF-weight entries.
void* onl_spf_create(int32_t n, int64_t e, const int32_t* src,
                     const int32_t* dst, const int32_t* w,
                     const uint8_t* overloaded);

void onl_spf_destroy(void* h);

// Patch one edge weight (position i in the original edge list) — the link
// flap / metric-change path; ONL_SPF_INF takes a link down.
void onl_spf_set_weight(void* h, int64_t edge, int32_t w);

// Set a node's overload (drain) bit.
void onl_spf_set_overloaded(void* h, int32_t node, uint8_t overloaded);

// Number of out-edge slots of `source` (including down links; their bits
// simply never appear in results). Returns -1 on bad node.
int32_t onl_spf_out_degree(void* h, int32_t source);

// Neighbor node id for each out-edge slot of `source`; fills up to `cap`.
// Returns the out-degree.
int32_t onl_spf_out_neighbors(void* h, int32_t source, int32_t* out,
                              int32_t cap);

// Single-source Dijkstra. dist_out must hold n int32 (ONL_SPF_INF =
// unreachable). If nh_out is non-null it must hold n * nh_words uint64;
// row v receives the first-hop set of v as a bitmask over the source's
// out-edge slots (nh_words >= ceil(out_degree/64); excess slots ignored,
// short rows truncate silently). Returns the number of settled nodes.
int64_t onl_spf_run(void* h, int32_t source, int32_t* dist_out,
                    uint64_t* nh_out, int32_t nh_words);

// Distances-only batch: run Dijkstra from each of `count` sources,
// discarding results (benchmark path — measures pure solver throughput the
// way the reference's decision_benchmark drives SpfSolver). Returns total
// settled nodes across runs.
int64_t onl_spf_run_many(void* h, const int32_t* sources, int32_t count);

}  // extern "C"
