// C++-side unit tests for the native SPF oracle (assert-based; the image
// has no gtest). Exercises the Dijkstra semantics of
// openr/decision/LinkState.cpp:806-880 directly against the C API. Run by
// tests/test_native_sanitizers.py (also as the ASan/UBSan target).

#include "onl_spf.h"

#include <cassert>
#include <cstdio>
#include <vector>

namespace {

struct EdgeList {
  std::vector<int32_t> src, dst, w;
  void add(int32_t a, int32_t b, int32_t wt) {
    src.push_back(a);
    dst.push_back(b);
    w.push_back(wt);
    src.push_back(b);
    dst.push_back(a);
    w.push_back(wt);
  }
};

void test_line_graph() {
  // 0 -1- 1 -2- 2 -3- 3
  EdgeList e;
  e.add(0, 1, 1);
  e.add(1, 2, 2);
  e.add(2, 3, 3);
  void* h = onl_spf_create(4, (int64_t)e.src.size(), e.src.data(),
                           e.dst.data(), e.w.data(), nullptr);
  assert(h);
  int32_t dist[4];
  assert(onl_spf_run(h, 0, dist, nullptr, 0) == 4);
  assert(dist[0] == 0 && dist[1] == 1 && dist[2] == 3 && dist[3] == 6);
  onl_spf_destroy(h);
}

void test_ecmp_union() {
  // diamond: 0->1->3 and 0->2->3, all weight 1: two first hops toward 3
  EdgeList e;
  e.add(0, 1, 1);
  e.add(0, 2, 1);
  e.add(1, 3, 1);
  e.add(2, 3, 1);
  void* h = onl_spf_create(4, (int64_t)e.src.size(), e.src.data(),
                           e.dst.data(), e.w.data(), nullptr);
  int32_t dist[4];
  uint64_t nh[4];
  assert(onl_spf_run(h, 0, dist, nh, 1) == 4);
  assert(dist[3] == 2);
  // node 3's first-hop set has two bits (both out-edge slots of 0)
  int bits = __builtin_popcountll(nh[3]);
  assert(bits == 2);
  assert(__builtin_popcountll(nh[1]) == 1);
  onl_spf_destroy(h);
}

void test_overload_no_transit() {
  // 0 - 1 - 2 with 1 overloaded: 2 unreachable from 0, 1 still reachable
  EdgeList e;
  e.add(0, 1, 1);
  e.add(1, 2, 1);
  std::vector<uint8_t> ov = {0, 1, 0};
  void* h = onl_spf_create(3, (int64_t)e.src.size(), e.src.data(),
                           e.dst.data(), e.w.data(), ov.data());
  int32_t dist[3];
  assert(onl_spf_run(h, 0, dist, nullptr, 0) == 2);
  assert(dist[1] == 1 && dist[2] == ONL_SPF_INF);
  // from the overloaded node itself, its own edges remain usable
  assert(onl_spf_run(h, 1, dist, nullptr, 0) == 3);
  assert(dist[0] == 1 && dist[2] == 1);
  onl_spf_destroy(h);
}

void test_weight_patch() {
  EdgeList e;
  e.add(0, 1, 1);
  e.add(1, 2, 1);
  e.add(0, 2, 5);
  void* h = onl_spf_create(3, (int64_t)e.src.size(), e.src.data(),
                           e.dst.data(), e.w.data(), nullptr);
  int32_t dist[3];
  onl_spf_run(h, 0, dist, nullptr, 0);
  assert(dist[2] == 2);
  // take 1<->2 down (both directions): path flips to the direct edge
  onl_spf_set_weight(h, 2, ONL_SPF_INF);
  onl_spf_set_weight(h, 3, ONL_SPF_INF);
  onl_spf_run(h, 0, dist, nullptr, 0);
  assert(dist[2] == 5);
  onl_spf_destroy(h);
}

void test_bad_inputs() {
  EdgeList e;
  e.add(0, 1, 1);
  assert(onl_spf_create(0, 0, nullptr, nullptr, nullptr, nullptr) ==
         nullptr);
  int32_t bad_dst[] = {7};
  int32_t one[] = {0};
  assert(onl_spf_create(2, 1, one, bad_dst, one, nullptr) == nullptr);
  void* h = onl_spf_create(2, (int64_t)e.src.size(), e.src.data(),
                           e.dst.data(), e.w.data(), nullptr);
  int32_t dist[2];
  assert(onl_spf_run(h, -1, dist, nullptr, 0) == -1);
  assert(onl_spf_run(h, 9, dist, nullptr, 0) == -1);
  onl_spf_destroy(h);
}

}  // namespace

int main() {
  test_line_graph();
  test_ecmp_union();
  test_overload_no_transit();
  test_weight_patch();
  test_bad_inputs();
  std::printf("onl_spf_test OK\n");
  return 0;
}
