// Native SPF oracle implementation — see onl_spf.h for the contract and
// openr/decision/LinkState.cpp:806-880 for the semantics being reproduced.

#include "onl_spf.h"

#include <algorithm>
#include <cstring>
#include <queue>
#include <utility>
#include <vector>

namespace {

struct Graph {
  int32_t n = 0;
  int64_t e = 0;
  // CSR by source node
  std::vector<int64_t> row;    // [n + 1]
  std::vector<int32_t> col;    // [e] neighbor ids, grouped by source
  std::vector<int32_t> wcsr;   // [e] weights (CSR order)
  std::vector<int32_t> slot;   // [e] out-edge slot index within the source
  std::vector<int64_t> csr_of; // [e] original edge position -> CSR position
  std::vector<uint8_t> overloaded;  // [n]

  // scratch reused across runs (single-threaded handle)
  std::vector<int32_t> dist;
  std::vector<uint8_t> settled;
  std::vector<std::vector<uint64_t>> nh;  // per-node first-hop bitmask
};

using HeapEntry = std::pair<int32_t, int32_t>;  // (metric, node)

int64_t run_dijkstra(Graph& g, int32_t source, int32_t* dist_out,
                     uint64_t* nh_out, int32_t nh_words) {
  const int32_t n = g.n;
  if (source < 0 || source >= n) return -1;

  g.dist.assign(n, ONL_SPF_INF);
  g.settled.assign(n, 0);
  const bool want_nh = nh_out != nullptr && nh_words > 0;
  const int32_t deg =
      static_cast<int32_t>(g.row[source + 1] - g.row[source]);
  const int32_t words = (deg + 63) / 64;
  if (want_nh) {
    g.nh.assign(n, {});
  }

  // min-heap with lazy deletion; ties pop in node-id order (see onl_spf.h:
  // settled metrics and ECMP unions are tie-break independent)
  std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                      std::greater<HeapEntry>>
      heap;
  g.dist[source] = 0;
  heap.push({0, source});
  int64_t settled_count = 0;

  while (!heap.empty()) {
    auto [metric, u] = heap.top();
    heap.pop();
    if (g.settled[u] || metric != g.dist[u]) continue;  // stale entry
    g.settled[u] = 1;
    ++settled_count;

    // overloaded nodes are reachable but carry no transit traffic unless
    // they are the source (LinkState.cpp:829-836)
    if (u != source && g.overloaded[u]) continue;

    for (int64_t i = g.row[u]; i < g.row[u + 1]; ++i) {
      const int32_t w = g.wcsr[i];
      if (w >= ONL_SPF_INF) continue;  // down link / padding
      const int32_t v = g.col[i];
      if (g.settled[v]) continue;
      const int32_t nd = metric + w;
      if (nd < g.dist[v]) {
        g.dist[v] = nd;
        heap.push({nd, v});
        if (want_nh) g.nh[v].assign(words, 0);
      } else if (nd > g.dist[v]) {
        continue;
      }
      if (want_nh) {
        // equal-or-better path: union first hops (LinkState.cpp:855-871)
        if (u == source) {
          // directly connected: first hop is this out-edge slot
          const int32_t s = g.slot[i];
          if (s / 64 < words) g.nh[v][s / 64] |= 1ull << (s % 64);
        } else {
          auto& dst_set = g.nh[v];
          const auto& src_set = g.nh[u];
          if (dst_set.size() < src_set.size()) dst_set.resize(words, 0);
          for (size_t k = 0; k < src_set.size(); ++k)
            dst_set[k] |= src_set[k];
        }
      }
    }
  }

  if (dist_out) std::memcpy(dist_out, g.dist.data(), sizeof(int32_t) * n);
  if (want_nh) {
    std::memset(nh_out, 0, sizeof(uint64_t) * static_cast<size_t>(n) *
                               nh_words);
    const int32_t copy_words = std::min(words, nh_words);
    for (int32_t v = 0; v < n; ++v) {
      const auto& set = g.nh[v];
      for (int32_t k = 0; k < copy_words && k < (int32_t)set.size(); ++k)
        nh_out[static_cast<int64_t>(v) * nh_words + k] = set[k];
    }
  }
  return settled_count;
}

}  // namespace

extern "C" {

void* onl_spf_create(int32_t n, int64_t e, const int32_t* src,
                     const int32_t* dst, const int32_t* w,
                     const uint8_t* overloaded) {
  if (n <= 0 || e < 0) return nullptr;
  auto* g = new Graph();
  g->n = n;
  g->e = e;
  g->row.assign(n + 1, 0);
  for (int64_t i = 0; i < e; ++i) {
    if (src[i] < 0 || src[i] >= n || dst[i] < 0 || dst[i] >= n) {
      delete g;
      return nullptr;
    }
    ++g->row[src[i] + 1];
  }
  for (int32_t v = 0; v < n; ++v) g->row[v + 1] += g->row[v];
  g->col.resize(e);
  g->wcsr.resize(e);
  g->slot.resize(e);
  g->csr_of.resize(e);
  std::vector<int64_t> fill(g->row.begin(), g->row.end() - 1);
  for (int64_t i = 0; i < e; ++i) {
    const int64_t p = fill[src[i]]++;
    g->col[p] = dst[i];
    g->wcsr[p] = w[i];
    g->slot[p] = static_cast<int32_t>(p - g->row[src[i]]);
    g->csr_of[i] = p;
  }
  g->overloaded.assign(n, 0);
  if (overloaded) std::memcpy(g->overloaded.data(), overloaded, n);
  return g;
}

void onl_spf_destroy(void* h) { delete static_cast<Graph*>(h); }

void onl_spf_set_weight(void* h, int64_t edge, int32_t w) {
  auto* g = static_cast<Graph*>(h);
  if (edge < 0 || edge >= g->e) return;
  g->wcsr[g->csr_of[edge]] = w;
}

void onl_spf_set_overloaded(void* h, int32_t node, uint8_t overloaded) {
  auto* g = static_cast<Graph*>(h);
  if (node < 0 || node >= g->n) return;
  g->overloaded[node] = overloaded;
}

int32_t onl_spf_out_degree(void* h, int32_t source) {
  auto* g = static_cast<Graph*>(h);
  if (source < 0 || source >= g->n) return -1;
  return static_cast<int32_t>(g->row[source + 1] - g->row[source]);
}

int32_t onl_spf_out_neighbors(void* h, int32_t source, int32_t* out,
                              int32_t cap) {
  auto* g = static_cast<Graph*>(h);
  if (source < 0 || source >= g->n) return -1;
  const int32_t deg =
      static_cast<int32_t>(g->row[source + 1] - g->row[source]);
  for (int32_t k = 0; k < deg && k < cap; ++k)
    out[k] = g->col[g->row[source] + k];
  return deg;
}

int64_t onl_spf_run(void* h, int32_t source, int32_t* dist_out,
                    uint64_t* nh_out, int32_t nh_words) {
  return run_dijkstra(*static_cast<Graph*>(h), source, dist_out, nh_out,
                      nh_words);
}

int64_t onl_spf_run_many(void* h, const int32_t* sources, int32_t count) {
  auto* g = static_cast<Graph*>(h);
  int64_t total = 0;
  for (int32_t i = 0; i < count; ++i) {
    const int64_t r = run_dijkstra(*g, sources[i], nullptr, nullptr, 0);
    if (r < 0) return r;
    total += r;
  }
  return total;
}

}  // extern "C"
