/*
 * openr-tpu native netlink library — implementation.
 *
 * Design (vs. reference openr/nl/):
 *   - The reference pipelines async requests on a folly EventBase with
 *     per-request ack futures (NetlinkProtocolSocket.h:92-255). Here the
 *     control plane lives in Python asyncio; the native layer instead
 *     offers bounded synchronous transactions (send + drain until ack /
 *     NLMSG_DONE with SO_RCVTIMEO) that Python runs on an executor. Event
 *     delivery stays async via a separate multicast-subscribed socket whose
 *     fd plugs into the Python event loop.
 *   - Message building mirrors NetlinkMessage.h:143 (bounded buffer,
 *     nlmsghdr + ancillary struct + rtattr appends incl. nested).
 *   - Route semantics mirror NetlinkRoute.cpp: RTA_MULTIPATH ECMP,
 *     AF_MPLS label routes (RTA_NEWDST), MPLS push via RTA_ENCAP.
 */

#include "onl_netlink.h"

#include <arpa/inet.h>
#include <errno.h>
#include <linux/lwtunnel.h>
#include <linux/mpls.h>
#include <linux/mpls_iptunnel.h>
#include <linux/neighbour.h>
#include <linux/netlink.h>
#include <linux/rtnetlink.h>
#include <net/if.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cstdio>
#include <string>
#include <vector>

#ifndef NDA_RTA /* glibc's rtnetlink.h stops at TA_RTA */
#define NDA_RTA(r) \
  ((struct rtattr*)(((char*)(r)) + NLMSG_ALIGN(sizeof(struct ndmsg))))
#endif

namespace {

constexpr int kRcvTimeoutSec = 2;
constexpr size_t kMsgBufSize = 32768;

/* ---------------- message builder ---------------- */

class MsgBuilder {
 public:
  MsgBuilder(uint16_t type, uint16_t flags, uint32_t seq) {
    buf_.resize(NLMSG_SPACE(0), 0);
    auto* h = hdr();
    h->nlmsg_len = NLMSG_LENGTH(0);
    h->nlmsg_type = type;
    h->nlmsg_flags = flags;
    h->nlmsg_seq = seq;
    h->nlmsg_pid = 0;
  }

  nlmsghdr* hdr() { return reinterpret_cast<nlmsghdr*>(buf_.data()); }

  /* append the fixed ancillary struct (rtmsg / ifinfomsg / ifaddrmsg) */
  template <typename T>
  T* add_payload() {
    size_t off = grow(NLMSG_ALIGN(sizeof(T)));
    return reinterpret_cast<T*>(buf_.data() + off);
  }

  void add_attr(uint16_t type, const void* data, size_t len) {
    size_t off = grow(RTA_SPACE(len));
    auto* rta = reinterpret_cast<rtattr*>(buf_.data() + off);
    rta->rta_type = type;
    rta->rta_len = RTA_LENGTH(len);
    if (len) memcpy(RTA_DATA(rta), data, len);
  }

  template <typename T>
  void add_attr(uint16_t type, const T& v) {
    add_attr(type, &v, sizeof(T));
  }

  /* nested attribute: returns offset to patch the length at close */
  size_t nest_begin(uint16_t type) {
    size_t off = grow(RTA_SPACE(0));
    auto* rta = reinterpret_cast<rtattr*>(buf_.data() + off);
    rta->rta_type = type;
    rta->rta_len = RTA_LENGTH(0);
    return off;
  }

  void nest_end(size_t off) {
    auto* rta = reinterpret_cast<rtattr*>(buf_.data() + off);
    rta->rta_len = buf_.size() - off;
  }

  /* rtnexthop inside RTA_MULTIPATH */
  size_t rtnh_begin() {
    size_t off = grow(RTNH_SPACE(0));
    auto* rtnh = reinterpret_cast<rtnexthop*>(buf_.data() + off);
    rtnh->rtnh_len = RTNH_LENGTH(0);
    rtnh->rtnh_flags = 0;
    rtnh->rtnh_hops = 0;
    rtnh->rtnh_ifindex = 0;
    return off;
  }

  void rtnh_end(size_t off) {
    auto* rtnh = reinterpret_cast<rtnexthop*>(buf_.data() + off);
    rtnh->rtnh_len = buf_.size() - off;
  }

  rtnexthop* rtnh_at(size_t off) {
    return reinterpret_cast<rtnexthop*>(buf_.data() + off);
  }

  const void* data() { return buf_.data(); }
  size_t size() const { return buf_.size(); }

 private:
  size_t grow(size_t bytes) {
    size_t off = buf_.size();
    buf_.resize(off + bytes, 0);
    hdr()->nlmsg_len = buf_.size();
    return off;
  }

  std::vector<char> buf_;
};

/* ---------------- address helpers ---------------- */

struct IpAddr {
  int family = 0;
  uint8_t bytes[16] = {0};
  int len = 0; /* 4 or 16 */
};

bool parse_addr(const char* s, IpAddr* out) {
  if (inet_pton(AF_INET, s, out->bytes) == 1) {
    out->family = AF_INET;
    out->len = 4;
    return true;
  }
  if (inet_pton(AF_INET6, s, out->bytes) == 1) {
    out->family = AF_INET6;
    out->len = 16;
    return true;
  }
  return false;
}

bool parse_prefix(const char* s, IpAddr* addr, int* prefixlen) {
  std::string str(s);
  auto slash = str.find('/');
  if (slash == std::string::npos) return false;
  std::string ip = str.substr(0, slash);
  *prefixlen = atoi(str.c_str() + slash + 1);
  return parse_addr(ip.c_str(), addr);
}

void format_addr(int family, const void* data, char* out, size_t outlen) {
  inet_ntop(family, data, out, outlen);
}

void format_mac(const uint8_t* mac, size_t len, char* out, size_t outlen) {
  if (len == 6) {
    snprintf(out, outlen, "%02x:%02x:%02x:%02x:%02x:%02x", mac[0], mac[1],
             mac[2], mac[3], mac[4], mac[5]);
  } else {
    out[0] = '\0';
  }
}

bool parse_mac(const char* s, uint8_t* out) {
  unsigned v[6];
  if (sscanf(s, "%x:%x:%x:%x:%x:%x", &v[0], &v[1], &v[2], &v[3], &v[4],
             &v[5]) != 6) {
    return false;
  }
  for (int i = 0; i < 6; i++) out[i] = static_cast<uint8_t>(v[i]);
  return true;
}

/* reference NetlinkTypes.cpp:15-23 kNeighborReachableStates */
bool neighbor_reachable(int state) {
  switch (state) {
    case NUD_REACHABLE:
    case NUD_STALE:
    case NUD_DELAY:
    case NUD_PERMANENT:
    case NUD_PROBE:
    case NUD_NOARP:
      return true;
    default:
      return false;
  }
}

/* parse one RTM_NEWNEIGH/RTM_DELNEIGH payload; false = not an IP neighbor
 * (e.g. AF_BRIDGE fdb entry) */
bool parse_neigh_msg(nlmsghdr* nh, onl_neigh* out) {
  auto* m = reinterpret_cast<ndmsg*>(NLMSG_DATA(nh));
  if (m->ndm_family != AF_INET && m->ndm_family != AF_INET6) return false;
  memset(out, 0, sizeof(*out));
  out->ifindex = m->ndm_ifindex;
  out->family = m->ndm_family;
  out->state = m->ndm_state;
  out->is_reachable =
      (nh->nlmsg_type == RTM_NEWNEIGH && neighbor_reachable(m->ndm_state))
          ? 1
          : 0;
  int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
  bool have_dst = false;
  for (auto* rta = reinterpret_cast<rtattr*>(NDA_RTA(m)); RTA_OK(rta, len);
       rta = RTA_NEXT(rta, len)) {
    if (rta->rta_type == NDA_DST) {
      format_addr(m->ndm_family, RTA_DATA(rta), out->dest,
                  sizeof(out->dest));
      have_dst = true;
    } else if (rta->rta_type == NDA_LLADDR) {
      format_mac(static_cast<uint8_t*>(RTA_DATA(rta)), RTA_PAYLOAD(rta),
                 out->lladdr, sizeof(out->lladdr));
    }
  }
  return have_dst;
}

/* mpls label stack entry encoding (RFC 3032): label<<12 | tc<<9 | S<<8 */
uint32_t mpls_lse(uint32_t label, bool bottom) {
  uint32_t v = (label << MPLS_LS_LABEL_SHIFT);
  if (bottom) v |= (1u << MPLS_LS_S_SHIFT);
  return htonl(v);
}

/* ---------------- the handle ---------------- */

struct Handle {
  int fd = -1;       /* transactional socket */
  int event_fd = -1; /* multicast-subscribed event socket */
  uint32_t seq = 1;
  std::string error;
  char evbuf[kMsgBufSize];

  bool fail(const std::string& msg) {
    error = msg + ": " + strerror(errno);
    return false;
  }
};

bool open_socket(int* out_fd, uint32_t groups) {
  int fd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_ROUTE);
  if (fd < 0) return false;
  struct timeval tv = {kRcvTimeoutSec, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int bufsz = 1 << 20;
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
  sockaddr_nl sa = {};
  sa.nl_family = AF_NETLINK;
  sa.nl_groups = groups;
  if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
    close(fd);
    return false;
  }
  *out_fd = fd;
  return true;
}

/* send one request; invoke cb on every data message; stop on ack/done.
 * Returns true on success (ack with error==0, or DONE for dumps). */
template <typename Cb>
bool transact(Handle* h, MsgBuilder& msg, Cb&& cb) {
  msg.hdr()->nlmsg_seq = ++h->seq;
  if (send(h->fd, msg.data(), msg.size(), 0) < 0) {
    return h->fail("netlink send");
  }
  char buf[kMsgBufSize];
  while (true) {
    ssize_t n = recv(h->fd, buf, sizeof(buf), 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return h->fail("netlink recv");
    }
    for (auto* nh = reinterpret_cast<nlmsghdr*>(buf); NLMSG_OK(nh, n);
         nh = NLMSG_NEXT(nh, n)) {
      if (nh->nlmsg_seq != h->seq) continue; /* stale */
      if (nh->nlmsg_type == NLMSG_DONE) return true;
      if (nh->nlmsg_type == NLMSG_ERROR) {
        auto* err = reinterpret_cast<nlmsgerr*>(NLMSG_DATA(nh));
        if (err->error == 0) return true; /* ack */
        errno = -err->error;
        return h->fail("netlink error");
      }
      cb(nh);
      if (!(nh->nlmsg_flags & NLM_F_MULTI)) return true;
    }
  }
}

void add_nexthop_attrs(MsgBuilder& msg, const onl_nexthop& nh, int family,
                       bool in_multipath, size_t rtnh_off) {
  IpAddr via;
  bool has_via = nh.via[0] != '\0' && parse_addr(nh.via, &via);

  if (nh.mpls_action == ONL_MPLS_SWAP || nh.mpls_action == ONL_MPLS_PHP) {
    /* label route nexthop: RTA_NEWDST carries the out-label for SWAP */
    if (nh.mpls_action == ONL_MPLS_SWAP && nh.num_labels > 0) {
      uint32_t lse = mpls_lse(nh.labels[0], true);
      msg.add_attr(RTA_NEWDST, &lse, sizeof(lse));
    }
    if (has_via) {
      /* RTA_VIA: family + raw address */
      char viabuf[2 + 16];
      uint16_t fam = via.family;
      memcpy(viabuf, &fam, 2);
      memcpy(viabuf + 2, via.bytes, via.len);
      msg.add_attr(RTA_VIA, viabuf, 2 + via.len);
    }
  } else {
    if (nh.mpls_action == ONL_MPLS_PUSH && nh.num_labels > 0) {
      /* IP->MPLS: lwtunnel encap */
      size_t encap = msg.nest_begin(RTA_ENCAP);
      std::vector<uint32_t> stack;
      for (int i = 0; i < nh.num_labels; i++) {
        stack.push_back(mpls_lse(nh.labels[i], i == nh.num_labels - 1));
      }
      msg.add_attr(MPLS_IPTUNNEL_DST, stack.data(),
                   stack.size() * sizeof(uint32_t));
      msg.nest_end(encap);
      uint16_t etype = LWTUNNEL_ENCAP_MPLS;
      msg.add_attr(RTA_ENCAP_TYPE, etype);
    }
    if (has_via) {
      if (via.family == family) {
        msg.add_attr(RTA_GATEWAY, via.bytes, via.len);
      } else {
        /* v4-over-v6 nexthop etc: RTA_VIA */
        char viabuf[2 + 16];
        uint16_t fam = via.family;
        memcpy(viabuf, &fam, 2);
        memcpy(viabuf + 2, via.bytes, via.len);
        msg.add_attr(RTA_VIA, viabuf, 2 + via.len);
      }
    }
  }
  if (!in_multipath && nh.ifindex > 0) {
    uint32_t oif = nh.ifindex;
    msg.add_attr(RTA_OIF, oif);
  }
  if (in_multipath) {
    auto* rtnh = msg.rtnh_at(rtnh_off);
    rtnh->rtnh_ifindex = nh.ifindex;
    rtnh->rtnh_hops = nh.weight > 0 ? nh.weight - 1 : 0;
  }
}

} /* namespace */

/* ================= C ABI ================= */

extern "C" {

void* onl_open(void) {
  auto* h = new Handle();
  if (!open_socket(&h->fd, 0)) {
    delete h;
    return nullptr;
  }
  return h;
}

void onl_close(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  if (!h) return;
  if (h->fd >= 0) close(h->fd);
  if (h->event_fd >= 0) close(h->event_fd);
  delete h;
}

const char* onl_strerror(void* hv) {
  return static_cast<Handle*>(hv)->error.c_str();
}

int onl_get_links(void* hv, onl_link* out, int max) {
  auto* h = static_cast<Handle*>(hv);
  MsgBuilder msg(RTM_GETLINK, NLM_F_REQUEST | NLM_F_DUMP, 0);
  auto* ifi = msg.add_payload<ifinfomsg>();
  ifi->ifi_family = AF_UNSPEC;
  int count = 0;
  bool ok = transact(h, msg, [&](nlmsghdr* nh) {
    if (nh->nlmsg_type != RTM_NEWLINK || count >= max) return;
    auto* m = reinterpret_cast<ifinfomsg*>(NLMSG_DATA(nh));
    onl_link& l = out[count];
    memset(&l, 0, sizeof(l));
    l.ifindex = m->ifi_index;
    l.up = (m->ifi_flags & IFF_UP) ? 1 : 0;
    int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
    for (auto* rta = IFLA_RTA(m); RTA_OK(rta, len);
         rta = RTA_NEXT(rta, len)) {
      if (rta->rta_type == IFLA_IFNAME) {
        snprintf(l.name, sizeof(l.name), "%s",
                 static_cast<char*>(RTA_DATA(rta)));
      }
    }
    count++;
  });
  return ok ? count : -1;
}

int onl_get_addrs(void* hv, onl_addr* out, int max) {
  auto* h = static_cast<Handle*>(hv);
  MsgBuilder msg(RTM_GETADDR, NLM_F_REQUEST | NLM_F_DUMP, 0);
  auto* ifa = msg.add_payload<ifaddrmsg>();
  ifa->ifa_family = AF_UNSPEC;
  int count = 0;
  bool ok = transact(h, msg, [&](nlmsghdr* nh) {
    if (nh->nlmsg_type != RTM_NEWADDR || count >= max) return;
    auto* m = reinterpret_cast<ifaddrmsg*>(NLMSG_DATA(nh));
    onl_addr& a = out[count];
    memset(&a, 0, sizeof(a));
    a.ifindex = m->ifa_index;
    a.prefixlen = m->ifa_prefixlen;
    a.family = m->ifa_family;
    int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
    bool have = false;
    for (auto* rta = IFA_RTA(m); RTA_OK(rta, len);
         rta = RTA_NEXT(rta, len)) {
      if (rta->rta_type == IFA_ADDRESS || rta->rta_type == IFA_LOCAL) {
        format_addr(m->ifa_family, RTA_DATA(rta), a.addr, sizeof(a.addr));
        have = true;
        if (rta->rta_type == IFA_LOCAL) break; /* prefer local */
      }
    }
    if (have) count++;
  });
  return ok ? count : -1;
}

static int addr_op(Handle* h, uint16_t op, uint16_t flags, int ifindex,
                   const char* addr, int prefixlen) {
  IpAddr ip;
  if (!parse_addr(addr, &ip)) {
    h->error = "bad address";
    return -1;
  }
  MsgBuilder msg(op, NLM_F_REQUEST | NLM_F_ACK | flags, 0);
  auto* ifa = msg.add_payload<ifaddrmsg>();
  ifa->ifa_family = ip.family;
  ifa->ifa_prefixlen = prefixlen;
  ifa->ifa_index = ifindex;
  msg.add_attr(IFA_LOCAL, ip.bytes, ip.len);
  msg.add_attr(IFA_ADDRESS, ip.bytes, ip.len);
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_add_addr(void* hv, int ifindex, const char* addr, int prefixlen) {
  return addr_op(static_cast<Handle*>(hv), RTM_NEWADDR,
                 NLM_F_CREATE | NLM_F_REPLACE, ifindex, addr, prefixlen);
}

int onl_del_addr(void* hv, int ifindex, const char* addr, int prefixlen) {
  return addr_op(static_cast<Handle*>(hv), RTM_DELADDR, 0, ifindex, addr,
                 prefixlen);
}

int onl_get_neighbors(void* hv, int family, onl_neigh* out, int max) {
  auto* h = static_cast<Handle*>(hv);
  MsgBuilder msg(RTM_GETNEIGH, NLM_F_REQUEST | NLM_F_DUMP, 0);
  auto* ndm = msg.add_payload<ndmsg>();
  ndm->ndm_family = family == 0 ? AF_UNSPEC : family;
  int count = 0;
  bool ok = transact(h, msg, [&](nlmsghdr* nh) {
    if (nh->nlmsg_type != RTM_NEWNEIGH || count >= max) return;
    onl_neigh n;
    if (!parse_neigh_msg(nh, &n)) return;
    if (family != 0 && n.family != family) return;
    out[count++] = n;
  });
  return ok ? count : -1;
}

int onl_add_neighbor(void* hv, int ifindex, const char* dest,
                     const char* lladdr) {
  auto* h = static_cast<Handle*>(hv);
  IpAddr ip;
  if (!parse_addr(dest, &ip)) {
    h->error = "bad neighbor address";
    return -1;
  }
  uint8_t mac[6];
  if (!parse_mac(lladdr, mac)) {
    h->error = "bad link address";
    return -1;
  }
  MsgBuilder msg(RTM_NEWNEIGH,
                 NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE, 0);
  auto* ndm = msg.add_payload<ndmsg>();
  ndm->ndm_family = ip.family;
  ndm->ndm_ifindex = ifindex;
  ndm->ndm_state = NUD_PERMANENT;
  msg.add_attr(NDA_DST, ip.bytes, ip.len);
  msg.add_attr(NDA_LLADDR, mac, sizeof(mac));
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_del_neighbor(void* hv, int ifindex, const char* dest) {
  auto* h = static_cast<Handle*>(hv);
  IpAddr ip;
  if (!parse_addr(dest, &ip)) {
    h->error = "bad neighbor address";
    return -1;
  }
  MsgBuilder msg(RTM_DELNEIGH, NLM_F_REQUEST | NLM_F_ACK, 0);
  auto* ndm = msg.add_payload<ndmsg>();
  ndm->ndm_family = ip.family;
  ndm->ndm_ifindex = ifindex;
  msg.add_attr(NDA_DST, ip.bytes, ip.len);
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_add_unicast_route(void* hv, const char* dest, int proto, int table,
                          const onl_nexthop* nhs, int n_nhs, int replace) {
  auto* h = static_cast<Handle*>(hv);
  IpAddr dst;
  int prefixlen = 0;
  if (!parse_prefix(dest, &dst, &prefixlen)) {
    h->error = "bad prefix";
    return -1;
  }
  uint16_t flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE;
  if (replace) flags |= NLM_F_REPLACE;
  MsgBuilder msg(RTM_NEWROUTE, flags, 0);
  auto* rtm = msg.add_payload<rtmsg>();
  rtm->rtm_family = dst.family;
  rtm->rtm_dst_len = prefixlen;
  rtm->rtm_table = RT_TABLE_UNSPEC;
  rtm->rtm_protocol = proto;
  rtm->rtm_scope = RT_SCOPE_UNIVERSE;
  rtm->rtm_type = RTN_UNICAST;
  uint32_t tbl = table;
  msg.add_attr(RTA_TABLE, tbl);
  msg.add_attr(RTA_DST, dst.bytes, dst.len);

  if (n_nhs == 1) {
    add_nexthop_attrs(msg, nhs[0], dst.family, false, 0);
  } else {
    size_t mp = msg.nest_begin(RTA_MULTIPATH);
    for (int i = 0; i < n_nhs; i++) {
      size_t off = msg.rtnh_begin();
      add_nexthop_attrs(msg, nhs[i], dst.family, true, off);
      msg.rtnh_end(off);
    }
    msg.nest_end(mp);
  }
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_del_unicast_route(void* hv, const char* dest, int proto, int table) {
  auto* h = static_cast<Handle*>(hv);
  IpAddr dst;
  int prefixlen = 0;
  if (!parse_prefix(dest, &dst, &prefixlen)) {
    h->error = "bad prefix";
    return -1;
  }
  MsgBuilder msg(RTM_DELROUTE, NLM_F_REQUEST | NLM_F_ACK, 0);
  auto* rtm = msg.add_payload<rtmsg>();
  rtm->rtm_family = dst.family;
  rtm->rtm_dst_len = prefixlen;
  rtm->rtm_table = RT_TABLE_UNSPEC;
  rtm->rtm_protocol = proto;
  uint32_t tbl = table;
  msg.add_attr(RTA_TABLE, tbl);
  msg.add_attr(RTA_DST, dst.bytes, dst.len);
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_add_mpls_route(void* hv, int label, const onl_nexthop* nhs, int n_nhs,
                       int replace) {
  auto* h = static_cast<Handle*>(hv);
  uint16_t flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE;
  if (replace) flags |= NLM_F_REPLACE;
  MsgBuilder msg(RTM_NEWROUTE, flags, 0);
  auto* rtm = msg.add_payload<rtmsg>();
  rtm->rtm_family = AF_MPLS;
  rtm->rtm_dst_len = 20; /* label length in bits */
  rtm->rtm_table = RT_TABLE_MAIN;
  rtm->rtm_protocol = RTPROT_STATIC;
  rtm->rtm_scope = RT_SCOPE_UNIVERSE;
  rtm->rtm_type = RTN_UNICAST;
  uint32_t in_lse = mpls_lse(label, true);
  msg.add_attr(RTA_DST, &in_lse, sizeof(in_lse));
  if (n_nhs == 1) {
    add_nexthop_attrs(msg, nhs[0], AF_MPLS, false, 0);
  } else {
    size_t mp = msg.nest_begin(RTA_MULTIPATH);
    for (int i = 0; i < n_nhs; i++) {
      size_t off = msg.rtnh_begin();
      add_nexthop_attrs(msg, nhs[i], AF_MPLS, true, off);
      msg.rtnh_end(off);
    }
    msg.nest_end(mp);
  }
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

int onl_del_mpls_route(void* hv, int label) {
  auto* h = static_cast<Handle*>(hv);
  MsgBuilder msg(RTM_DELROUTE, NLM_F_REQUEST | NLM_F_ACK, 0);
  auto* rtm = msg.add_payload<rtmsg>();
  rtm->rtm_family = AF_MPLS;
  rtm->rtm_dst_len = 20;
  rtm->rtm_table = RT_TABLE_MAIN;
  uint32_t in_lse = mpls_lse(label, true);
  msg.add_attr(RTA_DST, &in_lse, sizeof(in_lse));
  return transact(h, msg, [](nlmsghdr*) {}) ? 0 : -1;
}

namespace {

/* append "via,ifindex,weight" (+ ",swap:l" / ",push:l1/l2") */
void format_nexthop(std::string* line, const char* via, int ifindex,
                    int weight, const uint32_t* labels, int n_labels,
                    int action) {
  char tmp[160];
  snprintf(tmp, sizeof(tmp), "%s,%d,%d", via, ifindex, weight);
  *line += tmp;
  if (action == ONL_MPLS_SWAP || action == ONL_MPLS_PUSH) {
    *line += action == ONL_MPLS_SWAP ? ",swap:" : ",push:";
    for (int i = 0; i < n_labels; i++) {
      if (i) *line += '/';
      snprintf(tmp, sizeof(tmp), "%u", labels[i]);
      *line += tmp;
    }
  } else if (action == ONL_MPLS_PHP) {
    *line += ",php";
  }
}

/* parse one nexthop attr set (top-level or inside rtnexthop) */
void parse_nh_attrs(int family, rtattr* rta, int len, int ifindex_hint,
                    int weight, std::string* line) {
  char via[64] = "";
  int ifindex = ifindex_hint;
  uint32_t labels[8];
  int n_labels = 0;
  int action = ONL_MPLS_NONE;
  if (family == AF_MPLS) action = ONL_MPLS_PHP; /* no NEWDST => pop */
  for (; RTA_OK(rta, len); rta = RTA_NEXT(rta, len)) {
    switch (rta->rta_type) {
      case RTA_GATEWAY:
        format_addr(family, RTA_DATA(rta), via, sizeof(via));
        break;
      case RTA_VIA: {
        auto* p = static_cast<char*>(RTA_DATA(rta));
        uint16_t fam;
        memcpy(&fam, p, 2);
        format_addr(fam, p + 2, via, sizeof(via));
        break;
      }
      case RTA_OIF:
        ifindex = *static_cast<int32_t*>(RTA_DATA(rta));
        break;
      case RTA_NEWDST: {
        auto* lse = static_cast<uint32_t*>(RTA_DATA(rta));
        int cnt = RTA_PAYLOAD(rta) / 4;
        action = ONL_MPLS_SWAP;
        for (int i = 0; i < cnt && i < 8; i++) {
          labels[n_labels++] =
              (ntohl(lse[i]) & MPLS_LS_LABEL_MASK) >> MPLS_LS_LABEL_SHIFT;
        }
        break;
      }
      case RTA_ENCAP: {
        auto* erta = static_cast<rtattr*>(RTA_DATA(rta));
        int elen = RTA_PAYLOAD(rta);
        for (; RTA_OK(erta, elen); erta = RTA_NEXT(erta, elen)) {
          if (erta->rta_type == MPLS_IPTUNNEL_DST) {
            auto* lse = static_cast<uint32_t*>(RTA_DATA(erta));
            int cnt = RTA_PAYLOAD(erta) / 4;
            action = ONL_MPLS_PUSH;
            for (int i = 0; i < cnt && i < 8; i++) {
              labels[n_labels++] =
                  (ntohl(lse[i]) & MPLS_LS_LABEL_MASK) >> MPLS_LS_LABEL_SHIFT;
            }
          }
        }
        break;
      }
    }
  }
  format_nexthop(line, via, ifindex, weight, labels, n_labels, action);
}

} /* namespace */

int onl_get_routes(void* hv, int family, int proto, int table, char* buf,
                   int buflen) {
  auto* h = static_cast<Handle*>(hv);
  MsgBuilder msg(RTM_GETROUTE, NLM_F_REQUEST | NLM_F_DUMP, 0);
  auto* rtm = msg.add_payload<rtmsg>();
  rtm->rtm_family = family;
  std::string out;
  int count = 0;
  bool ok = transact(h, msg, [&](nlmsghdr* nh) {
    if (nh->nlmsg_type != RTM_NEWROUTE) return;
    auto* m = reinterpret_cast<rtmsg*>(NLMSG_DATA(nh));
    if (family != 0 && m->rtm_family != family) return;
    if (family == 0 &&
        (m->rtm_family != AF_INET && m->rtm_family != AF_INET6)) {
      return;
    }
    if (proto != 0 && m->rtm_protocol != proto) return;
    int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
    uint32_t rt_table = m->rtm_table;
    /* first pass: find RTA_TABLE + RTA_DST */
    char dst[80] = "";
    rtattr* multipath = nullptr;
    for (auto* rta = RTM_RTA(m); RTA_OK(rta, len);
         rta = RTA_NEXT(rta, len)) {
      if (rta->rta_type == RTA_TABLE) {
        rt_table = *static_cast<uint32_t*>(RTA_DATA(rta));
      } else if (rta->rta_type == RTA_DST) {
        if (m->rtm_family == AF_MPLS) {
          auto* lse = static_cast<uint32_t*>(RTA_DATA(rta));
          snprintf(dst, sizeof(dst), "mpls:%u",
                   (ntohl(*lse) & MPLS_LS_LABEL_MASK) >> MPLS_LS_LABEL_SHIFT);
        } else {
          char a[64];
          format_addr(m->rtm_family, RTA_DATA(rta), a, sizeof(a));
          snprintf(dst, sizeof(dst), "%s/%d", a, m->rtm_dst_len);
        }
      } else if (rta->rta_type == RTA_MULTIPATH) {
        multipath = rta;
      }
    }
    if (table != 0 && rt_table != static_cast<uint32_t>(table)) return;
    if (dst[0] == '\0') {
      if (m->rtm_family == AF_MPLS) return;
      snprintf(dst, sizeof(dst), "%s/0",
               m->rtm_family == AF_INET ? "0.0.0.0" : "::");
    }
    std::string line(dst);
    line += '|';
    if (multipath != nullptr) {
      auto* rtnh = static_cast<rtnexthop*>(RTA_DATA(multipath));
      int mplen = RTA_PAYLOAD(multipath);
      bool first = true;
      while (RTNH_OK(rtnh, mplen)) {
        if (!first) line += ';';
        first = false;
        parse_nh_attrs(m->rtm_family, RTNH_DATA(rtnh),
                       rtnh->rtnh_len - RTNH_LENGTH(0), rtnh->rtnh_ifindex,
                       rtnh->rtnh_hops + 1, &line);
        mplen -= RTNH_ALIGN(rtnh->rtnh_len);
        rtnh = RTNH_NEXT(rtnh);
      }
    } else {
      int len2 = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
      parse_nh_attrs(m->rtm_family, RTM_RTA(m), len2, 0, 1, &line);
    }
    line += '\n';
    out += line;
    count++;
  });
  if (!ok) return -1;
  if (static_cast<int>(out.size()) >= buflen) {
    h->error = "route dump buffer too small";
    return -1;
  }
  memcpy(buf, out.c_str(), out.size() + 1);
  return count;
}

int onl_subscribe(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  if (h->event_fd >= 0) return 0;
  uint32_t groups =
      RTMGRP_LINK | RTMGRP_IPV4_IFADDR | RTMGRP_IPV6_IFADDR | RTMGRP_NEIGH;
  if (!open_socket(&h->event_fd, groups)) {
    h->fail("event socket");
    return -1;
  }
  return 0;
}

int onl_event_fd(void* hv) {
  return static_cast<Handle*>(hv)->event_fd;
}

int onl_next_event(void* hv, onl_event* out) {
  auto* h = static_cast<Handle*>(hv);
  if (h->event_fd < 0) {
    h->error = "not subscribed";
    return -1;
  }
  ssize_t n = recv(h->event_fd, h->evbuf, sizeof(h->evbuf), MSG_DONTWAIT);
  if (n < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK) return 0;
    h->fail("event recv");
    return -1;
  }
  for (auto* nh = reinterpret_cast<nlmsghdr*>(h->evbuf); NLMSG_OK(nh, n);
       nh = NLMSG_NEXT(nh, n)) {
    memset(out, 0, sizeof(*out));
    if (nh->nlmsg_type == RTM_NEWLINK || nh->nlmsg_type == RTM_DELLINK) {
      auto* m = reinterpret_cast<ifinfomsg*>(NLMSG_DATA(nh));
      out->kind = 1;
      out->ifindex = m->ifi_index;
      out->up = (nh->nlmsg_type == RTM_NEWLINK && (m->ifi_flags & IFF_UP))
                    ? 1
                    : 0;
      int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
      for (auto* rta = IFLA_RTA(m); RTA_OK(rta, len);
           rta = RTA_NEXT(rta, len)) {
        if (rta->rta_type == IFLA_IFNAME) {
          snprintf(out->name, sizeof(out->name), "%s",
                   static_cast<char*>(RTA_DATA(rta)));
        }
      }
      return 1;
    }
    if (nh->nlmsg_type == RTM_NEWNEIGH || nh->nlmsg_type == RTM_DELNEIGH) {
      onl_neigh n;
      if (!parse_neigh_msg(nh, &n)) continue; /* bridge fdb etc */
      out->kind = 4;
      out->ifindex = n.ifindex;
      out->up = n.is_reachable;
      out->state = n.state;
      snprintf(out->addr, sizeof(out->addr), "%s", n.dest);
      snprintf(out->lladdr, sizeof(out->lladdr), "%s", n.lladdr);
      return 1;
    }
    if (nh->nlmsg_type == RTM_NEWADDR || nh->nlmsg_type == RTM_DELADDR) {
      auto* m = reinterpret_cast<ifaddrmsg*>(NLMSG_DATA(nh));
      out->kind = 2;
      out->ifindex = m->ifa_index;
      out->up = nh->nlmsg_type == RTM_NEWADDR ? 1 : 0;
      out->prefixlen = m->ifa_prefixlen;
      int len = nh->nlmsg_len - NLMSG_LENGTH(sizeof(*m));
      for (auto* rta = IFA_RTA(m); RTA_OK(rta, len);
           rta = RTA_NEXT(rta, len)) {
        if (rta->rta_type == IFA_ADDRESS || rta->rta_type == IFA_LOCAL) {
          format_addr(m->ifa_family, RTA_DATA(rta), out->addr,
                      sizeof(out->addr));
        }
      }
      return 1;
    }
  }
  return 0;
}

} /* extern "C" */
