/*
 * openr-tpu native netlink library — C ABI.
 *
 * Native equivalent of the reference's from-scratch rtnetlink stack
 * (openr/nl/NetlinkProtocolSocket.h:92, NetlinkMessage.h:143,
 * NetlinkTypes.h, NetlinkRoute.cpp): message serialization, seq-numbered
 * request/ack matching, dump iteration, route/link/addr object model and
 * MPLS route support — redesigned as a compact synchronous C++17 core with
 * a flat C ABI so the Python control plane binds via ctypes (no pybind11 in
 * this image). Blocking is bounded: every transaction is a single
 * send+drain on a socket with a receive timeout.
 */

#ifndef OPENR_TPU_ONL_NETLINK_H
#define OPENR_TPU_ONL_NETLINK_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* MPLS nexthop actions (mirrors openr/if/Network.thrift MplsActionCode) */
enum onl_mpls_action {
  ONL_MPLS_NONE = 0,
  ONL_MPLS_PUSH = 1,
  ONL_MPLS_SWAP = 2,
  ONL_MPLS_PHP = 3, /* pop-and-forward */
};

typedef struct onl_link {
  int32_t ifindex;
  int32_t up; /* IFF_UP && IFF_RUNNING */
  char name[32];
} onl_link;

typedef struct onl_addr {
  int32_t ifindex;
  int32_t prefixlen;
  int32_t family; /* AF_INET / AF_INET6 */
  char addr[64];  /* presentation form */
} onl_addr;

typedef struct onl_nexthop {
  char via[64];  /* gateway address, presentation form; "" = direct */
  int32_t ifindex;
  int32_t weight;      /* ECMP weight, 0 => 1 */
  int32_t mpls_action; /* enum onl_mpls_action */
  int32_t num_labels;
  int32_t labels[8]; /* PUSH: label stack (top first); SWAP: labels[0] */
} onl_nexthop;

typedef struct onl_event {
  int32_t kind; /* 1=link 2=addr 3=route 4=neighbor */
  int32_t ifindex;
  int32_t up;        /* link: admin+oper up; addr: 1=added 0=deleted;
                      * neigh: 1=reachable 0=unreachable/deleted */
  int32_t prefixlen; /* addr only */
  char name[32];     /* link name */
  char addr[64];     /* addr / neighbor dest, presentation form */
  int32_t state;     /* neigh: NUD_* state value */
  char lladdr[24];   /* neigh: link (MAC) address, presentation form */
} onl_event;

/* Neighbor-table entry (reference openr/nl/NetlinkTypes.h:438-525 Neighbor:
 * ifindex + destination + link address + NUD state + reachability). */
typedef struct onl_neigh {
  int32_t ifindex;
  int32_t family;       /* AF_INET / AF_INET6 */
  int32_t state;        /* NUD_* state value */
  int32_t is_reachable; /* per reference isNeighborReachable(state) */
  char dest[64];        /* neighbor IP, presentation form */
  char lladdr[24];      /* link (MAC) address; "" if kernel omitted it */
} onl_neigh;

/* Lifecycle. onl_open returns NULL on failure. */
void* onl_open(void);
void onl_close(void* h);
/* Last error string for this handle (valid until next call). */
const char* onl_strerror(void* h);

/* Link / address dumps. Return count written (<= max), or -1 on error. */
int onl_get_links(void* h, onl_link* out, int max);
int onl_get_addrs(void* h, onl_addr* out, int max);

/* Interface address management (NetlinkSystemHandler equivalent). */
int onl_add_addr(void* h, int ifindex, const char* addr, int prefixlen);
int onl_del_addr(void* h, int ifindex, const char* addr, int prefixlen);

/* Unicast routes. dest is "addr/len". Multi-nexthop => RTA_MULTIPATH ECMP.
 * Returns 0 on success, -1 on error. replace=1 uses NLM_F_REPLACE. */
int onl_add_unicast_route(void* h, const char* dest, int proto, int table,
                          const onl_nexthop* nhs, int n_nhs, int replace);
int onl_del_unicast_route(void* h, const char* dest, int proto, int table);

/* MPLS label routes (AF_MPLS): swap/php per nexthop. */
int onl_add_mpls_route(void* h, int label, const onl_nexthop* nhs, int n_nhs,
                       int replace);
int onl_del_mpls_route(void* h, int label);

/* Neighbor table (NetlinkProtocolSocket::getAllNeighbors equivalent).
 * family: AF_INET / AF_INET6 / 0 (= v4+v6; bridge fdb entries excluded).
 * Returns count written (<= max), or -1 on error. */
int onl_get_neighbors(void* h, int family, onl_neigh* out, int max);

/* Static neighbor management (NeighborBuilder add/del semantics): add
 * installs a NUD_PERMANENT entry for dest with the given link address;
 * del removes the entry. Returns 0 on success, -1 on error. */
int onl_add_neighbor(void* h, int ifindex, const char* dest,
                     const char* lladdr);
int onl_del_neighbor(void* h, int ifindex, const char* dest);

/* Dump routes for (proto, table). Writes one route per line into buf:
 *   dest|via,ifindex,weight[,action:l1/l2];via,ifindex,weight...
 * Returns number of routes, or -1 on error. family: AF_INET/AF_INET6/
 * AF_MPLS/0 (0 = v4+v6). */
int onl_get_routes(void* h, int family, int proto, int table, char* buf,
                   int buflen);

/* Event subscription (PlatformPublisher equivalent): join RTNLGRP_LINK +
 * v4/v6 IFADDR groups on a second socket. onl_event_fd can be polled from
 * an event loop; onl_next_event is non-blocking (returns 1 = event, 0 =
 * none, -1 = error). */
int onl_subscribe(void* h);
int onl_event_fd(void* h);
int onl_next_event(void* h, onl_event* out);

#ifdef __cplusplus
}
#endif

#endif /* OPENR_TPU_ONL_NETLINK_H */
