// Native KvStore storage + CRDT merge engine.
//
// Behavioral equivalent of the merge core of openr/kvstore/KvStore.cpp:
//   mergeKeyValues (KvStore.cpp:261-411): higher version wins; same version
//   -> higher originatorId; same originator -> higher value bytes; identical
//   value -> retain higher ttlVersion; ttl-refresh updates (no value body)
//   bump ttl/ttlVersion only.
//
// The store is a flat hash table of versioned records; Python talks to it
// through this C API with a compact little-endian record format (ctypes on
// the other side — no pybind11 in this image):
//
//   record :=
//     u32 key_len | key bytes
//     i64 version
//     u32 originator_len | originator bytes
//     u8  has_value  [ u32 value_len | value bytes ]
//     i64 ttl
//     i64 ttl_version
//     u8  has_hash   [ i64 hash ]
//
//   record_list := u32 count | record*
//
// Hashes are computed by the caller (generateHash runs at the originator in
// the reference too); the engine only compares and stores them.
#pragma once

#include <cstddef>
#include <cstdint>

extern "C" {

// Opaque store handle.
void *okv_create();
void okv_destroy(void *h);

// Merge a record_list into the store. Returns the number of accepted
// updates and writes their keys (u32 count | (u32 len | key bytes)*) to
// *out/*out_len (malloc'd; free with okv_free) — the caller already holds
// the incoming values mergeKeyValues publishes, so only keys cross the
// boundary. Returns -1 on malformed input.
int okv_merge(void *h, const uint8_t *buf, size_t len, uint8_t **out,
              size_t *out_len);

// Fetch one record (record_list of 0 or 1). Returns 1 if found.
int okv_get(void *h, const uint8_t *key, size_t key_len, uint8_t **out,
            size_t *out_len);

// Unconditional insert/overwrite of a single record. Returns 0, -1 on
// malformed input.
int okv_set(void *h, const uint8_t *rec, size_t len);

// Erase a key. Returns 1 if it existed.
int okv_erase(void *h, const uint8_t *key, size_t key_len);

size_t okv_size(void *h);

// Dump every record as a record_list (iteration order unspecified).
int okv_dump(void *h, uint8_t **out, size_t *out_len);

void okv_free(uint8_t *buf);

}  // extern "C"
