// C++-side unit tests for the native KvStore engine (assert-based; the
// image has no gtest). Exercises the CRDT ordering rules of
// openr/kvstore/KvStore.cpp:261-411 directly against the C API, without
// the Python binding in the loop. Run by tests/test_kvstore_native.py.

#include "onl_kvstore.h"

#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int64_t kTtlInfinity = -(int64_t(1) << 31);

void putU32(std::vector<uint8_t> &b, uint32_t v) {
  const uint8_t *p = reinterpret_cast<const uint8_t *>(&v);
  b.insert(b.end(), p, p + 4);
}
void putI64(std::vector<uint8_t> &b, int64_t v) {
  const uint8_t *p = reinterpret_cast<const uint8_t *>(&v);
  b.insert(b.end(), p, p + 8);
}
void putStr(std::vector<uint8_t> &b, const std::string &s) {
  putU32(b, static_cast<uint32_t>(s.size()));
  b.insert(b.end(), s.begin(), s.end());
}

std::vector<uint8_t> record(const std::string &key, int64_t version,
                            const std::string &orig, const char *value,
                            int64_t ttl = kTtlInfinity,
                            int64_t ttl_version = 0) {
  std::vector<uint8_t> b;
  putStr(b, key);
  putI64(b, version);
  putStr(b, orig);
  if (value) {
    b.push_back(1);
    putStr(b, value);
  } else {
    b.push_back(0);
  }
  putI64(b, ttl);
  putI64(b, ttl_version);
  b.push_back(0);  // no hash
  return b;
}

int mergeOne(void *h, const std::vector<uint8_t> &rec) {
  std::vector<uint8_t> buf;
  putU32(buf, 1);
  buf.insert(buf.end(), rec.begin(), rec.end());
  uint8_t *out;
  size_t out_len;
  int rc = okv_merge(h, buf.data(), buf.size(), &out, &out_len);
  okv_free(out);
  return rc;
}

std::string getValue(void *h, const std::string &key) {
  uint8_t *out;
  size_t out_len;
  int rc = okv_get(h, reinterpret_cast<const uint8_t *>(key.data()),
                   key.size(), &out, &out_len);
  assert(rc == 1);
  // skip: u32 count, u32 klen + key, i64 version, u32 olen + orig
  const uint8_t *p = out + 4;
  uint32_t klen;
  std::memcpy(&klen, p, 4);
  p += 4 + klen;
  p += 8;
  uint32_t olen;
  std::memcpy(&olen, p, 4);
  p += 4 + olen;
  assert(*p == 1);  // has_value
  ++p;
  uint32_t vlen;
  std::memcpy(&vlen, p, 4);
  p += 4;
  std::string v(reinterpret_cast<const char *>(p), vlen);
  okv_free(out);
  return v;
}

}  // namespace

int main() {
  void *h = okv_create();

  // higher version wins
  assert(mergeOne(h, record("k", 2, "b", "old")) == 1);
  assert(mergeOne(h, record("k", 1, "z", "zzz")) == 0);
  assert(mergeOne(h, record("k", 3, "a", "new")) == 1);
  assert(getValue(h, "k") == "new");

  // same version: higher originator wins
  assert(mergeOne(h, record("o", 1, "bbb", "x")) == 1);
  assert(mergeOne(h, record("o", 1, "aaa", "y")) == 0);
  assert(mergeOne(h, record("o", 1, "ccc", "y")) == 1);

  // same originator: higher value bytes win
  assert(mergeOne(h, record("v", 1, "a", "mmm")) == 1);
  assert(mergeOne(h, record("v", 1, "a", "aaa")) == 0);
  assert(mergeOne(h, record("v", 1, "a", "zzz")) == 1);
  assert(getValue(h, "v") == "zzz");

  // ttl refresh without body bumps ttl only
  assert(mergeOne(h, record("t", 1, "a", "body", 5000, 1)) == 1);
  assert(mergeOne(h, record("t", 1, "a", nullptr, 9000, 2)) == 1);
  assert(getValue(h, "t") == "body");
  // stale refresh rejected
  assert(mergeOne(h, record("t", 1, "a", nullptr, 100, 2)) == 0);

  // invalid version / ttl rejected
  assert(mergeOne(h, record("bad", 0, "a", "v")) == 0);
  assert(mergeOne(h, record("bad", 1, "a", "v", 0)) == 0);
  assert(mergeOne(h, record("bad", 1, "a", "v", -5)) == 0);

  // erase + size + dump
  assert(okv_size(h) == 4);
  std::string key = "k";
  assert(okv_erase(h, reinterpret_cast<const uint8_t *>(key.data()),
                   key.size()) == 1);
  assert(okv_size(h) == 3);
  uint8_t *out;
  size_t out_len;
  assert(okv_dump(h, &out, &out_len) == 3);
  okv_free(out);

  // malformed buffer rejected, store untouched
  uint8_t junk[7] = {9, 9, 9, 9, 9, 9, 9};
  assert(okv_merge(h, junk, sizeof(junk), &out, &out_len) == -1);
  assert(okv_size(h) == 3);

  okv_destroy(h);
  std::printf("onl_kvstore_test OK\n");
  return 0;
}
