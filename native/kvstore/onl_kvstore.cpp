// Native KvStore engine implementation. See onl_kvstore.h for the wire
// format and openr/kvstore/KvStore.cpp:261-411 for the merge semantics
// being reproduced.

#include "onl_kvstore.h"

#include <cstdlib>
#include <cstring>
#include <optional>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kTtlInfinity = -(int64_t(1) << 31);

struct Stored {
  int64_t version = 0;
  std::string originator;
  bool has_value = false;
  std::string value;
  int64_t ttl = kTtlInfinity;
  int64_t ttl_version = 0;
  bool has_hash = false;
  int64_t hash = 0;
};

struct Record {
  std::string key;
  Stored v;
};

struct Store {
  std::unordered_map<std::string, Stored> map;
};

// ---------------------------------------------------------------- parsing

class Reader {
 public:
  Reader(const uint8_t *buf, size_t len) : p_(buf), end_(buf + len) {}

  bool u8(uint8_t *v) {
    if (p_ + 1 > end_) return false;
    *v = *p_++;
    return true;
  }
  bool u32(uint32_t *v) {
    if (p_ + 4 > end_) return false;
    std::memcpy(v, p_, 4);
    p_ += 4;
    return true;
  }
  bool i64(int64_t *v) {
    if (p_ + 8 > end_) return false;
    std::memcpy(v, p_, 8);
    p_ += 8;
    return true;
  }
  bool bytes(std::string *out, uint32_t n) {
    if (p_ + n > end_) return false;
    out->assign(reinterpret_cast<const char *>(p_), n);
    p_ += n;
    return true;
  }
  bool done() const { return p_ == end_; }

 private:
  const uint8_t *p_;
  const uint8_t *end_;
};

bool readRecord(Reader &r, Record *rec) {
  uint32_t n;
  uint8_t flag;
  Stored &v = rec->v;
  if (!r.u32(&n) || !r.bytes(&rec->key, n)) return false;
  if (!r.i64(&v.version)) return false;
  if (!r.u32(&n) || !r.bytes(&v.originator, n)) return false;
  if (!r.u8(&flag)) return false;
  v.has_value = flag != 0;
  if (v.has_value) {
    if (!r.u32(&n) || !r.bytes(&v.value, n)) return false;
  }
  if (!r.i64(&v.ttl)) return false;
  if (!r.i64(&v.ttl_version)) return false;
  if (!r.u8(&flag)) return false;
  v.has_hash = flag != 0;
  if (v.has_hash && !r.i64(&v.hash)) return false;
  return true;
}

class Writer {
 public:
  void u8(uint8_t v) { buf_.push_back(v); }
  void u32(uint32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void bytes(const std::string &s) {
    u32(static_cast<uint32_t>(s.size()));
    append(s.data(), s.size());
  }
  void record(const std::string &key, const Stored &s) {
    bytes(key);
    i64(s.version);
    bytes(s.originator);
    u8(s.has_value ? 1 : 0);
    if (s.has_value) bytes(s.value);
    i64(s.ttl);
    i64(s.ttl_version);
    u8(s.has_hash ? 1 : 0);
    if (s.has_hash) i64(s.hash);
  }
  void raw(const Writer &other) {
    buf_.insert(buf_.end(), other.buf_.begin(), other.buf_.end());
  }
  // Hand the buffer to C: malloc'd copy the caller frees with okv_free.
  void release(uint8_t **out, size_t *out_len) {
    *out_len = buf_.size();
    *out = static_cast<uint8_t *>(std::malloc(buf_.size() ? buf_.size() : 1));
    if (!buf_.empty()) std::memcpy(*out, buf_.data(), buf_.size());
  }

 private:
  void append(const void *p, size_t n) {
    const uint8_t *b = static_cast<const uint8_t *>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

}  // namespace

// ----------------------------------------------------------------- C API

extern "C" {

void *okv_create() { return new Store(); }

void okv_destroy(void *h) { delete static_cast<Store *>(h); }

int okv_merge(void *h, const uint8_t *buf, size_t len, uint8_t **out,
              size_t *out_len) {
  auto *store = static_cast<Store *>(h);
  Reader r(buf, len);
  uint32_t count;
  if (!r.u32(&count)) return -1;

  Writer updates;
  uint32_t accepted = 0;
  for (uint32_t i = 0; i < count; ++i) {
    Record rec;
    if (!readRecord(r, &rec)) return -1;

    const Stored &in = rec.v;
    // versions start at 1 (KvStore.cpp:277-279)
    if (in.version < 1) continue;
    // TTL must be infinite or positive
    if (in.ttl != kTtlInfinity && in.ttl <= 0) continue;

    auto it = store->map.find(rec.key);
    const Stored *existing = it == store->map.end() ? nullptr : &it->second;
    int64_t my_version = existing ? existing->version : 0;
    if (in.version < my_version) continue;  // stale

    bool update_all = false;
    bool update_ttl = false;
    if (in.has_value) {
      if (in.version > my_version) {
        update_all = true;
      } else if (in.originator > existing->originator) {
        update_all = true;
      } else if (in.originator == existing->originator) {
        if (!existing->has_value || in.value > existing->value) {
          // deterministic winner on divergent same-version values
          update_all = true;
        } else if (in.value == existing->value) {
          if (in.ttl_version > existing->ttl_version) update_ttl = true;
        }
      }
    }
    // ttl refresh (no value body)
    if (!in.has_value && existing && in.version == existing->version &&
        in.originator == existing->originator &&
        in.ttl_version > existing->ttl_version) {
      update_ttl = true;
    }

    if (!update_all && !update_ttl) continue;

    if (update_all) {
      // caller pre-computes missing hashes
      store->map[rec.key] = std::move(rec.v);
    } else {  // update_ttl
      Stored &s = it->second;
      s.ttl = in.ttl;
      s.ttl_version = in.ttl_version;
    }
    updates.bytes(rec.key);
    ++accepted;
  }

  Writer result;
  result.u32(accepted);
  result.raw(updates);
  result.release(out, out_len);
  return static_cast<int>(accepted);
}

int okv_get(void *h, const uint8_t *key, size_t key_len, uint8_t **out,
            size_t *out_len) {
  auto *store = static_cast<Store *>(h);
  std::string k(reinterpret_cast<const char *>(key), key_len);
  auto it = store->map.find(k);
  Writer w;
  if (it == store->map.end()) {
    w.u32(0);
    w.release(out, out_len);
    return 0;
  }
  w.u32(1);
  w.record(k, it->second);
  w.release(out, out_len);
  return 1;
}

int okv_set(void *h, const uint8_t *rec_buf, size_t len) {
  auto *store = static_cast<Store *>(h);
  Reader r(rec_buf, len);
  Record rec;
  if (!readRecord(r, &rec)) return -1;
  store->map[rec.key] = std::move(rec.v);
  return 0;
}

int okv_erase(void *h, const uint8_t *key, size_t key_len) {
  auto *store = static_cast<Store *>(h);
  std::string k(reinterpret_cast<const char *>(key), key_len);
  return store->map.erase(k) ? 1 : 0;
}

size_t okv_size(void *h) { return static_cast<Store *>(h)->map.size(); }

int okv_dump(void *h, uint8_t **out, size_t *out_len) {
  auto *store = static_cast<Store *>(h);
  Writer w;
  w.u32(static_cast<uint32_t>(store->map.size()));
  for (const auto &[key, s] : store->map) w.record(key, s);
  w.release(out, out_len);
  return static_cast<int>(store->map.size());
}

void okv_free(uint8_t *buf) { std::free(buf); }

}  // extern "C"
