"""Tier-1 observability lint: every counter/histogram name emitted through
CountersMixin/HistogramsMixin follows the `<module>.<name>` convention from
docs/Monitoring.md — drift fails at test time, not in dashboards.

This test is now a thin alias onto the `registry-drift` rule of the
project analysis suite (openr_tpu/analysis/registry_drift.py,
docs/Analysis.md) so the naming contract lives in ONE place: the rule owns
the AST walk (mixin users, `self._bump("...")`/`_observe`/`_timer` and
literal subscripts on `counters`/`histograms`/`_ensure_*()`), the
convention regex, the prefix allowlist, and — beyond what this file ever
checked — the cross-checks against docs/Monitoring.md's tables. The test
names below are kept for continuity; the deeper per-check coverage lives
in tests/test_analysis.py.
"""

import functools
from pathlib import Path

import openr_tpu
from openr_tpu.analysis import RULES, build_context
from openr_tpu.analysis.registry_drift import (
    collect_emitted_names as _collect_emitted,
)

PKG = Path(openr_tpu.__file__).resolve().parent


@functools.lru_cache(maxsize=1)
def _ctx():
    return build_context([PKG])


def collect_emitted_names():
    """Legacy shape: (name, 'file:line') pairs from every mixin user in
    the package — kept so downstream tooling keyed on this helper keeps
    working; the walk itself lives in the registry-drift rule."""
    return [
        (name, f"{sf.rel}:{line}")
        for name, sf, line in _collect_emitted(_ctx())
    ]


@functools.lru_cache(maxsize=1)
def _drift_findings():
    return list(RULES["registry-drift"].run(_ctx()))


def test_scanner_finds_the_counter_surface():
    """Guard against scanner rot: the walk must see the known emission
    sites, including the observability layer's new names."""
    names = {name for name, _ in collect_emitted_names()}
    assert len(names) >= 40, sorted(names)
    for expected in (
        "decision.adj_db_update",
        "decision.debounce_ms",
        "decision.spf.solve_ms",
        "decision.spf.invalidation_rounds_last",
        # solver fault domain (solver/supervisor.py + tpu.py)
        "decision.spf.fallback_active",
        "decision.spf.fallback_solves",
        "decision.spf.solver_failures",
        "decision.spf.solver_retries",
        "decision.spf.breaker_trips",
        "decision.spf.probe_attempts",
        "decision.spf.probe_successes",
        "decision.spf.probe_failures",
        "decision.spf.audit_runs",
        "decision.spf.audit_mismatches",
        "decision.spf.audit_forced_cold_solves",
        "decision.spf.warm_state_invalidations",
        "fib.program_ms",
        "convergence.e2e_ms",
        "kvstore.num_updates",
        "link_monitor.neighbor_up",
    ):
        assert expected in names, expected


def test_counter_names_follow_convention():
    bad = [
        (f.message, f"{f.path}:{f.line}")
        for f in _drift_findings()
        if f.check == "counter-name"
    ]
    assert not bad, f"counter names violating <module>.<name>: {bad}"


def test_histogram_names_carry_a_unit_suffix():
    """Latency/size distributions must self-describe their unit."""
    bad = [
        (f.message, f"{f.path}:{f.line}")
        for f in _drift_findings()
        if f.check == "histogram-unit"
    ]
    assert not bad, f"histogram names missing unit suffix: {bad}"


def test_registry_docs_match_code():
    """The naming tables in docs/Monitoring.md and the fault-point catalog
    in docs/Robustness.md describe the shipped code — the part of the
    contract the old standalone lint could not check."""
    doc_checks = {
        "doc-ghost",
        "undocumented-histogram",
        "undocumented-fault-point",
        "ghost-fault-point",
        "undocumented-config-knob",
    }
    bad = [
        (f.check, f.message)
        for f in _drift_findings()
        if f.check in doc_checks
    ]
    assert not bad, bad
