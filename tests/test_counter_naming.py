"""Tier-1 observability lint: every counter/histogram name emitted through
CountersMixin/HistogramsMixin follows the `<module>.<name>` convention from
docs/Monitoring.md — drift fails at test time, not in dashboards.

The walk is AST-based: classes inheriting (transitively, by name) from the
mixins are scanned for literal names at the emission sites —
`self._bump("...")`, `self._observe("...")`, `self._timer("...")` and
literal subscripts on `counters` / `histograms` /
`_ensure_counters()` / `_ensure_histograms()`. Non-mixin counter dicts
(e.g. MockFibHandler's per-API mock counters) are intentionally out of
scope, exactly as the convention is.
"""

import ast
import re
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "openr_tpu"

MIXINS = {"CountersMixin", "HistogramsMixin"}

# module prefixes registered with the Monitor (openr.py) plus the
# cross-module end-to-end namespace
ALLOWED_PREFIXES = {
    "decision",
    "kvstore",
    "fib",
    "spark",
    "link_monitor",
    "prefix_manager",
    "convergence",
}

# <module>.<name>[.<name>...], lowercase snake segments
NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")

_EMIT_CALLS = {"_bump", "_observe", "_timer"}
_DICT_ATTRS = {"counters", "histograms"}
_ENSURE_CALLS = {"_ensure_counters", "_ensure_histograms"}


def _base_names(node: ast.ClassDef):
    for base in node.bases:
        if isinstance(base, ast.Name):
            yield base.id
        elif isinstance(base, ast.Attribute):
            yield base.attr


def _mixin_classes(trees):
    """Names of classes inheriting a mixin, transitively by simple name."""
    bases = {}
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases[node.name] = set(_base_names(node))
    users = set(MIXINS)
    changed = True
    while changed:
        changed = False
        for name, bs in bases.items():
            if name not in users and bs & users:
                users.add(name)
                changed = True
    return users - MIXINS


def _is_dict_ref(node) -> bool:
    """`self.counters` / `x.histograms` / `self._ensure_counters()` or a
    local alias of one (`counters = self._ensure_counters()`)."""
    if isinstance(node, ast.Attribute) and node.attr in _DICT_ATTRS:
        return True
    if isinstance(node, ast.Name) and node.id in _DICT_ATTRS:
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _ENSURE_CALLS
    )


def collect_emitted_names():
    """(name, 'file:line') pairs from every mixin user in the package."""
    trees = {
        py: ast.parse(py.read_text(), filename=str(py))
        for py in sorted(PKG.rglob("*.py"))
    }
    mixin_users = _mixin_classes(trees)
    found = []
    for py, tree in trees.items():
        for cls in ast.walk(tree):
            if not (
                isinstance(cls, ast.ClassDef) and cls.name in mixin_users
            ):
                continue
            for node in ast.walk(cls):
                name = None
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    name = node.args[0].value
                elif (
                    isinstance(node, ast.Subscript)
                    and _is_dict_ref(node.value)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    name = node.slice.value
                if name is not None:
                    rel = py.relative_to(PKG.parent)
                    found.append((name, f"{rel}:{node.lineno}"))
    return found


def test_scanner_finds_the_counter_surface():
    """Guard against scanner rot: the walk must see the known emission
    sites, including the observability layer's new names."""
    names = {name for name, _ in collect_emitted_names()}
    assert len(names) >= 40, sorted(names)
    for expected in (
        "decision.adj_db_update",
        "decision.debounce_ms",
        "decision.spf.solve_ms",
        "decision.spf.invalidation_rounds_last",
        # solver fault domain (solver/supervisor.py + tpu.py)
        "decision.spf.fallback_active",
        "decision.spf.fallback_solves",
        "decision.spf.solver_failures",
        "decision.spf.solver_retries",
        "decision.spf.breaker_trips",
        "decision.spf.probe_attempts",
        "decision.spf.probe_successes",
        "decision.spf.probe_failures",
        "decision.spf.audit_runs",
        "decision.spf.audit_mismatches",
        "decision.spf.audit_forced_cold_solves",
        "decision.spf.warm_state_invalidations",
        "fib.program_ms",
        "convergence.e2e_ms",
        "kvstore.num_updates",
        "link_monitor.neighbor_up",
    ):
        assert expected in names, expected


def test_counter_names_follow_convention():
    bad = [
        (name, where)
        for name, where in collect_emitted_names()
        if not NAME_RE.match(name)
        or name.split(".", 1)[0] not in ALLOWED_PREFIXES
    ]
    assert not bad, f"counter names violating <module>.<name>: {bad}"


def test_histogram_names_carry_a_unit_suffix():
    """Latency/size distributions must self-describe their unit."""
    trees = {
        py: ast.parse(py.read_text(), filename=str(py))
        for py in sorted(PKG.rglob("*.py"))
    }
    bad = []
    for py, tree in trees.items():
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in {"_observe", "_timer"}
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                name = node.args[0].value
                if not name.endswith(("_ms", "_bytes")):
                    bad.append((name, f"{py.name}:{node.lineno}"))
    assert not bad, f"histogram names missing unit suffix: {bad}"
