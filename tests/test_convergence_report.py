"""Cross-node convergence tracing (ISSUE 5): KvStore flood-hop traces
(per-hop PerfEvent stamps, hop counts, duplicate accounting, buffer
delay), publication-stamp completeness, the report aggregation layer
(monitor/report.py), and its ctrl/breeze surfaces."""

import asyncio
import json

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreParams,
    PeerSpec,
)
from openr_tpu.kvstore import wire
from openr_tpu.kvstore.store import (
    FLOOD_ORIGINATED_EVENT,
    FLOOD_RECEIVED_EVENT,
    FLOOD_TRACE_EVENT,
)
from openr_tpu.monitor import LogSample, Monitor
from openr_tpu.monitor.report import (
    aggregate_convergence_reports,
    node_convergence_report,
    percentile_summary,
)
from openr_tpu.monitor.spans import SPAN_EVENT
from openr_tpu.types import PerfEvents, Publication, Value


def run(coro, timeout=60.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def v(version=1, originator="node1", value=b"data"):
    return Value(version, originator, value)


def make_stores(names, log_sinks=None, **params_kw):
    transport = InProcessTransport()
    stores = {}
    for name in names:
        stores[name] = KvStore(
            name,
            ["0"],
            transport,
            params=KvStoreParams(node_id=name, **params_kw),
            log_sample_fn=(
                log_sinks[name].append if log_sinks is not None else None
            ),
        )
    return stores, transport


async def settle(delay=0.05):
    await asyncio.sleep(delay)


class TestFloodHopTrace:
    def test_chain_flood_carries_hop_trace(self):
        """a → b → c: c receives the publication with the origin stamp and
        one per-hop stamp, measures hop latency, and records hop count 2."""
        sinks = {n: [] for n in ("a", "b", "c")}

        async def body():
            stores, _ = make_stores(["a", "b", "c"], log_sinks=sinks)
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a"), "c": PeerSpec("c")})
            stores["c"].add_peers({"b": PeerSpec("b")})
            await settle()
            for sink in sinks.values():
                sink.clear()
            stores["a"].set_key("k", v(originator="a", value=b"flood"))
            await settle()
            return stores

        stores = run(body())
        # b is one hop from the origin, c two
        assert stores["b"].db().counters["kvstore.flood.hop_count_last"] == 1
        assert stores["c"].db().counters["kvstore.flood.hop_count_last"] == 2
        # per-hop + e2e latency histograms recorded on both receivers
        for name in ("b", "c"):
            hists = stores[name].histograms
            assert hists["kvstore.flood.hop_ms"].count >= 1
            assert hists["kvstore.flood.e2e_ms"].count >= 1
        # c's FLOOD_TRACE names the origin and the 2-hop path
        traces = [
            s for s in sinks["c"] if s.get("event") == FLOOD_TRACE_EVENT
        ]
        assert traces, sinks["c"]
        flap = [t for t in traces if t.get("origin") == "a"]
        assert flap and flap[-1].get("hop_count") == 2
        assert flap[-1].get("hop_ms") is not None
        assert flap[-1].get("e2e_ms") >= flap[-1].get("hop_ms") - 1e-6

    def test_ring_duplicate_floods_counted(self):
        """In a full mesh of three stores the same update arrives over
        multiple paths: the extra arrivals are redundant floods (path
        vector loops or empty merges) and must show up in the duplicate
        ratio."""

        async def body():
            stores, _ = make_stores(["a", "b", "c"])
            ring = {"a": ["b", "c"], "b": ["a", "c"], "c": ["a", "b"]}
            for name, peers in ring.items():
                stores[name].add_peers({p: PeerSpec(p) for p in peers})
            await settle()
            stores["a"].set_key("k", v(originator="a", value=b"ring"))
            await settle()
            return stores

        stores = run(body())
        received = sum(
            s.counters.get("kvstore.flood.received", 0)
            for s in stores.values()
        )
        duplicates = sum(
            s.counters.get("kvstore.flood.duplicates", 0)
            for s in stores.values()
        )
        assert received > 0
        assert 0 < duplicates < received

    def test_rate_limited_buffer_records_queue_delay(self):
        async def body():
            stores, _ = make_stores(
                ["a", "b"],
                flood_rate=2.0,
                flood_burst=2.0,
                flood_buffer_delay=0.03,
            )
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await settle()
            for i in range(10):
                stores["a"].set_key(f"k{i}", v(originator="a", value=b"x"))
            await settle(0.4)
            return stores

        stores = run(body())
        hist = stores["a"].histograms["kvstore.flood.buffer_delay_ms"]
        assert hist.count >= 1
        assert hist.max > 0.0

    def test_origin_stamp_only_at_origin(self):
        """A forwarded publication must not be re-stamped as originated:
        the trace c receives starts with a's origin event followed by b's
        receive event, in stamp order."""
        captured = {}

        async def body():
            stores, transport = make_stores(["a", "b", "c"])
            original = transport.call_set

            async def spy(caller, peer_addr, area, kv, node_ids, perf=None):
                if caller == "b" and peer_addr == "c":
                    captured["perf"] = perf
                await original(caller, peer_addr, area, kv, node_ids, perf)

            transport.call_set = spy
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a"), "c": PeerSpec("c")})
            stores["c"].add_peers({"b": PeerSpec("b")})
            await settle()
            stores["a"].set_key("k", v(originator="a", value=b"flood"))
            await settle()

        run(body())
        perf = captured["perf"]
        descrs = [(e.node_name, e.event_descr) for e in perf.events]
        assert descrs == [
            ("a", FLOOD_ORIGINATED_EVENT),
            ("b", FLOOD_RECEIVED_EVENT),
        ]
        assert perf.events[0].unix_ts <= perf.events[1].unix_ts


class TestFloodTraceBound:
    def test_trace_keeps_origin_plus_recent_hops(self):
        """The timing trace is capped (origin + most recent hops) so
        large-diameter topologies don't pay O(diameter²) per publication;
        hop COUNTS come from the uncapped nodeIds vector."""
        from openr_tpu.kvstore.store import FLOOD_TRACE_MAX_EVENTS

        stores, _ = make_stores(["z"])
        db = stores["z"].db()
        perf = PerfEvents()
        perf.add_fine("origin", FLOOD_ORIGINATED_EVENT)
        for i in range(FLOOD_TRACE_MAX_EVENTS + 10):
            perf.add_fine(f"hop{i}", FLOOD_RECEIVED_EVENT)
        node_ids = ["origin"] + [
            f"hop{i}" for i in range(FLOOD_TRACE_MAX_EVENTS + 10)
        ]
        # db has no peers, so observe the capped trace on the internal
        # publication instead of a peer forward
        reader = stores["z"].updates_queue.get_reader()
        db.handle_set_key_vals(
            {"k": v(originator="origin")}, node_ids, perf
        )
        pub = reader.try_get()
        assert pub is not None
        traced = pub.perf_events
        assert len(traced.events) == FLOOD_TRACE_MAX_EVENTS
        # origin stamp survives; the newest hop is this store's own stamp
        assert traced.events[0].event_descr == FLOOD_ORIGINATED_EVENT
        assert traced.events[-1].node_name == "z"
        # the exact hop count rode the path vector, uncapped
        assert db.counters["kvstore.flood.hop_count_last"] == len(node_ids)


class TestPublicationStamps:
    """Satellite: every publication-emitting path stamps ts_monotonic so
    downstream spans never seed from a missing stamp."""

    def test_dump_and_sync_responses_are_stamped(self):
        stores, _ = make_stores(["a"])
        db = stores["a"].db()
        db.set_key_vals({"k": v(originator="a")})
        assert db.dump_all().ts_monotonic is not None
        assert db.dump_hashes().ts_monotonic is not None
        assert db.get_key_vals(["k"]).ts_monotonic is not None
        # full-sync response (3-way difference) path
        hashes = db.dump_hashes().key_vals
        assert db.handle_dump(hashes).ts_monotonic is not None
        assert db.handle_dump(None).ts_monotonic is not None

    def test_internal_publications_are_stamped(self):
        async def body():
            stores, _ = make_stores(["a", "b"])
            reader = stores["b"].updates_queue.get_reader()
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await settle()
            stores["a"].set_key("k", v(originator="a"))
            await settle()
            pubs = []
            while True:
                pub = reader.try_get()
                if pub is None:
                    break
                pubs.append(pub)
            assert pubs
            assert all(p.ts_monotonic is not None for p in pubs)

        run(body())


class TestWireRoundTrip:
    def test_perf_events_survive_publication_json(self):
        perf = PerfEvents()
        perf.add_fine("a", FLOOD_ORIGINATED_EVENT)
        perf.add_fine("b", FLOOD_RECEIVED_EVENT)
        pub = Publication(
            key_vals={"k": v(originator="a")},
            node_ids=["a", "b"],
            perf_events=perf,
        )
        decoded = wire.publication_from_json(
            json.loads(json.dumps(wire.publication_to_json(pub)))
        )
        assert decoded.node_ids == ["a", "b"]
        got = [
            (e.node_name, e.event_descr, e.unix_ts)
            for e in decoded.perf_events.events
        ]
        want = [
            (e.node_name, e.event_descr, e.unix_ts) for e in perf.events
        ]
        assert got == want

    def test_absent_trace_stays_absent(self):
        pub = Publication(key_vals={"k": v()})
        decoded = wire.publication_from_json(wire.publication_to_json(pub))
        assert decoded.perf_events is None


# ---------------------------------------------------------------------------
# report aggregation
# ---------------------------------------------------------------------------


def _span_sample(node, total_ms, stages):
    sample = LogSample()
    sample.add_string("event", SPAN_EVENT)
    sample.add_string("span", "convergence")
    sample.add_string("node_name", node)
    for stage, ms in stages.items():
        sample.add_double(f"{stage}_ms", ms)
    sample.add_double("total_ms", total_ms)
    return sample


def _flood_sample(origin, hop_count, hop_ms):
    sample = LogSample()
    sample.add_string("event", FLOOD_TRACE_EVENT)
    sample.add_string("origin", origin)
    sample.add_int("hop_count", hop_count)
    sample.add_int("keys", 1)
    sample.add_int("updated", 1)
    sample.add_int("duplicate", 0)
    sample.add_double("hop_ms", hop_ms)
    sample.add_double("e2e_ms", hop_ms * hop_count)
    return sample


class TestPercentileSummary:
    def test_empty(self):
        summary = percentile_summary([])
        assert summary["count"] == 0 and summary["p95"] == 0.0

    def test_order_and_bounds(self):
        summary = percentile_summary(range(1, 101))
        assert summary["count"] == 100
        assert summary["min"] == 1 and summary["max"] == 100
        assert summary["p50"] == 50 and summary["p95"] == 95
        assert summary["p50"] <= summary["p95"] <= summary["max"]


class TestReportAggregation:
    def _monitor(self, node, samples):
        monitor = Monitor(node)
        for sample in samples:
            monitor.add_event_log(sample)
        return monitor

    def test_node_report_collects_spans_and_floods(self):
        monitor = self._monitor(
            "n1",
            [
                _span_sample("n1", 12.0, {"decision.recv": 1.0}),
                _flood_sample("n0", 2, 0.5),
                LogSample().add_string("event", "SOLVER_BREAKER_TRIPPED"),
            ],
        )
        report = node_convergence_report("n1", monitor)
        assert len(report["spans"]) == 1
        assert report["e2e_ms"] == [12.0]
        assert len(report["floods"]) == 1
        assert report["flood"]["duplicate_ratio"] == 0.0

    def test_aggregate_percentiles_and_slowest_stage(self):
        reports = []
        for i, node in enumerate(("n0", "n1", "n2")):
            monitor = self._monitor(
                node,
                [
                    _span_sample(
                        node,
                        10.0 * (i + 1),
                        {
                            "decision.route_build": 2.0,
                            "fib.program": 5.0 * (i + 1),
                        },
                    ),
                    _flood_sample("n0", i, 0.25 * (i + 1)),
                ],
            )
            reports.append(node_convergence_report(node, monitor))
        agg = aggregate_convergence_reports(reports)
        assert agg["nodes"] == 3 and agg["spans_total"] == 3
        assert agg["e2e_ms"]["p50"] == 20.0
        assert agg["e2e_ms"]["max"] == 30.0
        assert agg["slowest_stage"] == {
            "node": "n2",
            "stage": "fib.program",
            "ms": 15.0,
        }
        assert set(agg["stages"]) == {"decision.route_build", "fib.program"}
        assert agg["flood"]["hop_count_max"] == 2
        assert agg["flood"]["hop_ms"]["count"] == 3
        # per-node breakdown present for dashboards
        assert agg["node_e2e_ms"]["n1"]["max"] == 20.0


class TestCtrlAndBreezeSurfaces:
    def test_ctrl_get_convergence_report(self):
        from openr_tpu.ctrl.server import CtrlServer

        stores, _ = make_stores(["a"])
        monitor = Monitor("a")
        monitor.add_event_log(
            _span_sample("a", 7.0, {"decision.recv": 1.0})
        )
        server = CtrlServer("a", kvstore=stores["a"], monitor=monitor)
        report = server.m_getConvergenceReport({})
        assert report["node"] == "a"
        assert report["e2e_ms"] == [7.0]
        # the report must be JSON-serializable (it rides the ctrl wire)
        json.dumps(report)

    def test_breeze_perf_report_renders(self, capsys):
        from openr_tpu.cli.breeze import build_parser, cmd_perf

        report = {
            "node": "a",
            "spans": [
                {
                    "decision.route_build_ms": 2.0,
                    "fib.program_ms": 3.0,
                    "total_ms": 9.0,
                }
            ],
            "e2e_ms": [9.0],
            "floods": [{"hop_count": 2, "hop_ms": 0.4}],
            "flood": {"received": 4, "duplicates": 1},
        }

        class StubClient:
            ssl_context = None

            def call(self, method, **params):
                assert method == "getConvergenceReport"
                return report

        args = build_parser().parse_args(
            ["--port", "1", "perf", "report", "--json"]
        )
        cmd_perf(StubClient(), args)
        out = capsys.readouterr().out
        assert "network-wide convergence: 1 node(s)" in out
        assert "node-to-converge e2e_ms" in out
        assert "stage fib.program_ms" in out
        assert "slowest hop: fib.program on a" in out
        assert "max hop count 2" in out
        assert '"nodes": 1' in out  # --json dump

    def test_breeze_perf_report_against_live_emulator(self):
        """ISSUE 5 acceptance surface, end to end over real sockets: an
        emulator run, `breeze perf report --hosts <peer>` against the live
        ctrl servers, network-wide percentiles out."""
        import contextlib
        import io

        from openr_tpu.cli import breeze
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            net = VirtualNetwork()
            a = net.add_node("ra", loopback_prefix="10.91.0.0/24")
            b = net.add_node("rb", loopback_prefix="10.92.0.0/24")
            await net.start_all()
            net.connect("ra", "eth0", "rb", "eth0")
            await wait_until(
                lambda: "10.92.0.0/24" in a.programmed_prefixes()
                and "10.91.0.0/24" in b.programmed_prefixes(),
                timeout=30,
            )

            def has_span(wrapper):
                return any(
                    s.get("event") == SPAN_EVENT
                    for s in wrapper.daemon.monitor.get_event_logs()
                )

            await wait_until(
                lambda: has_span(a) and has_span(b), timeout=30
            )
            loop = asyncio.get_running_loop()

            def collect() -> str:
                # the blocking CLI client must not run on the loop thread
                # that serves the ctrl sockets — executor it is
                args = breeze.build_parser().parse_args(
                    [
                        "--port", str(a.ctrl_port),
                        "perf", "report",
                        "--hosts", f"127.0.0.1:{b.ctrl_port}",
                    ]
                )
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    with breeze.BlockingCtrlClient(
                        "127.0.0.1", a.ctrl_port
                    ) as client:
                        breeze.cmd_perf(client, args)
                return buf.getvalue()

            try:
                return await loop.run_in_executor(None, collect)
            finally:
                await net.stop_all()

        out = run(body())
        assert "network-wide convergence: 2 node(s)" in out
        assert "node-to-converge e2e_ms" in out
        assert "stage fib.program_ms" in out
        assert "slowest hop:" in out
        assert "flood:" in out
