"""SOAK_SMOKE tier-1 smoke (the churn sibling of FAULT_SMOKE and
TRACE_SMOKE): a seconds-long topology-churn soak — 3-node line, one
OCS-style reconfiguration wave, one injected fault — must drive the whole
continuous-telemetry loop end to end: the judged report machinery runs,
the windowed rollup accounts for 100% of convergence events while the
deliberately tiny LogSample ring only retains a tail (the eviction-proof
invariant), every scrape parses as valid exposition with full registry
coverage, and the verdict block carries every check."""

from openr_tpu.testing.soak import run_soak_smoke


def test_soak_smoke():
    report = run_soak_smoke()
    # the assertions live inside run_soak_smoke (shared with the driver
    # dry-run); re-pin the headline evidence here so a future refactor
    # cannot silently hollow the smoke out
    assert report["verdict"]["pass"] is True
    events = report["events"]
    assert events["total"] > report["config"]["max_event_log"]
    assert events["spans_in_rings"] < events["total"]
    assert (
        events["windowed"] + events["evicted_window_events"]
        == events["total"]
    )
    assert report["faults"]["fired"]["fib.program"] == 1
    assert len(report["waves"]) == 1 and report["waves"][0]["converged"]
