"""SOAK_SMOKE tier-1 smoke (the churn sibling of FAULT_SMOKE and
TRACE_SMOKE): a seconds-long topology-churn soak — 3-node line, one
OCS-style reconfiguration wave, one injected fault — must drive the whole
continuous-telemetry loop end to end: the judged report machinery runs,
the windowed rollup accounts for 100% of convergence events while the
deliberately tiny LogSample ring only retains a tail (the eviction-proof
invariant), every scrape parses as valid exposition with full registry
coverage, and the verdict block carries every check."""

from openr_tpu.testing.soak import SoakConfig, run_soak, run_soak_smoke


def test_soak_smoke():
    report = run_soak_smoke()
    # the assertions live inside run_soak_smoke (shared with the driver
    # dry-run); re-pin the headline evidence here so a future refactor
    # cannot silently hollow the smoke out
    assert report["verdict"]["pass"] is True
    events = report["events"]
    assert events["total"] > report["config"]["max_event_log"]
    assert events["spans_in_rings"] < events["total"]
    assert (
        events["windowed"] + events["evicted_window_events"]
        == events["total"]
    )
    assert report["faults"]["fired"]["fib.program"] == 1
    assert len(report["waves"]) == 1 and report["waves"][0]["converged"]


def test_soak_partition_wave():
    """--partition-every wave type: one asymmetric line-edge split via
    the chaos mesh, healed after partition_hold_s — convergence must
    recover and the verdict must carry the partition checks."""
    report = run_soak(
        SoakConfig(
            nodes=3,
            waves=1,
            settle_s=0.3,
            fault_every=0,
            partition_every=1,
            partition_hold_s=0.3,
            seed=5,
            window_s=0.5,
        )
    )
    wave = report["waves"][0]
    assert len(wave["partitioned"]) == 1 and "->" in wave["partitioned"][0]
    assert wave["converged"] is True
    checks = report["verdict"]["checks"]
    assert checks["partitions_recovered"]["ok"] is True
    assert "1/1 partition wave(s)" in checks["partitions_recovered"]["detail"]
    assert checks["flood_health_attributed"]["ok"] is True
    # the partition interval is recorded as a fault interval, so any
    # p95 effect inside it is attributed, never a clean trend break
    assert len(report["faults"]["intervals"]) == 1
    assert report["verdict"]["pass"] is True
