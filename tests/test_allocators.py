"""RangeAllocator + PrefixAllocator tests, mirroring
openr/allocators/tests/RangeAllocatorTest.cpp (unique elections, conflict
resolution by originator id) and PrefixAllocatorTest.cpp (sub-prefix
computation, modes, persisted index reuse)."""

import asyncio
import random

import pytest

from openr_tpu.allocators import (
    PrefixAllocationMode,
    PrefixAllocationParams,
    PrefixAllocator,
    PrefixAllocatorConfig,
    RangeAllocator,
)
from openr_tpu.allocators.prefix_allocator import (
    SEED_PREFIX_KEY,
    STATIC_ALLOC_KEY,
    get_nth_prefix,
)
from openr_tpu.configstore import PersistentStore
from openr_tpu.kvstore import InProcessTransport, KvStore, KvStoreClient
from openr_tpu.types import IpPrefix
from openr_tpu.utils import serializer


def run(coro, timeout=20.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def make_store_mesh(names, transport=None):
    """Full-mesh KvStores over the in-process transport."""
    transport = transport or InProcessTransport()
    stores = {
        name: KvStore(name, ["0"], transport) for name in names
    }
    from openr_tpu.kvstore import PeerSpec

    for name, store in stores.items():
        store.add_peers(
            {other: PeerSpec(other) for other in names if other != name}
        )
    return stores


async def wait_until(predicate, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        assert asyncio.get_event_loop().time() < deadline, "timed out"
        await asyncio.sleep(0.02)


class TestRangeAllocator:
    def test_single_node_allocates_init_value(self):
        async def body():
            stores = make_store_mesh(["n1"])
            client = KvStoreClient(stores["n1"])
            got = []
            alloc = RangeAllocator(
                "n1", "alloc:", client, got.append, min_backoff=0.001
            )
            alloc.start_allocator((0, 15), init_value=7)
            await wait_until(lambda: got)
            assert got == [7]
            assert alloc.get_value() == 7
            assert alloc.get_value_from_kvstore() == 7
            alloc.stop()
            client.stop()

        run(body())

    def test_unique_values_across_nodes(self):
        async def body():
            names = [f"node-{i}" for i in range(4)]
            stores = make_store_mesh(names)
            clients = {n: KvStoreClient(stores[n]) for n in names}
            results = {}
            allocators = {}
            for i, n in enumerate(names):
                results[n] = []
                allocators[n] = RangeAllocator(
                    n,
                    "alloc:",
                    clients[n],
                    results[n].append,
                    min_backoff=0.001,
                    max_backoff=0.05,
                    rng=random.Random(i),
                )
            for i, n in enumerate(names):
                # everyone wants value 0 initially: conflicts must resolve
                allocators[n].start_allocator((0, 7), init_value=0)
            await wait_until(
                lambda: all(
                    a.get_value() is not None for a in allocators.values()
                ),
                timeout=15,
            )
            # let elections settle (steals can still be in flight)
            for _ in range(50):
                await asyncio.sleep(0.02)
                values = [a.get_value() for a in allocators.values()]
                if None not in values and len(set(values)) == len(names):
                    break
            values = [a.get_value() for a in allocators.values()]
            assert len(set(values)) == len(names), values
            assert all(0 <= v <= 7 for v in values)
            for a in allocators.values():
                a.stop()
            for c in clients.values():
                c.stop()

        run(body())

    def test_higher_originator_steals_with_override(self):
        async def body():
            stores = make_store_mesh(["aaa", "zzz"])
            ca = KvStoreClient(stores["aaa"])
            cz = KvStoreClient(stores["zzz"])
            got_a, got_z = [], []
            # range of exactly one value: they must fight for it
            alloc_a = RangeAllocator(
                "aaa", "alloc:", ca, got_a.append, min_backoff=0.001
            )
            alloc_a.start_allocator((5, 5), init_value=5)
            await wait_until(lambda: alloc_a.get_value() == 5)

            alloc_z = RangeAllocator(
                "zzz", "alloc:", cz, got_z.append, min_backoff=0.001
            )
            alloc_z.start_allocator((5, 5), init_value=5)
            await wait_until(lambda: alloc_z.get_value() == 5)
            # lower originator loses its value (callback with None)
            await wait_until(lambda: None in got_a)
            assert alloc_a.get_value() is None
            alloc_a.stop()
            alloc_z.stop()
            ca.stop()
            cz.stop()

        run(body())

    def test_no_steal_without_override(self):
        async def body():
            stores = make_store_mesh(["aaa", "zzz"])
            ca = KvStoreClient(stores["aaa"])
            cz = KvStoreClient(stores["zzz"])
            alloc_a = RangeAllocator(
                "aaa", "alloc:", ca, lambda v: None, min_backoff=0.001
            )
            alloc_a.start_allocator((5, 5), init_value=5)
            await wait_until(lambda: alloc_a.get_value() == 5)

            alloc_z = RangeAllocator(
                "zzz",
                "alloc:",
                cz,
                lambda v: None,
                min_backoff=0.001,
                max_backoff=0.02,
                override_owner=False,
            )
            alloc_z.start_allocator((5, 5), init_value=5)
            await asyncio.sleep(0.5)
            # zzz never steals; aaa keeps the value
            assert alloc_a.get_value() == 5
            assert alloc_z.get_value() is None
            assert alloc_z.is_range_consumed()
            alloc_a.stop()
            alloc_z.stop()
            ca.stop()
            cz.stop()

        run(body())


class TestGetNthPrefix:
    def test_v6_subprefixes(self):
        params = PrefixAllocationParams(IpPrefix("fc00:cafe::/56"), 64)
        assert params.range_size == 256
        assert get_nth_prefix(params, 0) == IpPrefix("fc00:cafe::/64")
        assert get_nth_prefix(params, 1) == IpPrefix("fc00:cafe:0:1::/64")
        assert get_nth_prefix(params, 255) == IpPrefix("fc00:cafe:0:ff::/64")

    def test_v4_subprefixes(self):
        params = PrefixAllocationParams(IpPrefix("10.0.0.0/16"), 24)
        assert params.range_size == 256
        assert get_nth_prefix(params, 0) == IpPrefix("10.0.0.0/24")
        assert get_nth_prefix(params, 17) == IpPrefix("10.0.17.0/24")

    def test_parse_encode_roundtrip(self):
        params = PrefixAllocationParams.parse("fc00:cafe::/56,64")
        assert params.seed_prefix == IpPrefix("fc00:cafe::/56")
        assert params.alloc_prefix_len == 64
        assert PrefixAllocationParams.parse(params.encode()) == params


class TestPrefixAllocator:
    def test_root_node_allocates_and_advertises_seed(self):
        async def body():
            stores = make_store_mesh(["root"])
            client = KvStoreClient(stores["root"])
            advertised = []
            alloc = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name="root",
                    mode=PrefixAllocationMode.DYNAMIC_ROOT_NODE,
                    params=PrefixAllocationParams(
                        IpPrefix("fc00:cafe::/56"), 64
                    ),
                ),
                client,
                on_advertise=advertised.append,
            )
            alloc.start()
            await wait_until(lambda: advertised)
            prefix = alloc.get_prefix()
            assert prefix is not None
            assert prefix.prefix_length == 64
            assert prefix.network.subnet_of(
                IpPrefix("fc00:cafe::/56").network
            )
            # seed advertised into kvstore for leaves
            seed = stores["root"].get_key(SEED_PREFIX_KEY)
            assert seed is not None
            assert seed.value == b"fc00:cafe::/56,64"
            alloc.stop()
            client.stop()

        run(body())

    def test_leaf_learns_params_from_kvstore(self):
        async def body():
            stores = make_store_mesh(["leaf"])
            client = KvStoreClient(stores["leaf"])
            advertised = []
            alloc = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name="leaf",
                    mode=PrefixAllocationMode.DYNAMIC_LEAF_NODE,
                ),
                client,
                on_advertise=advertised.append,
            )
            alloc.start()
            await asyncio.sleep(0.05)
            assert alloc.get_prefix() is None  # no params yet
            # seed arrives via kvstore (e.g. from a root node)
            client.set_key(SEED_PREFIX_KEY, b"10.1.0.0/16,24")
            await wait_until(lambda: advertised)
            assert alloc.get_prefix().prefix_length == 24
            alloc.stop()
            client.stop()

        run(body())

    def test_two_nodes_unique_prefixes(self):
        async def body():
            stores = make_store_mesh(["node-a", "node-b"])
            clients = {n: KvStoreClient(stores[n]) for n in stores}
            allocs = {}
            for n, c in clients.items():
                allocs[n] = PrefixAllocator(
                    PrefixAllocatorConfig(
                        node_name=n,
                        mode=PrefixAllocationMode.DYNAMIC_ROOT_NODE,
                        params=PrefixAllocationParams(
                            IpPrefix("fc00:cafe::/56"), 64
                        ),
                    ),
                    c,
                )
                allocs[n].start()
            await wait_until(
                lambda: all(
                    a.get_prefix() is not None for a in allocs.values()
                )
            )
            for _ in range(50):
                await asyncio.sleep(0.02)
                prefixes = [a.get_prefix() for a in allocs.values()]
                if None not in prefixes and len(set(prefixes)) == 2:
                    break
            prefixes = [a.get_prefix() for a in allocs.values()]
            assert len(set(prefixes)) == 2, prefixes
            for a in allocs.values():
                a.stop()
            for c in clients.values():
                c.stop()

        run(body())

    def test_static_mode(self):
        async def body():
            stores = make_store_mesh(["s1"])
            client = KvStoreClient(stores["s1"])
            advertised, withdrawn = [], []
            alloc = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name="s1", mode=PrefixAllocationMode.STATIC
                ),
                client,
                on_advertise=advertised.append,
                on_withdraw=withdrawn.append,
            )
            alloc.start()
            client.set_key(
                STATIC_ALLOC_KEY,
                serializer.dumps({"s1": "10.5.0.0/24", "s2": "10.5.1.0/24"}),
            )
            await wait_until(lambda: advertised)
            assert alloc.get_prefix() == IpPrefix("10.5.0.0/24")
            # removal from the static map withdraws
            client.set_key(
                STATIC_ALLOC_KEY, serializer.dumps({"s2": "10.5.1.0/24"})
            )
            await wait_until(lambda: withdrawn)
            assert alloc.get_prefix() is None
            alloc.stop()
            client.stop()

        run(body())

    def test_persisted_index_reused_after_restart(self, tmp_path):
        async def body():
            config_store = PersistentStore(str(tmp_path / "cs.bin"))
            stores = make_store_mesh(["n1"])
            client = KvStoreClient(stores["n1"])
            params = PrefixAllocationParams(IpPrefix("10.0.0.0/16"), 24)
            alloc = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name="n1",
                    mode=PrefixAllocationMode.DYNAMIC_ROOT_NODE,
                    params=params,
                ),
                client,
                config_store=config_store,
            )
            alloc.start()
            await wait_until(lambda: alloc.get_prefix() is not None)
            first = alloc.get_prefix()
            alloc.stop()
            client.stop()
            config_store.flush()

            # "restart": fresh kvstore, same config store
            stores2 = make_store_mesh(["n1"])
            client2 = KvStoreClient(stores2["n1"])
            alloc2 = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name="n1",
                    mode=PrefixAllocationMode.DYNAMIC_ROOT_NODE,
                    params=params,
                ),
                client2,
                config_store=PersistentStore(str(tmp_path / "cs.bin")),
            )
            alloc2.start()
            await wait_until(lambda: alloc2.get_prefix() is not None)
            assert alloc2.get_prefix() == first
            alloc2.stop()
            client2.stop()

        run(body())
