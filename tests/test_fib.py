"""Fib module tests, mirroring openr/fib/tests/FibTest.cpp scenarios:
programming deltas against a mock agent, doNotInstall filtering, sync-on-
failure with backoff, agent-restart detection via aliveSince, interface-down
ECMP shrink/restore, longest-prefix-match filtered getters."""

import asyncio

import pytest

from openr_tpu.fib import (
    Fib,
    FibConfig,
    get_best_nexthops_mpls,
    get_best_nexthops_unicast,
    longest_prefix_match,
)
from openr_tpu.messaging import ReplicateQueue, RWQueue
from openr_tpu.platform import FIB_CLIENT_OPENR, MockFibHandler
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.solver.routes import RibMplsEntry, RibUnicastEntry
from openr_tpu.types import (
    InterfaceDatabase,
    InterfaceInfo,
    IpPrefix,
    MplsAction,
    MplsActionCode,
    NextHop,
    PerfEvents,
    UnicastRoute,
)


def run(coro, timeout=10.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def nh(addr, iface=None, metric=0, weight=0, label=None):
    action = None
    if label is not None:
        action = MplsAction(MplsActionCode.SWAP, swap_label=label)
    return NextHop(
        address=addr, iface=iface, metric=metric, weight=weight,
        mpls_action=action,
    )


def unicast_entry(prefix, *nexthops, do_not_install=False):
    return RibUnicastEntry(
        prefix=IpPrefix(prefix),
        nexthops=set(nexthops),
        do_not_install=do_not_install,
    )


def mpls_entry(label, *nexthops):
    return RibMplsEntry(label=label, nexthops=set(nexthops))


def make_fib(handler=None, **cfg_kw):
    handler = handler or MockFibHandler()
    route_q = RWQueue()
    if_q = RWQueue()
    cfg_kw.setdefault("cold_start_duration", 0.0)
    cfg = FibConfig(my_node_name="node-1", **cfg_kw)
    fib = Fib(cfg, handler, route_q, if_q)
    return fib, handler, route_q, if_q


async def wait_until(predicate, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        assert asyncio.get_event_loop().time() < deadline, "timed out"
        await asyncio.sleep(0.01)


class TestProgramming:
    def test_initial_sync_then_delta(self):
        async def body():
            fib, handler, route_q, _ = make_fib()
            fib.start()
            # initial full sync happens (empty routes)
            await handler.wait_for_sync_fib()
            assert fib.has_synced_fib

            delta = DecisionRouteUpdate(
                unicast_routes_to_update=[
                    unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0")),
                    unicast_entry("10.0.1.0/24", nh("fe80::2", "eth1")),
                ]
            )
            route_q.push(delta)
            await wait_until(
                lambda: len(handler.unicast_routes.get(FIB_CLIENT_OPENR, {}))
                == 2
            )
            assert handler.counters["add_unicast_routes"] == 1

            # incremental delete
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_delete=[IpPrefix("10.0.1.0/24")]
                )
            )
            await wait_until(
                lambda: len(handler.unicast_routes[FIB_CLIENT_OPENR]) == 1
            )
            fib.stop()

        run(body())

    def test_do_not_install_filtered(self):
        async def body():
            fib, handler, route_q, _ = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry(
                            "10.1.0.0/24",
                            nh("fe80::1", "eth0"),
                            do_not_install=True,
                        ),
                        unicast_entry("10.2.0.0/24", nh("fe80::1", "eth0")),
                    ]
                )
            )
            await wait_until(
                lambda: IpPrefix("10.2.0.0/24")
                in handler.unicast_routes.get(FIB_CLIENT_OPENR, {})
            )
            assert (
                IpPrefix("10.1.0.0/24")
                not in handler.unicast_routes[FIB_CLIENT_OPENR]
            )
            assert IpPrefix("10.1.0.0/24") not in fib.route_state.unicast_routes
            fib.stop()

        run(body())

    def test_mpls_routes_programmed_with_segment_routing(self):
        async def body():
            fib, handler, route_q, _ = make_fib(enable_segment_routing=True)
            fib.start()
            await handler.wait_for_sync_fib()
            await handler.wait_for_sync_mpls_fib()
            route_q.push(
                DecisionRouteUpdate(
                    mpls_routes_to_update=[
                        mpls_entry(100, nh("fe80::1", "eth0", label=101))
                    ]
                )
            )
            await wait_until(
                lambda: 100 in handler.mpls_routes.get(FIB_CLIENT_OPENR, {})
            )
            route_q.push(DecisionRouteUpdate(mpls_routes_to_delete=[100]))
            await wait_until(
                lambda: 100 not in handler.mpls_routes[FIB_CLIENT_OPENR]
            )
            fib.stop()

        run(body())

    def test_mpls_ignored_without_segment_routing(self):
        async def body():
            fib, handler, route_q, _ = make_fib(enable_segment_routing=False)
            fib.start()
            await handler.wait_for_sync_fib()
            route_q.push(
                DecisionRouteUpdate(
                    mpls_routes_to_update=[
                        mpls_entry(100, nh("fe80::1", "eth0", label=101))
                    ]
                )
            )
            await asyncio.sleep(0.05)
            assert 100 not in handler.mpls_routes.get(FIB_CLIENT_OPENR, {})
            # still cached locally for getters
            assert 100 in fib.route_state.mpls_routes
            fib.stop()

        run(body())


class TestFailureRecovery:
    def test_programming_failure_triggers_full_sync(self):
        async def body():
            fib, handler, route_q, _ = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            handler.fail_next(1)  # fail the incremental add
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0"))
                    ]
                )
            )
            # recovery full sync must land the route
            await handler.wait_for_sync_fib()
            assert (
                IpPrefix("10.0.0.0/24")
                in handler.unicast_routes[FIB_CLIENT_OPENR]
            )
            assert not fib.route_state.dirty_route_db
            assert fib.counters["fib.thrift.failure.add_del_route"] == 1
            fib.stop()

        run(body())

    def test_sync_failure_retries_with_backoff(self):
        async def body():
            fib, handler, route_q, _ = make_fib()
            handler.set_unhealthy(True)
            fib.start()
            await asyncio.sleep(0.05)
            assert not fib.has_synced_fib
            assert fib.counters.get("fib.thrift.failure.sync_fib", 0) >= 1
            handler.set_unhealthy(False)
            await handler.wait_for_sync_fib()
            assert fib.has_synced_fib
            fib.stop()

        run(body())

    def test_agent_restart_detected_by_alive_since(self):
        async def body():
            fib, handler, route_q, _ = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0"))
                    ]
                )
            )
            await wait_until(
                lambda: handler.unicast_routes.get(FIB_CLIENT_OPENR)
            )
            await fib.keep_alive_check()  # records aliveSince
            handler.restart()  # wipes agent state
            assert not handler.unicast_routes.get(FIB_CLIENT_OPENR)
            await fib.keep_alive_check()  # detects the restart
            await handler.wait_for_sync_fib()
            assert (
                IpPrefix("10.0.0.0/24")
                in handler.unicast_routes[FIB_CLIENT_OPENR]
            )
            fib.stop()

        run(body())


class TestInterfaceEvents:
    def test_interface_down_shrinks_and_restores_ecmp(self):
        async def body():
            fib, handler, route_q, if_q = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            # both interfaces up
            if_q.push(
                InterfaceDatabase(
                    "node-1",
                    {
                        "eth0": InterfaceInfo(is_up=True),
                        "eth1": InterfaceInfo(is_up=True),
                    },
                )
            )
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry(
                            "10.0.0.0/24",
                            nh("fe80::1", "eth0"),
                            nh("fe80::2", "eth1"),
                        )
                    ]
                )
            )
            await wait_until(
                lambda: handler.unicast_routes.get(FIB_CLIENT_OPENR)
            )

            # eth0 down → group shrinks to eth1 only
            if_q.push(
                InterfaceDatabase(
                    "node-1", {"eth0": InterfaceInfo(is_up=False)}
                )
            )
            await wait_until(
                lambda: len(
                    handler.unicast_routes[FIB_CLIENT_OPENR][
                        IpPrefix("10.0.0.0/24")
                    ].nexthops
                )
                == 1
            )
            route = handler.unicast_routes[FIB_CLIENT_OPENR][
                IpPrefix("10.0.0.0/24")
            ]
            assert route.nexthops[0].iface == "eth1"
            assert IpPrefix("10.0.0.0/24") in fib.route_state.dirty_prefixes

            # eth0 back up → full group restored
            if_q.push(
                InterfaceDatabase(
                    "node-1", {"eth0": InterfaceInfo(is_up=True)}
                )
            )
            await wait_until(
                lambda: len(
                    handler.unicast_routes[FIB_CLIENT_OPENR][
                        IpPrefix("10.0.0.0/24")
                    ].nexthops
                )
                == 2
            )
            assert (
                IpPrefix("10.0.0.0/24") not in fib.route_state.dirty_prefixes
            )
            fib.stop()

        run(body())

    def test_all_interfaces_down_deletes_route(self):
        async def body():
            fib, handler, route_q, if_q = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            if_q.push(
                InterfaceDatabase(
                    "node-1", {"eth0": InterfaceInfo(is_up=True)}
                )
            )
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0"))
                    ]
                )
            )
            await wait_until(
                lambda: handler.unicast_routes.get(FIB_CLIENT_OPENR)
            )
            if_q.push(
                InterfaceDatabase(
                    "node-1", {"eth0": InterfaceInfo(is_up=False)}
                )
            )
            await wait_until(
                lambda: IpPrefix("10.0.0.0/24")
                not in handler.unicast_routes[FIB_CLIENT_OPENR]
            )
            # route survives in local cache for restore
            assert IpPrefix("10.0.0.0/24") in fib.route_state.unicast_routes
            fib.stop()

        run(body())


class TestHelpers:
    def test_best_nexthops_unicast_min_metric(self):
        hops = [
            nh("fe80::1", "eth0", metric=10),
            nh("fe80::2", "eth1", metric=20),
            nh("fe80::3", "eth2", metric=10),
        ]
        best = get_best_nexthops_unicast(hops)
        assert {h.address for h in best} == {"fe80::1", "fe80::3"}

    def test_best_nexthops_unicast_keeps_non_shortest(self):
        hops = [
            nh("fe80::1", "eth0", metric=10),
            NextHop(
                address="fe80::2",
                iface="eth1",
                metric=20,
                use_non_shortest_route=True,
            ),
        ]
        best = get_best_nexthops_unicast(hops)
        assert len(best) == 2

    def test_best_nexthops_mpls_prefers_php(self):
        php = NextHop(
            address="fe80::1",
            iface="eth0",
            metric=10,
            mpls_action=MplsAction(MplsActionCode.PHP),
        )
        swap = nh("fe80::2", "eth1", metric=10, label=99)
        best = get_best_nexthops_mpls([php, swap])
        assert best == [php]

    def test_longest_prefix_match(self):
        routes = {
            IpPrefix(p): UnicastRoute(IpPrefix(p), ())
            for p in ["10.0.0.0/8", "10.1.0.0/16", "10.1.1.0/24"]
        }
        assert longest_prefix_match("10.1.1.5", routes) == IpPrefix(
            "10.1.1.0/24"
        )
        assert longest_prefix_match("10.2.0.1", routes) == IpPrefix(
            "10.0.0.0/8"
        )
        assert longest_prefix_match("10.1.0.0/16", routes) == IpPrefix(
            "10.1.0.0/16"
        )
        assert longest_prefix_match("192.168.0.1", routes) is None

    def test_get_unicast_routes_filtered(self):
        async def body():
            fib, handler, route_q, _ = make_fib(dryrun=True)
            await fib.process_route_updates(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/8", nh("fe80::1", "eth0")),
                        unicast_entry("10.1.0.0/16", nh("fe80::1", "eth0")),
                        unicast_entry("20.0.0.0/8", nh("fe80::2", "eth1")),
                    ]
                )
            )
            assert len(fib.get_unicast_routes()) == 3
            filtered = fib.get_unicast_routes(["10.1.2.3"])
            assert [r.dest for r in filtered] == [IpPrefix("10.1.0.0/16")]

        run(body())

    def test_perf_events_convergence_recorded(self):
        async def body():
            fib, handler, route_q, _ = make_fib(dryrun=True)
            perf = PerfEvents()
            perf.add("node-0", "DECISION_RECEIVED")
            await fib.process_route_updates(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0"))
                    ],
                    perf_events=perf,
                )
            )
            assert len(fib.get_perf_db()) == 1
            events = fib.get_perf_db()[0].events
            assert events[-1].event_descr == "OPENR_FIB_ROUTES_PROGRAMMED"
            assert any(
                e.event_descr == "FIB_ROUTE_DB_RECVD" for e in events
            )

        run(body())


class TestKeepAliveFaultInjection:
    """Agent-restart detection driven by the deterministic fault injector
    (openr_tpu/testing/faults.py): the injector kills/restarts the stub
    FibService agent exactly when keepAliveCheck polls it, and the module
    must detect the restart, run a full resync, and recover."""

    def test_injected_agent_restart_triggers_full_resync(self):
        from openr_tpu.testing.faults import injected

        async def body():
            fib, handler, route_q, _ = make_fib()
            fib.start()
            await handler.wait_for_sync_fib()
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        unicast_entry("10.0.0.0/24", nh("fe80::1", "eth0")),
                        unicast_entry("10.0.1.0/24", nh("fe80::2", "eth1")),
                    ]
                )
            )
            await wait_until(
                lambda: len(handler.unicast_routes.get(FIB_CLIENT_OPENR, {}))
                == 2
            )
            await fib.keep_alive_check()  # baseline aliveSince recorded

            with injected() as inj:
                # the agent dies and comes back empty right as the next
                # keep-alive poll observes it
                inj.arm(
                    "fib.keepalive",
                    times=1,
                    action=lambda _fib: handler.restart(),
                )
                # and the first post-restart full-sync attempt fails too,
                # so recovery must ride the (jittered) backoff retry path
                inj.arm("fib.sync", times=1)
                await fib.keep_alive_check()
                assert inj.fired("fib.keepalive") == 1
                assert handler.unicast_routes.get(FIB_CLIENT_OPENR, {}) == {}
                assert fib.route_state.dirty_route_db

                # restart detected → full resync repopulates the agent
                await wait_until(
                    lambda: len(
                        handler.unicast_routes.get(FIB_CLIENT_OPENR, {})
                    )
                    == 2
                    and not fib.route_state.dirty_route_db
                )
                assert inj.fired("fib.sync") == 1
            assert fib.has_synced_fib
            assert fib.counters["fib.thrift.failure.sync_fib"] == 1
            assert fib.counters["fib.sync_fib_calls"] >= 2
            # a later keep-alive with a stable agent schedules nothing new
            synced_before = fib.counters["fib.sync_fib_calls"]
            await fib.keep_alive_check()
            await asyncio.sleep(0.05)
            assert fib.counters["fib.sync_fib_calls"] == synced_before
            fib.stop()

        run(body())

    def test_injected_keepalive_error_counts_and_loop_survives(self):
        from openr_tpu.testing.faults import FaultInjected, injected

        async def body():
            fib, handler, route_q, _ = make_fib(keep_alive_interval=0.01)
            fib.start()
            await handler.wait_for_sync_fib()
            with injected() as inj:
                inj.arm("fib.keepalive", times=2)
                await wait_until(lambda: inj.fired("fib.keepalive") == 2)
                await wait_until(
                    lambda: fib.counters.get("fib.thrift.failure.keepalive")
                    == 2
                )
            # the poll loop survived the injected failures and still
            # detects a later real restart
            handler.restart()
            await wait_until(
                lambda: getattr(fib, "_latest_alive_since", None)
                == handler._alive_since
            )
            fib.stop()

        run(body())
