"""MEM_SMOKE (tier-1 acceptance): the device-memory observatory catches
an injected ledger leak — clean flap fires nothing, one `solver.mem.retain`
pin raises exactly one attributed `device_memory` breach with well-formed
ledger forensics and a `breeze decision memory` round-trip (the breeze
assertions live inside run_mem_smoke, against the victim's live ctrl
port)."""

from openr_tpu.monitor.mem_smoke import run_mem_smoke


class TestMemSmoke:
    def test_mem_smoke(self):
        summary = run_mem_smoke()
        # the acceptance assertions live inside run_mem_smoke; pin the
        # headline evidence here too
        assert summary["clean_findings"] == 0
        assert summary["faults_fired"] == 1
        assert len(summary["findings"]) == 1
        finding = summary["findings"][0]
        assert finding["kind"] == "device_memory"
        # the ledger is pool-global, so the elected reporter node is
        # scrape-timing dependent — membership is the contract
        assert finding["node"] in {f"n{i}" for i in range(summary["nodes"])}
        assert finding["attribution"], finding
        assert summary["forensics"][0]["id"] == finding["forensics_id"]
        # the injected pin is visible end-to-end: ledger totals count it
        # and the leaked structure survives daemon teardown
        assert summary["leaked_structure"] is not None
        assert summary["ledger"]["totals"]["retained"] >= 1
        assert summary["breeze"]["exact"]
