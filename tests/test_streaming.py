"""Streaming control plane tests (docs/Streaming.md): delta
subscriptions, bounded fan-out with coalesce/overflow→resync semantics,
slow-client isolation, and admission control for expensive ctrl RPCs.

The concurrent-client regression suite at the bottom pins the ISSUE 11
acceptance criteria: a flap sequence delivered to >= 64 concurrent
subscribers (one deliberately stalled) programs Fib with convergence e2e
p95 within noise of the zero-subscriber baseline, the stalled subscriber
recovers via marked snapshot-resync with state equal to a fresh dump,
and an injected slow runTeOptimize is rejected/queued by admission
control without delaying route programming.
"""

import asyncio
import time

import pytest

from openr_tpu.ctrl import CtrlClient, CtrlServer
from openr_tpu.ctrl.client import CtrlError
from openr_tpu.kvstore import InProcessTransport, KvStore
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.solver.routes import RibUnicastEntry
from openr_tpu.streaming import (
    AdmissionConfig,
    AdmissionController,
    ServerBusyError,
    StreamConfig,
    StreamManager,
)
from openr_tpu.testing.faults import FaultInjector, injected
from openr_tpu.types import IpPrefix, NextHop, Publication, Value


def run(coro, timeout=60.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def _value(originator: str, version: int = 1, value: bytes = b"x") -> Value:
    return Value(
        version=version, originator_id=originator, value=value, ttl=600000
    )


def _pub(keys: dict, expired=(), area: str = "0") -> Publication:
    return Publication(
        key_vals=dict(keys), expired_keys=list(expired), area=area
    )


# ---------------------------------------------------------------------------
# subscription-layer units: coalescing, overflow -> resync
# ---------------------------------------------------------------------------


class TestKvSubscription:
    def make_sub(self, max_pending=2, budget=4, **kw):
        mgr = StreamManager(
            config=StreamConfig(
                subscriber_max_pending=max_pending,
                coalesce_budget=budget,
            )
        )
        return mgr, mgr.add_kvstore_subscriber(area="0", **kw)

    def test_filters(self):
        mgr, sub = self.make_sub(
            prefixes=["adj:"], originators={"n1"}, max_pending=16
        )
        sub.offer(_pub({"prefix:n1": _value("n1")}), 0.0)  # prefix filter
        sub.offer(_pub({"adj:n2": _value("n2")}), 0.0)  # originator filter
        sub.offer(_pub({"adj:n1": _value("n1")}, area="0"), 0.0)  # match
        sub.offer(
            Publication(key_vals={"adj:n1": _value("n1")}, area="1"), 0.0
        )  # wrong area
        assert len(sub._frames) == 1
        kind, pub, _ = run(sub.next_frame())
        assert kind == "delta" and list(pub.key_vals) == ["adj:n1"]

    def test_coalesce_merges_per_key(self):
        mgr, sub = self.make_sub(max_pending=2, budget=10)
        sub.offer(_pub({"a": _value("n", 1)}), 1.0)
        sub.offer(_pub({"a": _value("n", 2)}), 2.0)
        sub.offer(_pub({"b": _value("n", 1)}, expired=["a"]), 3.0)
        # 3 frames > max_pending 2: coalesced to one merged frame; the
        # later expiry of "a" cancels its pending updates
        assert len(sub._frames) == 1
        kind, merged, t0 = run(sub.next_frame())
        assert kind == "delta"
        assert t0 == 1.0  # oldest enqueue stamp survives coalescing
        assert list(merged.key_vals) == ["b"]
        assert merged.expired_keys == ["a"]
        assert sub.coalesces == 1

    def test_update_after_expiry_cancels_expiry(self):
        mgr, sub = self.make_sub(max_pending=1, budget=10)
        sub.offer(_pub({}, expired=["a"]), 1.0)
        sub.offer(_pub({"a": _value("n", 5)}), 2.0)
        kind, merged, _ = run(sub.next_frame())
        assert kind == "delta"
        assert merged.key_vals["a"].version == 5
        assert merged.expired_keys == []

    def test_overflow_forces_marked_resync(self):
        mgr, sub = self.make_sub(max_pending=1, budget=2)
        for i in range(4):
            sub.offer(_pub({f"k{i}": _value("n")}), float(i))
        # merged delta spans >2 keys -> queue dropped, resync flagged
        assert sub.resyncs == 1
        assert mgr.counters["ctrl.stream.resyncs"] == 1
        kind, payload, t0 = run(sub.next_frame())
        assert kind == "resync" and payload is None
        # deltas offered while a resync is pending are dropped (the
        # snapshot the handler takes will already contain them)
        sub.offer(_pub({"late": _value("n")}), 9.0)
        kind2, pub2, _ = run(sub.next_frame())
        assert kind2 == "resync" or pub2 is None or "late" in pub2.key_vals

    def test_publish_fault_degrades_to_resync(self):
        """An injected fan-out failure becomes a marked resync on every
        subscriber — never silent loss (ctrl.stream.publish seam)."""
        updates = ReplicateQueue()
        mgr = StreamManager(kvstore_updates=updates)

        async def body():
            mgr.start()
            sub = mgr.add_kvstore_subscriber(area="0")
            with injected(FaultInjector()) as inj:
                inj.arm("ctrl.stream.publish", times=1)
                updates.push(_pub({"a": _value("n")}))
                kind, _, _ = await sub.next_frame()
                assert kind == "resync"
                assert inj.fired("ctrl.stream.publish") == 1
            assert mgr.counters["ctrl.stream.publish_errors"] == 1
            mgr.stop()

        run(body())


class TestRouteSubscription:
    def entry(self, prefix: str, metric: int = 10) -> RibUnicastEntry:
        return RibUnicastEntry(
            prefix=IpPrefix(prefix),
            nexthops={NextHop(address="fe80::1", iface="if0", metric=metric)},
        )

    def test_coalesce_latest_wins_and_delete_overrides(self):
        mgr = StreamManager(
            config=StreamConfig(subscriber_max_pending=1, coalesce_budget=10)
        )
        sub = mgr.add_route_subscriber()
        sub.offer(
            DecisionRouteUpdate(
                unicast_routes_to_update=[self.entry("10.0.0.0/24", 10)]
            ),
            1.0,
        )
        sub.offer(
            DecisionRouteUpdate(
                unicast_routes_to_update=[self.entry("10.0.0.0/24", 20)],
                unicast_routes_to_delete=[IpPrefix("10.1.0.0/24")],
            ),
            2.0,
        )
        kind, merged, t0 = run(sub.next_frame())
        assert kind == "delta" and t0 == 1.0
        assert len(merged.unicast_routes_to_update) == 1
        (entry,) = merged.unicast_routes_to_update
        assert next(iter(entry.nexthops)).metric == 20
        assert merged.unicast_routes_to_delete == [IpPrefix("10.1.0.0/24")]

    def test_route_overflow_resync(self):
        mgr = StreamManager(
            config=StreamConfig(subscriber_max_pending=1, coalesce_budget=2)
        )
        sub = mgr.add_route_subscriber()
        for i in range(4):
            sub.offer(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[self.entry(f"10.{i}.0.0/24")]
                ),
                float(i),
            )
        kind, _, _ = run(sub.next_frame())
        assert kind == "resync"
        assert sub.resyncs == 1


# ---------------------------------------------------------------------------
# admission controller units
# ---------------------------------------------------------------------------


class TestAdmission:
    def make(self, **kw) -> AdmissionController:
        defaults = dict(
            capacity=2, max_wait_s=0.5, max_queue=4, max_queue_per_client=2
        )
        defaults.update(kw)
        return AdmissionController(AdmissionConfig(**defaults))

    def test_concurrency_cap(self):
        adm = self.make(capacity=2)
        high_water = {"now": 0, "max": 0}

        async def job(i):
            async def work():
                high_water["now"] += 1
                high_water["max"] = max(
                    high_water["max"], high_water["now"]
                )
                await asyncio.sleep(0.02)
                high_water["now"] -= 1
                return True

            return await adm.run("getRouteDbComputed", f"c{i}", work)

        async def body():
            results = await asyncio.gather(*(job(i) for i in range(6)))
            assert all(results)

        run(body())
        # getRouteDbComputed cost 1, capacity 2 -> never more than 2
        assert high_water["max"] <= 2
        assert adm.counters["ctrl.admission.admitted"] == 6

    def test_te_cost_serializes(self):
        adm = self.make(capacity=2)
        running = {"n": 0, "max": 0}

        async def job():
            async def work():
                running["n"] += 1
                running["max"] = max(running["max"], running["n"])
                await asyncio.sleep(0.02)
                running["n"] -= 1

            await adm.run("runTeOptimize", "c", work)

        async def body():
            await asyncio.gather(*(job() for _ in range(3)))

        run(body())
        assert running["max"] == 1  # cost 2 on capacity 2: one at a time

    def test_bounded_wait_timeout(self):
        adm = self.make(capacity=2, max_wait_s=0.05)

        async def body():
            started = asyncio.Event()

            async def slow():
                started.set()
                await asyncio.sleep(0.5)

            holder = asyncio.ensure_future(
                adm.run("runTeOptimize", "a", slow)
            )
            await started.wait()
            with pytest.raises(ServerBusyError) as exc:
                await adm.run("runTeOptimize", "b", lambda: 1)
            assert exc.value.retry_after_ms > 0
            await holder

        run(body())
        assert adm.counters["ctrl.admission.timeouts"] == 1

    def test_queue_full_and_client_cap_reject(self):
        adm = self.make(
            capacity=2, max_wait_s=2.0, max_queue=2, max_queue_per_client=1
        )

        async def body():
            release = asyncio.Event()
            started = asyncio.Event()

            async def blocker():
                started.set()
                await release.wait()

            holder = asyncio.ensure_future(
                adm.run("runTeOptimize", "h", blocker)
            )
            await started.wait()
            waiters = [
                asyncio.ensure_future(
                    adm.run("runTeOptimize", f"c{i}", lambda: i)
                )
                for i in range(2)
            ]
            await asyncio.sleep(0.01)
            # queue (2) is full -> typed rejection
            with pytest.raises(ServerBusyError):
                await adm.run("runTeOptimize", "c9", lambda: 9)
            # per-client cap: c0 already has one queued
            with pytest.raises(ServerBusyError):
                await adm.run("runTeOptimize", "c0", lambda: 0)
            release.set()
            await asyncio.gather(*waiters)
            await holder

        run(body())
        assert adm.counters["ctrl.admission.rejected_queue_full"] == 1
        assert adm.counters["ctrl.admission.rejected_client_cap"] == 1

    def test_round_robin_fairness(self):
        """A heavy client's queued burst cannot starve another client:
        grants rotate across client queues."""
        adm = self.make(
            capacity=2, max_wait_s=5.0, max_queue=8, max_queue_per_client=8
        )
        order = []

        async def body():
            release = asyncio.Event()
            started = asyncio.Event()

            async def blocker():
                started.set()
                await release.wait()

            holder = asyncio.ensure_future(
                adm.run("runTeOptimize", "heavy", blocker)
            )
            await started.wait()

            def work(tag):
                async def inner():
                    order.append(tag)
                    return tag

                return inner

            tasks = [
                asyncio.ensure_future(
                    adm.run("runTeOptimize", "heavy", work(f"heavy{i}"))
                )
                for i in range(3)
            ]
            await asyncio.sleep(0.01)
            tasks.append(
                asyncio.ensure_future(
                    adm.run("runTeOptimize", "light", work("light0"))
                )
            )
            await asyncio.sleep(0.01)
            release.set()
            await asyncio.gather(*tasks)
            await holder

        run(body())
        # the light client's single request is served before the heavy
        # client's 2nd/3rd queued requests (round-robin grant order)
        assert order.index("light0") < order.index("heavy1"), order

    def test_sync_fn_and_exceptions_release_slot(self):
        adm = self.make(capacity=2)

        async def body():
            assert await adm.run("getRouteDbComputed", "c", lambda: 41) == 41
            with pytest.raises(ValueError):
                await adm.run(
                    "getRouteDbComputed",
                    "c",
                    lambda: (_ for _ in ()).throw(ValueError("boom")),
                )
            # slot released despite the exception
            assert await adm.run("getRouteDbComputed", "c", lambda: 42) == 42

        run(body())
        assert adm.counters["ctrl.admission.in_flight_last"] == 0


# ---------------------------------------------------------------------------
# wire-level: ctrl server streaming + typed errors
# ---------------------------------------------------------------------------


def _apply_kv_frame(state: dict, frame: dict) -> None:
    """Client-side frame application: snapshot/resync replace, deltas
    merge per key (the documented consumption contract)."""
    pub = frame["pub"]
    if frame["type"] in ("snapshot", "resync"):
        state.clear()
    for key in pub["expired_keys"]:
        state.pop(key, None)
    for key, value in pub["key_vals"].items():
        state[key] = (value["version"], value["value"])


class TestWire:
    def test_snapshot_then_delta_and_stats(self):
        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            store.db("0").set_key_vals({"adj:n1": _value("n1")})
            server = CtrlServer("n1", port=0, kvstore=store)
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            frames = []

            async def consume():
                async for frame in client.subscribe(
                    "subscribeKvStore", area="0", client="t1"
                ):
                    frames.append(frame)
                    if len(frames) >= 2:
                        return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.05)
            store.db("0").set_key_vals({"prefix:n2": _value("n2")})
            await asyncio.wait_for(task, 10)
            assert frames[0]["type"] == "snapshot" and frames[0]["seq"] == 0
            assert "adj:n1" in frames[0]["pub"]["key_vals"]
            assert frames[1]["type"] == "delta" and frames[1]["seq"] == 1
            assert "prefix:n2" in frames[1]["pub"]["key_vals"]

            stats = await (
                await CtrlClient("127.0.0.1", port).connect()
            ).call("getStreamStats")
            assert stats["stream"]["kv_subscribers"] == 1
            assert stats["stream"]["counters"]["ctrl.stream.delivered"] >= 1
            assert stats["admission"]["capacity"] > 0
            # encode attribution (ISSUE 13 satellite): every delivered
            # frame's per-subscriber JSON re-encode is measured, so the
            # ROADMAP's shared-encoding serving-wall hypothesis has
            # numbers before anyone builds the fast path
            delivered = stats["stream"]["counters"]["ctrl.stream.delivered"]
            assert (
                stats["stream"]["counters"]["ctrl.stream.encode_bytes"] > 0
            )
            encode_hist = server.stream_manager.histograms[
                "ctrl.stream.encode_ms"
            ]
            # snapshot + delta both encode; delivered counts deltas only
            assert encode_hist.count >= delivered + 1 >= 2
            await client.close()
            await server.stop()
            store.stop()

        run(body())

    def test_subscriber_limit_typed_rejection(self):
        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            manager = StreamManager(
                kvstore_updates=store.updates_queue,
                config=StreamConfig(max_subscribers=1),
            )
            manager.start()
            server = CtrlServer(
                "n1", port=0, kvstore=store, stream_manager=manager
            )
            port = await server.start()
            c1 = await CtrlClient("127.0.0.1", port).connect()
            got_snapshot = asyncio.Event()

            async def consume():
                async for _ in c1.subscribe("subscribeKvStore", area="0"):
                    got_snapshot.set()

            task = asyncio.ensure_future(consume())
            await got_snapshot.wait()
            c2 = await CtrlClient("127.0.0.1", port).connect()
            with pytest.raises(CtrlError) as exc:
                async for _ in c2.subscribe("subscribeKvStore", area="0"):
                    pass
            assert exc.value.server_busy
            assert exc.value.retry_after_ms > 0
            task.cancel()
            await c1.close()
            await c2.close()
            manager.stop()
            await server.stop()
            store.stop()

        run(body())

    def test_overflow_resync_state_equals_fresh_dump(self):
        """The acceptance invariant at the wire level: a subscriber
        throttled through queue overflow receives a marked resync and
        ends bit-identical to a fresh dump."""

        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            manager = StreamManager(
                kvstore_updates=store.updates_queue,
                config=StreamConfig(
                    subscriber_max_pending=1, coalesce_budget=2
                ),
            )
            manager.start()
            server = CtrlServer(
                "n1", port=0, kvstore=store, stream_manager=manager
            )
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            state: dict = {}
            kinds = []

            async def consume():
                async for frame in client.subscribe(
                    "subscribeKvStore", area="0", client="stalled"
                ):
                    kinds.append(frame["type"])
                    _apply_kv_frame(state, frame)

            with injected(FaultInjector()) as inj:
                inj.arm(
                    "ctrl.stream.deliver",
                    times=None,
                    action=lambda sub: setattr(sub, "throttle_s", 0.05),
                    when=lambda sub: getattr(sub, "label", "") == "stalled",
                )
                task = asyncio.ensure_future(consume())
                await asyncio.sleep(0.05)
                # a burst far past the budget while delivery crawls
                for i in range(30):
                    store.db("0").set_key_vals(
                        {f"adj:k{i}": _value("n1", version=i + 1)}
                    )
                    await asyncio.sleep(0.01)
                # let the stream quiesce, then stop throttling
                await asyncio.sleep(1.0)
                inj.disarm("ctrl.stream.deliver")
                await asyncio.sleep(0.5)

            assert "resync" in kinds, kinds
            dump = await (
                await CtrlClient("127.0.0.1", port).connect()
            ).call("getKvStoreKeyValsFiltered", area="0", prefixes=[])
            expect = {
                k: (v["version"], v["value"])
                for k, v in dump["key_vals"].items()
            }
            assert state == expect
            stats = manager.stats()["counters"]
            assert stats["ctrl.stream.resyncs"] >= 1
            assert stats["ctrl.stream.coalesced"] >= 1
            task.cancel()
            await client.close()
            manager.stop()
            await server.stop()
            store.stop()

        run(body())

    def test_legacy_snoop_rides_fanout(self):
        """subscribeKvStoreFilter (breeze kvstore snoop) still speaks the
        bare-publication frame shape over the new fan-out."""

        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            store.db("0").set_key_vals({"adj:n1": _value("n1")})
            server = CtrlServer("n1", port=0, kvstore=store)
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            frames = []

            async def consume():
                async for frame in client.subscribe(
                    "subscribeKvStoreFilter", area="0", prefixes=["adj:"]
                ):
                    frames.append(frame)
                    if len(frames) >= 2:
                        return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.05)
            store.db("0").set_key_vals({"adj:n2": _value("n2")})
            store.db("0").set_key_vals({"prefix:n3": _value("n3")})
            await asyncio.wait_for(task, 10)
            assert "adj:n1" in frames[0]["key_vals"]  # bare publication
            assert "type" not in frames[0]
            assert list(frames[1]["key_vals"]) == ["adj:n2"]
            task.cancel()
            await client.close()
            await server.stop()
            store.stop()

        run(body())


# ---------------------------------------------------------------------------
# frame codecs: golden bytes, round-trips, negotiation + graceful fallback
# ---------------------------------------------------------------------------


class TestFrameCodec:
    def sample_pub(self) -> Publication:
        return Publication(
            key_vals={
                "adj:n1": Value(
                    version=3,
                    originator_id="n1",
                    value=b"\x00\xffraw",
                    ttl=600000,
                    ttl_version=2,
                    hash=-12345,
                ),
                "prefix:n2": Value(
                    version=1,
                    originator_id="n2",
                    value=None,
                    ttl=7,
                    ttl_version=0,
                    hash=None,
                ),
            },
            expired_keys=["gone:k"],
            area="0",
        )

    def test_binary_kv_body_golden(self):
        """The binary kv body layout is a wire contract: pin the exact
        bytes so an accidental struct/order change cannot slip through
        as a silent protocol break."""
        import struct

        from openr_tpu.streaming import codec as sc

        body = sc.encode_kv_body(self.sample_pub(), "binary")
        golden = b"".join(
            [
                struct.pack("!H", 1),
                b"0",  # area
                struct.pack("!I", 2),  # key count
                struct.pack("!H", 6),
                b"adj:n1",
                # flags=HAS_VALUE|HAS_HASH, version, ttl, ttl_version,
                # hash, value length
                struct.pack("!Bqqqqi", 3, 3, 600000, 2, -12345, 5),
                struct.pack("!H", 2),
                b"n1",
                b"\x00\xffraw",
                struct.pack("!H", 9),
                b"prefix:n2",
                struct.pack("!Bqqqqi", 0, 1, 7, 0, 0, 0),
                struct.pack("!H", 2),
                b"n2",
                struct.pack("!I", 1),  # expired count
                struct.pack("!H", 6),
                b"gone:k",
            ]
        )
        assert body == golden

    def test_binary_kv_body_roundtrip_matches_json_payload(self):
        """decode(encode(pub, binary)) is the EXACT JSON payload dict —
        consumers stay codec-agnostic, None-ness and b64 restored."""
        from openr_tpu.streaming import codec as sc

        pub = self.sample_pub()
        decoded = sc.decode_kv_body(sc.encode_kv_body(pub, "binary"))
        assert decoded == sc._pub_to_json(pub)
        # and the binary body is smaller than its JSON twin (raw bytes,
        # struct-packed ints — the codec's reason to exist)
        assert len(sc.encode_kv_body(pub, "binary")) < len(
            sc.encode_kv_body(pub, "json")
        )

    def test_binary_route_body_roundtrip(self):
        from openr_tpu.streaming import codec as sc

        update = DecisionRouteUpdate(
            unicast_routes_to_update=[
                RibUnicastEntry(
                    prefix=IpPrefix("10.0.0.0/24"),
                    nexthops={
                        NextHop(address="fe80::1", iface="if0", metric=10)
                    },
                )
            ],
            unicast_routes_to_delete=[IpPrefix("10.1.0.0/24")],
        )
        fields = sc.route_fields_from_update(update)
        decoded = sc.decode_route_body(
            sc.encode_route_body(fields, "binary")
        )
        assert decoded == fields

    def test_json_splice_bit_identical_to_dumps(self):
        """The shared-path envelope splice must be byte-identical to
        json.dumps of the whole frame: a shared and a privately encoded
        frame cannot be told apart on the wire."""
        import json

        from openr_tpu.streaming import codec as sc

        pub = self.sample_pub()
        body = sc.encode_kv_body(pub, "json")
        spliced = b"".join(
            sc.kv_frame_segments("json", 7, "delta", 42, "0", body)
        )
        whole = {
            "id": 7,
            "stream": {
                "type": "delta",
                "seq": 42,
                "area": "0",
                "pub": sc._pub_to_json(pub),
            },
        }
        assert spliced == json.dumps(whole).encode() + b"\n"
        # legacy (subscribeKvStoreFilter): bare publication frame
        legacy = b"".join(
            sc.kv_frame_segments(
                "json", 7, "delta", 42, "0", body, legacy=True
            )
        )
        assert (
            legacy
            == json.dumps(
                {"id": 7, "stream": sc._pub_to_json(pub)}
            ).encode()
            + b"\n"
        )

    def test_unknown_codec_normalizes_to_json(self):
        from openr_tpu.streaming import codec as sc

        assert sc.normalize_codec("binary") == "binary"
        assert sc.normalize_codec("json") == "json"
        assert sc.normalize_codec(None) == "json"
        assert sc.normalize_codec("zstd") == "json"

    def test_negotiation_binary_end_to_end_and_payload_equality(self):
        """One JSON and one binary subscriber on the same server: both
        must observe identical payload dicts for the snapshot AND the
        delta (bit-identical semantics across codecs), with the binary
        connection actually negotiated (ack consumed by the client)."""

        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            store.db("0").set_key_vals({"adj:n1": _value("n1")})
            server = CtrlServer("n1", port=0, kvstore=store)
            port = await server.start()
            got = {"json": [], "binary": []}

            async def consume(codec):
                client = await CtrlClient("127.0.0.1", port).connect()
                try:
                    async for frame in client.subscribe(
                        "subscribeKvStore",
                        area="0",
                        client=f"t-{codec}",
                        codec=codec,
                    ):
                        got[codec].append(frame)
                        if len(got[codec]) >= 2:
                            return
                finally:
                    await client.close()

            tasks = [
                asyncio.ensure_future(consume("json")),
                asyncio.ensure_future(consume("binary")),
            ]
            await asyncio.sleep(0.1)
            store.db("0").set_key_vals({"prefix:n2": _value("n2")})
            await asyncio.wait_for(asyncio.gather(*tasks), 10)
            await server.stop()
            store.stop()
            return got

        got = run(body())
        assert [f["type"] for f in got["json"]] == ["snapshot", "delta"]
        assert got["binary"] == got["json"]

    def test_binary_request_against_old_server_falls_back_to_json(self):
        """A server that predates the codec ignores the param and streams
        newline-JSON; the absent ack IS the fallback — the client must
        yield the JSON frames instead of misreading them as binary."""
        import json

        async def old_server(reader, writer):
            req = json.loads(await reader.readline())
            pub = {"area": "0", "key_vals": {}, "expired_keys": []}
            for seq, kind in enumerate(["snapshot", "delta"]):
                frame = {
                    "id": req["id"],
                    "stream": {
                        "type": kind,
                        "seq": seq,
                        "area": "0",
                        "pub": pub,
                    },
                }
                writer.write(json.dumps(frame).encode() + b"\n")
            await writer.drain()
            writer.close()

        async def body():
            server = await asyncio.start_server(
                old_server, "127.0.0.1", 0
            )
            port = server.sockets[0].getsockname()[1]
            client = await CtrlClient("127.0.0.1", port).connect()
            frames = []
            async for frame in client.subscribe(
                "subscribeKvStore", area="0", codec="binary"
            ):
                frames.append(frame)
            await client.close()
            server.close()
            await server.wait_closed()
            return frames

        frames = run(body())
        assert [f["type"] for f in frames] == ["snapshot", "delta"]

    @pytest.mark.parametrize("codec", ["json", "binary"])
    def test_overflow_resync_state_equals_fresh_dump_both_codecs(
        self, codec
    ):
        """The resync-snapshot invariant holds bit-identically in both
        codecs: a subscriber throttled through overflow recovers via a
        marked resync to a state equal to a fresh dump."""

        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            manager = StreamManager(
                kvstore_updates=store.updates_queue,
                config=StreamConfig(
                    subscriber_max_pending=1, coalesce_budget=2
                ),
            )
            manager.start()
            server = CtrlServer(
                "n1", port=0, kvstore=store, stream_manager=manager
            )
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            state: dict = {}
            kinds = []

            async def consume():
                async for frame in client.subscribe(
                    "subscribeKvStore",
                    area="0",
                    client="stalled",
                    codec=codec,
                ):
                    kinds.append(frame["type"])
                    _apply_kv_frame(state, frame)

            with injected(FaultInjector()) as inj:
                inj.arm(
                    "ctrl.stream.deliver",
                    times=None,
                    action=lambda sub: setattr(sub, "throttle_s", 0.05),
                    when=lambda sub: (
                        getattr(sub, "label", "") == "stalled"
                    ),
                )
                task = asyncio.ensure_future(consume())
                await asyncio.sleep(0.05)
                for i in range(30):
                    store.db("0").set_key_vals(
                        {f"adj:k{i}": _value("n1", version=i + 1)}
                    )
                    await asyncio.sleep(0.01)
                await asyncio.sleep(1.0)
                inj.disarm("ctrl.stream.deliver")
                await asyncio.sleep(0.5)

            assert "resync" in kinds, kinds
            dump = await (
                await CtrlClient("127.0.0.1", port).connect()
            ).call("getKvStoreKeyValsFiltered", area="0", prefixes=[])
            expect = {
                k: (v["version"], v["value"])
                for k, v in dump["key_vals"].items()
            }
            assert state == expect
            task.cancel()
            await client.close()
            manager.stop()
            await server.stop()
            store.stop()

        run(body())


# ---------------------------------------------------------------------------
# concurrent-client regression suite (the ISSUE 11 acceptance criteria)
# ---------------------------------------------------------------------------


def _flap_network(subscribers: int, stall_one: bool, codec: str = "json"):
    """Drive a 3-node line through 2 flap cycles with N concurrent
    subscribeKvStore subscribers (one optionally server-side-throttled
    into overflow) plus a burst of snapshot/scrape clients; returns the
    evidence dict. `codec` is "json", "binary", or "mixed" (round-robin
    across the cohort — the soak-round shape)."""
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = 3

    def _sub_codec(i: int) -> str:
        if codec == "mixed":
            return "binary" if i % 2 else "json"
        return codec

    async def body() -> dict:
        net = VirtualNetwork()
        # n0 hosts the stalled subscriber: one-frame queue and a
        # one-entry coalesce budget make any multi-key burst overflow
        # into a marked resync deterministically; the other nodes keep
        # roomy production-like bounds
        tight = {
            "stream_config": {
                "subscriber_max_pending": 1,
                "coalesce_budget": 1,
            }
        }
        roomy = {
            "stream_config": {
                "subscriber_max_pending": 8,
                "coalesce_budget": 64,
            }
        }
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                config_overrides=tight if i == 0 else roomy,
            )
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def partitioned() -> bool:
            left = net.wrappers["n0"].programmed_prefixes()
            right = net.wrappers[f"n{n - 1}"].programmed_prefixes()
            return (
                f"10.{n - 1}.0.0/24" not in left
                and "10.0.0.0/24" not in right
            )

        sub_tasks, sub_clients = [], []
        delta_counts = [0] * max(subscribers, 1)
        stalled_state: dict = {}
        stalled_kinds: list = []
        snapshot_calls = {"count": 0}
        stop_burst = asyncio.Event()

        async def watch(idx, client, label):
            try:
                async for frame in client.subscribe(
                    "subscribeKvStore",
                    area="0",
                    client=label,
                    codec=_sub_codec(idx),
                ):
                    if label == "stalled":
                        stalled_kinds.append(frame["type"])
                        _apply_kv_frame(stalled_state, frame)
                    if frame["type"] in ("delta", "resync"):
                        # both count as post-snapshot activity: a
                        # tight-budget node may legally serve a burst
                        # as one resync instead of N deltas
                        delta_counts[idx] += 1
            except Exception:
                pass

        async def snapshot_burst(client):
            # scrape/snapshot client hammering full dumps during flaps
            try:
                while not stop_burst.is_set():
                    await client.call(
                        "getKvStoreKeyValsFiltered", area="0", prefixes=[]
                    )
                    snapshot_calls["count"] += 1
                    await asyncio.sleep(0.005)
            except Exception:
                pass

        wrappers = list(net.wrappers.values())
        with injected(FaultInjector()) as inj:
            if stall_one:
                inj.arm(
                    "ctrl.stream.deliver",
                    times=None,
                    action=lambda sub: setattr(sub, "throttle_s", 0.3),
                    when=lambda sub: (
                        getattr(sub, "label", "") == "stalled"
                    ),
                )
            try:
                await wait_until(converged, timeout=60.0)
                for i in range(subscribers):
                    wrapper = wrappers[i % len(wrappers)]
                    client = await CtrlClient(
                        "127.0.0.1", wrapper.ctrl_port
                    ).connect()
                    sub_clients.append(client)
                    label = (
                        "stalled" if (stall_one and i == 0) else f"sub{i}"
                    )
                    sub_tasks.append(
                        asyncio.get_running_loop().create_task(
                            watch(i, client, label)
                        )
                    )
                burst_clients = []
                for _ in range(4):
                    client = await CtrlClient(
                        "127.0.0.1", wrappers[0].ctrl_port
                    ).connect()
                    burst_clients.append(client)
                    sub_tasks.append(
                        asyncio.get_running_loop().create_task(
                            snapshot_burst(client)
                        )
                    )
                sub_clients.extend(burst_clients)

                t0 = time.perf_counter()
                for _ in range(2):
                    net.fail_link("n1", "if1r", "n2", "if2l")
                    await wait_until(partitioned, timeout=60.0)
                    net.restore_link("n1", "if1r", "n2", "if2l")
                    await wait_until(converged, timeout=60.0)
                flap_elapsed = time.perf_counter() - t0
                stop_burst.set()
                if stall_one:
                    # recovery: stop throttling, let the stalled
                    # subscriber drain to quiescence
                    await asyncio.sleep(1.0)
                    inj.disarm("ctrl.stream.deliver")
                    await asyncio.sleep(0.8)
                agg = net.convergence_report()
                dump = None
                stream_counters = {}
                if stall_one:
                    reader = await CtrlClient(
                        "127.0.0.1", wrappers[0].ctrl_port
                    ).connect()
                    dump = await reader.call(
                        "getKvStoreKeyValsFiltered", area="0", prefixes=[]
                    )
                    await reader.close()
                    stream_counters = dict(
                        net.wrappers["n0"].daemon.stream_manager.counters
                    )
                spans = sum(
                    w.daemon.fib.counters.get("fib.convergence_spans", 0)
                    for w in net.wrappers.values()
                )
            finally:
                stop_burst.set()
                for task in sub_tasks:
                    task.cancel()
                if sub_tasks:
                    await asyncio.gather(*sub_tasks, return_exceptions=True)
                for client in sub_clients:
                    await client.close()
                await net.stop_all()

        e2e = agg["e2e_ms"]
        return {
            "e2e_p95_ms": e2e["p95"],
            "e2e_max_ms": e2e["max"],
            "spans_total": agg["spans_total"],
            "fib_spans": spans,
            "flap_elapsed_s": flap_elapsed,
            "delta_counts": delta_counts,
            "stalled_kinds": stalled_kinds,
            "stalled_state": stalled_state,
            "dump": dump,
            "snapshot_calls": snapshot_calls["count"],
            "stream_counters": stream_counters,
        }

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(body(), 300))
    finally:
        loop.close()


class TestConcurrentClients:
    def test_fanout_64_subscribers_with_stall_and_admission(self):
        """The acceptance run: baseline flap batch without subscribers,
        then the same batch against 64 concurrent subscribers — MIXED
        JSON/binary codecs round-robin across the cohort (ISSUE 16),
        one server-side-stalled into overflow — plus a snapshot-client
        burst. Convergence must stay within noise, every healthy
        subscriber must see deltas regardless of codec, and the stalled
        one must recover via a marked resync to a state equal to a
        fresh dump."""
        baseline = _flap_network(subscribers=0, stall_one=False)
        loaded = _flap_network(subscribers=64, stall_one=True, codec="mixed")

        # routes kept programming: same flap sequence converged, spans
        # closed on every node, and the p95 stayed inside the noise
        # envelope of the unloaded run (generous: shared-CI jitter)
        assert loaded["spans_total"] > 0
        assert loaded["fib_spans"] >= baseline["fib_spans"] * 0.5
        assert loaded["e2e_p95_ms"] <= max(
            baseline["e2e_p95_ms"] * 5.0, baseline["e2e_p95_ms"] + 250.0
        ), (loaded["e2e_p95_ms"], baseline["e2e_p95_ms"])

        # the fan-out actually fanned out: every healthy subscriber saw
        # at least one delta during two flap cycles
        healthy = loaded["delta_counts"][1:]
        assert len(healthy) == 63
        assert all(count >= 1 for count in healthy), (
            f"min deliveries {min(healthy)}"
        )
        # the snapshot burst ran alongside without starving anything
        assert loaded["snapshot_calls"] > 0

        # the stalled subscriber overflowed -> marked resync -> state
        # equal to a fresh dump (never silent loss)
        assert "resync" in loaded["stalled_kinds"], (
            loaded["stalled_kinds"][:10],
            loaded["stream_counters"],
        )
        assert loaded["stream_counters"].get("ctrl.stream.resyncs", 0) >= 1
        expect = {
            k: (v["version"], v["value"])
            for k, v in loaded["dump"]["key_vals"].items()
        }
        assert loaded["stalled_state"] == expect

    def test_slow_te_optimize_admission_does_not_delay_routes(self):
        """An injected slow runTeOptimize is queued/rejected by admission
        control while route programming proceeds: the flap converges
        while the slow call is still in flight, excess calls get typed
        server-busy rejections, and at most one optimize runs at once."""
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            net = VirtualNetwork()
            overrides = {
                "stream_config": {
                    "admission_capacity": 2,
                    "admission_max_wait_s": 0.2,
                    "admission_max_queue": 2,
                    "admission_max_queue_per_client": 1,
                }
            }
            for i in range(3):
                net.add_node(
                    f"n{i}",
                    loopback_prefix=f"10.{i}.0.0/24",
                    config_overrides=overrides,
                )
            await net.start_all()
            for i in range(2):
                net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

            def converged() -> bool:
                for i in range(3):
                    got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                    want = {f"10.{j}.0.0/24" for j in range(3) if j != i}
                    if not want.issubset(got):
                        return False
                return True

            def partitioned() -> bool:
                return "10.2.0.0/24" not in net.wrappers[
                    "n0"
                ].programmed_prefixes()

            running = {"n": 0, "max": 0}

            async def slow_te(params):
                running["n"] += 1
                running["max"] = max(running["max"], running["n"])
                await asyncio.sleep(1.2)
                running["n"] -= 1
                return {"slow": True}

            try:
                await wait_until(converged, timeout=60.0)
                n0 = net.wrappers["n0"]
                n0.daemon.decision.run_te_optimize = slow_te

                async def call_te(tag):
                    client = await CtrlClient(
                        "127.0.0.1", n0.ctrl_port
                    ).connect()
                    try:
                        return await client.call(
                            "runTeOptimize", client=tag
                        )
                    except CtrlError as exc:
                        return exc
                    finally:
                        await client.close()

                te_tasks = [
                    asyncio.get_running_loop().create_task(
                        call_te(f"client{i}")
                    )
                    for i in range(6)
                ]
                await asyncio.sleep(0.1)
                # the slow optimize is in flight NOW; the flap must
                # still program routes promptly
                t0 = time.perf_counter()
                net.fail_link("n1", "if1r", "n2", "if2l")
                await wait_until(partitioned, timeout=30.0)
                net.restore_link("n1", "if1r", "n2", "if2l")
                await wait_until(converged, timeout=30.0)
                flap_s = time.perf_counter() - t0
                assert running["n"] >= 1, (
                    "slow optimize should still be in flight"
                )
                results = await asyncio.gather(*te_tasks)
            finally:
                await net.stop_all()

            ok = [r for r in results if isinstance(r, dict)]
            busy = [
                r
                for r in results
                if isinstance(r, CtrlError) and r.server_busy
            ]
            assert ok, "at least one optimize must be admitted"
            assert busy, "excess optimize calls must be typed-rejected"
            assert all(r.retry_after_ms > 0 for r in busy)
            # cost-2 optimize on capacity 2: strictly one at a time —
            # the concurrency cap is what bounds loop occupancy
            assert running["max"] == 1
            # route programming proceeded while the optimize slept
            assert flap_s < 25.0
            adm = net.wrappers["n0"].daemon.admission.counters
            assert adm["ctrl.admission.admitted"] >= 1
            return True

        loop = asyncio.new_event_loop()
        try:
            assert loop.run_until_complete(asyncio.wait_for(body(), 180))
        finally:
            loop.close()


# ---------------------------------------------------------------------------
# route-db streaming over a live daemon
# ---------------------------------------------------------------------------

class TestRouteDbStream:
    def test_snapshot_then_delta_tracks_rib(self):
        from openr_tpu.ctrl.client import decode_obj
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            net = VirtualNetwork()
            for i in range(3):
                net.add_node(
                    f"n{i}", loopback_prefix=f"10.{i}.0.0/24"
                )
            await net.start_all()
            for i in range(2):
                net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

            def converged() -> bool:
                got = set(net.wrappers["n0"].programmed_prefixes())
                return {"10.1.0.0/24", "10.2.0.0/24"}.issubset(got)

            try:
                await wait_until(converged, timeout=60.0)
                n0 = net.wrappers["n0"]
                client = await CtrlClient(
                    "127.0.0.1", n0.ctrl_port
                ).connect()
                rib: dict = {}
                frames = []

                async def consume():
                    async for frame in client.subscribe(
                        "subscribeRouteDb", client="ribwatch"
                    ):
                        frames.append(frame["type"])
                        if frame["type"] in ("snapshot", "resync"):
                            rib.clear()
                        for prefix in frame["unicast_to_delete"]:
                            rib.pop(prefix, None)
                        for blob in frame["unicast_to_update"]:
                            route = decode_obj(blob)
                            rib[str(route.dest)] = route
                        if "10.2.0.0/24" not in rib and frames[-1] == (
                            "delta"
                        ):
                            return  # saw the withdrawal delta

                task = asyncio.ensure_future(consume())
                await asyncio.sleep(0.1)
                assert "10.2.0.0/24" in rib  # snapshot carried the RIB
                net.fail_link("n1", "if1r", "n2", "if2l")
                await asyncio.wait_for(task, 30)
                assert "delta" in frames
                assert "10.2.0.0/24" not in rib
                assert "10.1.0.0/24" in rib
                await client.close()
            finally:
                await net.stop_all()

        run(body(), timeout=120.0)


# ---------------------------------------------------------------------------
# soak judge sharpening + stream-scrape mode
# ---------------------------------------------------------------------------


class TestSoakJudge:
    def test_series_slope(self):
        from openr_tpu.testing.soak import series_slope

        assert series_slope([]) == 0.0
        assert series_slope([5.0]) == 0.0
        assert series_slope([1.0, 2.0, 3.0]) == pytest.approx(1.0)
        assert series_slope([3.0, 2.0, 1.0]) == pytest.approx(-1.0)
        assert series_slope([2.0, 2.0, 2.0]) == pytest.approx(0.0)

    def test_detect_step(self):
        from openr_tpu.testing.soak import detect_step

        assert detect_step([10.0] * 8) is None
        step = detect_step([10.0] * 4 + [50.0] * 4)
        assert step is not None and step["index"] == 4
        assert step["before_ms"] == 10.0 and step["after_ms"] == 50.0
        # sub-threshold jumps (relative OR absolute) stay quiet
        assert detect_step([10.0] * 4 + [14.0] * 4) is None
        assert detect_step([0.001] * 4 + [0.004] * 4) is None
        # too few windows on a side
        assert detect_step([10.0, 50.0, 50.0]) is None

    def test_analyze_trend_attributes_stage(self):
        from openr_tpu.testing.soak import analyze_trend

        windows = [
            {"start": float(i), "events": 1, "e2e_p95_ms": p}
            for i, p in enumerate([10.0, 10.0, 10.0, 60.0, 60.0, 60.0])
        ]
        stage_series = {
            "fib.program": [1.0, 1.0, 1.0, 50.0, 50.0, 50.0],
            "decision.route_build": [2.0] * 6,
        }
        trend = analyze_trend(windows, stage_series, [], 1.0)
        assert trend["step"] is not None
        assert trend["step"]["index"] == 3
        assert trend["step"]["faulted"] is False
        stages = [s["stage"] for s in trend["attributed_stages"]]
        assert stages == ["fib.program"]
        assert trend["p95_slope_ms_per_window"] > 0

    def test_analyze_trend_fault_attribution(self):
        from openr_tpu.testing.soak import analyze_trend

        windows = [
            {"start": float(i), "events": 1, "e2e_p95_ms": p}
            for i, p in enumerate([10.0, 10.0, 80.0, 80.0])
        ]
        trend = analyze_trend(
            windows, {}, fault_intervals=[(1.5, 2.5)], window_s=1.0
        )
        assert trend["step"] is not None
        assert trend["step"]["faulted"] is True

    def test_stream_scrape_soak(self):
        """The soak scrape loop riding subscribeKvStore streams instead
        of polling: every node's stream delivers its snapshot + the
        wave's adjacency deltas, and the judged report carries the
        stream section plus the sharpened trend checks."""
        from openr_tpu.testing.soak import SoakConfig, run_soak

        cfg = SoakConfig(
            nodes=3,
            waves=1,
            wave_links=1,
            settle_s=0.3,
            fault_every=0,
            seed=5,
            max_event_log=50,
            window_s=0.5,
            max_windows=240,
            stream_scrapes=True,
        )
        report = run_soak(cfg)
        assert report["stream"]["enabled"]
        assert len(report["stream"]["nodes"]) == 3
        # one snapshot per node plus the wave's adj deltas
        assert report["stream"]["frames_total"] >= 3 + 1
        assert all(
            c["frames"] >= 1 for c in report["stream"]["nodes"].values()
        )
        assert "trend" in report
        checks = report["verdict"]["checks"]
        assert "no_clean_trend_break" in checks
        assert report["verdict"]["pass"], checks


# ---------------------------------------------------------------------------
# STREAM_SMOKE (tier-1 acceptance): one class encode per frame
# ---------------------------------------------------------------------------


class TestStreamSmoke:
    def test_stream_smoke(self):
        """The shared-encode invariant end-to-end over real ctrl
        sockets: N subscribers in one filter-equivalence class cost
        exactly one class encode per dispatched frame (the acceptance
        assertions live inside run_stream_smoke; pin the headline
        evidence here too)."""
        from openr_tpu.streaming.smoke import run_stream_smoke

        summary = run_stream_smoke()
        assert summary["filter_classes_live"] == 1
        assert summary["class_encodes"] == summary["frames_per_subscriber"]
        assert summary["class_hits"] == (
            (summary["subscribers"] - 1) * summary["class_encodes"]
        )
        assert summary["resyncs"] == 0
