"""RESTART_SMOKE tier-1 + the warm-boot acceptance drills.

The graceful-restart sibling of FAULT_SMOKE/TRACE_SMOKE/SOAK_SMOKE
(openr_tpu/testing/restart.py): restart the middle node of an emulated
line and assert the whole warm-boot contract end to end —

  (a) neighbors never withdraw routes toward the restarted node's
      prefixes during the GR window (no NEIGHBOR_DOWN, GR holds enter
      and exit cleanly);
  (b) the restarted node's agent forwarding table is continuously
      non-empty through the daemon gap (stale routes keep forwarding);
  (c) post-boot route tables are oracle-identical to a never-restarted
      run of the same topology;
  (d) with Decision convergence fault-injected away, the stale-sweep
      deadline force-flushes with a forensics dump
      (run_stale_deadline_drill).

Plus the satellite units: PersistentStore-backed KvStore version floors
and the restart wave type in the soak harness.
"""

import asyncio

from openr_tpu.testing.restart import (
    run_restart_smoke,
    run_stale_deadline_drill,
)


class TestRestartSmoke:
    def test_restart_smoke(self):
        report = run_restart_smoke()
        assert report["oracle_parity"] is True
        assert report["restarted"] == f"n{report['nodes'] // 2}"
        assert report["fib_counters"]["fib.warm_boots"] == 1
        assert report["fib_counters"]["fib.restart_reconciles"] == 1
        assert report["fib_counters"]["fib.stale_routes_swept"] == 1
        assert report["kvstore_restart_syncs"] >= 1
        assert report["restart_e2e_ms"]["count"] == 1
        assert report["restart_e2e_ms"]["max"] > 0
        # ISSUE 17: the state journal's durable log survives the daemon
        # gap (sequence continues past the crash point) and the replayed
        # RIB matches both the CPU oracle on every node and the
        # never-restarted oracle network's replay
        assert report["journal_survived_restart"] is True
        assert report["journal_last_seq"] > report["journal_pre_restart_seq"]
        assert report["journal_verified_nodes"] == report["nodes"]
        assert report["journal_replay_parity"] is True

    def test_stale_deadline_force_flush(self):
        report = run_stale_deadline_drill()
        assert report["flushes"] == 1
        assert report["swept"] >= 1
        reasons = {d["reason"] for d in report["forensics"]}
        assert "stale_deadline_flush" in reasons
        assert "gr_expired_mid_boot" in reasons
        assert report["gr_hold_expiries"] >= 1


class TestKvStoreVersionFloor:
    """Warm-boot version floors: a client re-attached to the same
    PersistentStore must re-advertise strictly above every version it
    ever used, even against an empty local store."""

    def test_floor_supersedes_after_restart(self, tmp_path):
        from openr_tpu.configstore import PersistentStore
        from openr_tpu.kvstore import (
            InProcessTransport,
            KvStore,
            KvStoreClient,
        )

        async def body():
            store_path = str(tmp_path / "node.bin")
            transport = InProcessTransport()
            config_store = PersistentStore(store_path)

            kv1 = KvStore("a", ["0"], transport)
            client1 = KvStoreClient(kv1, "a", config_store=config_store)
            for _ in range(3):
                client1.set_key("adj:a", b"v")  # versions 1, 2, 3
            assert kv1.get_key("adj:a").version == 3
            client1.stop()
            kv1.stop()
            config_store.flush()

            # "restart": fresh store + client, same persistent store —
            # the first re-advertisement must beat the replicas peers
            # still hold (version 3), not start over at 1
            transport2 = InProcessTransport()
            config_store2 = PersistentStore(store_path)
            kv2 = KvStore("a", ["0"], transport2)
            client2 = KvStoreClient(kv2, "a", config_store=config_store2)
            client2.set_key("adj:a", b"v2")
            assert kv2.get_key("adj:a").version == 4
            assert kv2.counters.get("kvstore.restart_syncs") == 1
            # subsequent advertisements are ordinary bumps, not counted
            client2.set_key("adj:a", b"v3")
            assert kv2.get_key("adj:a").version == 5
            assert kv2.counters.get("kvstore.restart_syncs") == 1
            client2.stop()
            kv2.stop()
            config_store2.stop()

        asyncio.new_event_loop().run_until_complete(body())

    def test_no_config_store_keeps_seed_behavior(self):
        from openr_tpu.kvstore import (
            InProcessTransport,
            KvStore,
            KvStoreClient,
        )

        async def body():
            kv = KvStore("a", ["0"], InProcessTransport())
            client = KvStoreClient(kv, "a")
            client.set_key("k", b"v")
            assert kv.get_key("k").version == 1
            assert "kvstore.restart_syncs" not in kv.counters
            client.stop()
            kv.stop()

        asyncio.new_event_loop().run_until_complete(body())


class TestSoakRestartWave:
    def test_soak_restart_wave(self):
        """One soak wave that both reconfigures a chord AND restarts a
        node: the judged report must still pass every check (restart
        counters reset is forgiven by the scrape log, the wave
        converges, rollup accounting holds)."""
        from openr_tpu.testing.soak import SoakConfig, run_soak

        cfg = SoakConfig(
            nodes=3,
            waves=1,
            wave_links=1,
            settle_s=0.3,
            fault_every=0,  # no chaos: isolate the restart wave
            restart_every=1,
            seed=5,
            window_s=0.5,
            max_windows=240,
        )
        report = run_soak(cfg)
        assert report["waves"][0]["restarted"], report["waves"]
        checks = report["verdict"]["checks"]
        assert checks["waves_converged"]["ok"], checks
        assert checks["scrape_health"]["ok"], checks
        assert report["verdict"]["pass"], checks
