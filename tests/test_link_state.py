"""LinkState graph + SPF tests, mirroring openr/decision/tests/LinkStateTest.cpp."""

import pytest

from openr_tpu.lsdb import HoldableValue, LinkState
from openr_tpu.lsdb.link_state import path_a_in_path_b
from openr_tpu.topology import build_adj_dbs, grid_edges, make_adj_pair
from openr_tpu.types import Adjacency, AdjacencyDatabase


def build_link_state(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


class TestHoldableValue:
    def test_bool_holds(self):
        hv = HoldableValue(True)
        assert hv.value is True
        assert not hv.has_hold()
        assert not hv.decrement_ttl()
        hold_up, hold_down = 10, 5
        # True->False is a "down" change... for bool, bringing-up means
        # clearing overload (True->False), so holdUpTtl applies
        assert not hv.update_value(False, hold_up, hold_down)
        for _ in range(hold_up - 1):
            assert hv.has_hold()
            assert hv.value is True
            assert not hv.decrement_ttl()
        assert hv.decrement_ttl()
        assert not hv.has_hold()
        assert hv.value is False

        # same-value update: no-op
        assert not hv.update_value(False, hold_up, hold_down)
        assert not hv.has_hold()

        # False->True uses holdDownTtl
        assert not hv.update_value(True, hold_up, hold_down)
        for _ in range(hold_down - 1):
            assert hv.has_hold()
            assert hv.value is False
            assert not hv.decrement_ttl()
        assert hv.decrement_ttl()
        assert hv.value is True

        # double change within ttl falls back to fast update
        assert not hv.update_value(False, hold_up, hold_down)
        assert hv.has_hold()
        assert hv.value is True
        assert not hv.decrement_ttl()
        assert hv.update_value(True, hold_up, hold_down)
        assert not hv.has_hold()
        assert hv.value is True

    def test_metric_holds(self):
        hv = HoldableValue(10)
        # lowering a metric is a bringing-up change
        assert not hv.update_value(5, 10, 5)
        for _ in range(9):
            assert hv.has_hold()
            assert hv.value == 10
            assert not hv.decrement_ttl()
        assert hv.decrement_ttl()
        assert hv.value == 5
        # raising is a down change -> holdDownTtl
        assert not hv.update_value(7, 10, 5)
        for _ in range(4):
            assert not hv.decrement_ttl()
        assert hv.value == 7 or hv.has_hold()  # hold expired on 5th
        # zero ttl -> immediate
        hv2 = HoldableValue(1)
        assert hv2.update_value(2, 0, 0)
        assert hv2.value == 2


class TestLink:
    def test_accessors(self):
        a1, a2 = make_adj_pair("node1", "node2", 7, 9)
        from openr_tpu.lsdb.link_state import Link

        l = Link("0", "node1", a1, "node2", a2)
        assert l.other_node_name("node1") == "node2"
        assert l.other_node_name("node2") == "node1"
        with pytest.raises(ValueError):
            l.other_node_name("node3")
        assert l.iface_from_node("node1") == "if-node1-node2"
        assert l.metric_from_node("node1") == 7
        assert l.metric_from_node("node2") == 9
        assert not l.overload_from_node("node1")
        assert l.is_up()
        assert l.set_metric_from_node("node1", 2, 0, 0)
        assert l.metric_from_node("node1") == 2
        assert l.set_overload_from_node("node2", True, 0, 0)
        assert not l.is_up()
        # second overload on other side: up-ness unchanged -> no topo change
        assert not l.set_overload_from_node("node1", True, 0, 0)

    def test_identity(self):
        a1, a2 = make_adj_pair("node1", "node2")
        from openr_tpu.lsdb.link_state import Link

        l1 = Link("0", "node1", a1, "node2", a2)
        l2 = Link("0", "node2", a2, "node1", a1)  # same link, other direction
        assert l1 == l2
        assert hash(l1) == hash(l2)
        assert l1.first_node_name() == "node1"


class TestLinkStateTopology:
    def test_bidirectional_only(self):
        """A link exists only once both ends advertise it."""
        ls = LinkState("0")
        a1, a2 = make_adj_pair("n1", "n2")
        ch = ls.update_adjacency_database(
            AdjacencyDatabase("n1", [a1], area="0")
        )
        assert not ch.topology_changed  # unidirectional: no link yet
        assert ls.num_links() == 0
        ch = ls.update_adjacency_database(
            AdjacencyDatabase("n2", [a2], area="0")
        )
        assert ch.topology_changed
        assert ls.num_links() == 1
        assert ls.num_nodes() == 2

    def test_link_removal(self):
        ls = build_link_state([("n1", "n2", 1), ("n2", "n3", 1)])
        assert ls.num_links() == 2
        # n2 withdraws adjacency to n3
        a1, _ = make_adj_pair("n2", "n1")
        ch = ls.update_adjacency_database(
            AdjacencyDatabase("n2", [a1], area="0")
        )
        assert ch.topology_changed
        assert ls.num_links() == 1

    def test_delete_adjacency_database(self):
        ls = build_link_state([("n1", "n2", 1), ("n2", "n3", 1)])
        ch = ls.delete_adjacency_database("n2")
        assert ch.topology_changed
        assert ls.num_links() == 0
        assert not ls.has_node("n2")
        assert not ls.delete_adjacency_database("nope").topology_changed

    def test_metric_change_invalidates_spf(self):
        ls = build_link_state([("n1", "n2", 1), ("n2", "n3", 1), ("n1", "n3", 5)])
        assert ls.get_metric_from_a_to_b("n1", "n3") == 2
        # raise n1-n2 metric from n1 side to 10 => direct path wins
        dbs = build_adj_dbs(
            [("n1", "n2", 10), ("n1", "n3", 5)]
        )
        ch = ls.update_adjacency_database(dbs["n1"])
        assert ch.topology_changed
        assert ls.get_metric_from_a_to_b("n1", "n3") == 5

    def test_node_label_change(self):
        ls = LinkState("0")
        db = AdjacencyDatabase("n1", [], area="0", node_label=100)
        ch = ls.update_adjacency_database(db)
        assert ch.node_label_changed
        db2 = AdjacencyDatabase("n1", [], area="0", node_label=100)
        assert not ls.update_adjacency_database(db2).node_label_changed
        db3 = AdjacencyDatabase("n1", [], area="0", node_label=200)
        assert ls.update_adjacency_database(db3).node_label_changed


class TestSpf:
    def test_line_topology(self):
        ls = build_link_state([("a", "b", 1), ("b", "c", 2), ("c", "d", 3)])
        res = ls.get_spf_result("a")
        assert res["a"].metric == 0
        assert res["b"].metric == 1
        assert res["c"].metric == 3
        assert res["d"].metric == 6
        assert res["d"].next_hops == {"b"}

    def test_ecmp_nexthops(self):
        # a->b->d and a->c->d equal cost
        ls = build_link_state(
            [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        )
        res = ls.get_spf_result("a")
        assert res["d"].metric == 2
        assert res["d"].next_hops == {"b", "c"}
        # with unequal costs only one nexthop
        ls2 = build_link_state(
            [("a", "b", 1), ("a", "c", 2), ("b", "d", 1), ("c", "d", 1)]
        )
        assert ls2.get_spf_result("a")["d"].next_hops == {"b"}

    def test_overloaded_node_no_transit(self):
        # b overloaded: a can reach b but must not transit through it
        ls = build_link_state(
            [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)],
            overloaded_nodes={"b"},
        )
        res = ls.get_spf_result("a")
        assert res["b"].metric == 1  # still reachable
        assert res["c"].metric == 10  # but not via b
        assert res["c"].next_hops == {"c"}

    def test_overloaded_source_ok(self):
        # the source itself overloaded still computes its own routes
        ls = build_link_state(
            [("a", "b", 1), ("b", "c", 1)], overloaded_nodes={"a"}
        )
        res = ls.get_spf_result("a")
        assert res["c"].metric == 2

    def test_link_down_via_overload(self):
        ls = build_link_state([("a", "b", 1), ("a", "c", 1), ("c", "b", 1)])
        assert ls.get_spf_result("a")["b"].metric == 1
        # overload the a-b link from a's side => path a->c->b
        dbs = build_adj_dbs([("a", "b", 1), ("a", "c", 1)])
        a_adjs = []
        for adj in dbs["a"].adjacencies:
            if adj.other_node_name == "b":
                from openr_tpu.types import replace

                adj = replace(adj, is_overloaded=True)
            a_adjs.append(adj)
        ch = ls.update_adjacency_database(
            AdjacencyDatabase("a", a_adjs, area="0")
        )
        assert ch.topology_changed
        assert ls.get_spf_result("a")["b"].metric == 2
        assert ls.get_spf_result("a")["b"].next_hops == {"c"}

    def test_hop_count_mode(self):
        ls = build_link_state([("a", "b", 10), ("b", "c", 20)])
        assert ls.get_metric_from_a_to_b("a", "c") == 30
        assert ls.get_hops_from_a_to_b("a", "c") == 2
        assert ls.get_max_hops_to_node("a") == 2

    def test_unreachable(self):
        ls = build_link_state([("a", "b", 1), ("c", "d", 1)])
        assert ls.get_metric_from_a_to_b("a", "c") is None
        assert ls.get_metric_from_a_to_b("a", "a") == 0

    def test_memoization(self):
        ls = build_link_state([("a", "b", 1), ("b", "c", 1)])
        ls.get_spf_result("a")
        runs = ls.spf_runs
        ls.get_spf_result("a")
        assert ls.spf_runs == runs  # cached
        ls.get_spf_result("b")
        assert ls.spf_runs == runs + 1
        # topology change invalidates
        ls.update_adjacency_database(
            build_adj_dbs([("a", "b", 5), ("b", "c", 1)])["a"]
        )
        ls.get_spf_result("a")
        assert ls.spf_runs == runs + 2


class TestHolds:
    def test_ordered_fib_hold(self):
        # new link held up for hold_up_ttl ticks
        ls = LinkState("0")
        dbs = build_adj_dbs([("a", "b", 1)])
        ls.update_adjacency_database(dbs["a"], hold_up_ttl=2, hold_down_ttl=1)
        ch = ls.update_adjacency_database(
            dbs["b"], hold_up_ttl=2, hold_down_ttl=1
        )
        # new link is held (not up) => no topology change yet
        assert not ch.topology_changed
        assert ls.has_holds()
        assert "b" not in ls.get_spf_result("a")
        assert not ls.decrement_holds().topology_changed
        assert ls.decrement_holds().topology_changed  # hold expired
        assert not ls.has_holds()
        assert ls.get_spf_result("a")["b"].metric == 1

    def test_metric_hold(self):
        ls = build_link_state([("a", "b", 10)])
        # lower the metric with holds: old value visible until expiry
        dbs = build_adj_dbs([("a", "b", 1)])
        ch = ls.update_adjacency_database(
            dbs["a"], hold_up_ttl=3, hold_down_ttl=1
        )
        assert not ch.topology_changed  # held
        assert ls.get_spf_result("a")["b"].metric == 10
        ls.decrement_holds()
        ls.decrement_holds()
        assert ls.decrement_holds().topology_changed
        assert ls.get_spf_result("a")["b"].metric == 1


class TestKthPaths:
    def test_two_disjoint_paths(self):
        # square: two edge-disjoint equal-cost paths a->d
        ls = build_link_state(
            [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
        )
        paths = ls.get_kth_paths("a", "d", 1)
        assert len(paths) == 2
        used = set()
        for p in paths:
            assert len(p) == 2
            for link in p:
                assert link not in used  # edge-disjoint
                used.add(link)
        assert ls.get_kth_paths("a", "d", 2) == []

    def test_second_shortest(self):
        # triangle with a longer detour: k=1 direct, k=2 via c
        ls = build_link_state(
            [("a", "b", 1), ("a", "c", 1), ("c", "b", 1)]
        )
        k1 = ls.get_kth_paths("a", "b", 1)
        assert len(k1) == 1 and len(k1[0]) == 1
        k2 = ls.get_kth_paths("a", "b", 2)
        assert len(k2) == 1 and len(k2[0]) == 2

    def test_path_a_in_path_b(self):
        ls = build_link_state(
            [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
        )
        pab = ls.get_kth_paths("a", "b", 1)[0]
        pad = ls.get_kth_paths("a", "d", 1)[0]
        assert path_a_in_path_b(pab, pad)
        assert not path_a_in_path_b(pad, pab)

    def test_same_node(self):
        ls = build_link_state([("a", "b", 1)])
        assert ls.get_kth_paths("a", "a", 1) == []


class TestGrid:
    def test_grid_spf(self):
        n = 5
        ls = build_link_state(grid_edges(n))
        res = ls.get_spf_result("g0_0")
        assert len(res) == n * n
        # manhattan distance on unit grid
        assert res[f"g{n-1}_{n-1}"].metric == 2 * (n - 1)
        # corner-to-corner ECMP: both neighbors of source are nexthops
        assert res[f"g{n-1}_{n-1}"].next_hops == {"g0_1", "g1_0"}


class TestPrefixState:
    def test_advertise_withdraw(self):
        from openr_tpu.lsdb import PrefixState
        from openr_tpu.types import (
            IpPrefix,
            PrefixDatabase,
            PrefixEntry,
            PrefixType,
        )

        ps = PrefixState()
        p1 = IpPrefix("10.1.0.0/16")
        p2 = IpPrefix("10.2.0.0/16")
        db = PrefixDatabase(
            "n1",
            [PrefixEntry(p1), PrefixEntry(p2)],
            area="0",
        )
        changed = ps.update_prefix_database(db)
        assert changed == {p1, p2}
        # no-op re-advertisement
        assert ps.update_prefix_database(db) == set()
        # withdraw p2
        db2 = PrefixDatabase("n1", [PrefixEntry(p1)], area="0")
        assert ps.update_prefix_database(db2) == {p2}
        assert ps.has_prefix(p1) and not ps.has_prefix(p2)

    def test_multi_node_multi_area(self):
        from openr_tpu.lsdb import PrefixState
        from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry

        ps = PrefixState()
        p = IpPrefix("10.0.0.0/8")
        ps.update_prefix_database(
            PrefixDatabase("n1", [PrefixEntry(p)], area="a1")
        )
        ps.update_prefix_database(
            PrefixDatabase("n2", [PrefixEntry(p)], area="a2")
        )
        assert set(ps.prefixes[p].keys()) == {"n1", "n2"}
        # withdraw from n1/a1 only
        ps.update_prefix_database(PrefixDatabase("n1", [], area="a1"))
        assert set(ps.prefixes[p].keys()) == {"n2"}

    def test_loopback_tracking(self):
        from openr_tpu.lsdb import PrefixState
        from openr_tpu.types import (
            IpPrefix,
            PrefixDatabase,
            PrefixEntry,
            PrefixType,
        )

        ps = PrefixState()
        lo = IpPrefix("192.168.0.1/32")
        ps.update_prefix_database(
            PrefixDatabase(
                "n1", [PrefixEntry(lo, type=PrefixType.LOOPBACK)], area="0"
            )
        )
        vias = ps.get_loopback_vias({"n1"}, is_v4=True, igp_metric=5)
        assert len(vias) == 1
        assert vias[0].address == "192.168.0.1"
        assert vias[0].metric == 5
        assert ps.get_loopback_vias({"n1"}, is_v4=False) == []
        # withdrawal clears it
        ps.update_prefix_database(PrefixDatabase("n1", [], area="0"))
        assert ps.get_loopback_vias({"n1"}, is_v4=True) == []
