"""DeltaPath (device-side route-delta extraction) differential suite.

The O(changes) partial route rebuild (solver/delta.py) must be
byte-identical to the classic full-mirror rebuild on every event class:
randomized flap sequences (metric decrease/increase, adjacency flap,
node-overload toggle), partitions, and `_PATCH_SLOTS` overflow — and the
warm single-link event must copy back O(changes) bytes, never the full
[s_pad, n_pad] mirror (the ISSUE 6 transfer-budget acceptance criterion).
"""

import dataclasses
import random

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.solver import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    DeltaRouteBuilder,
    SolverSupervisor,
    SpfSolver,
    SupervisorConfig,
    TpuSpfSolver,
    apply_route_delta,
    get_route_delta,
)
from openr_tpu.solver.supervisor import OPEN
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def make_prefix_state(announcers, area="0", **entry_kw):
    ps = PrefixState()
    for node, pfxs in announcers.items():
        ps.update_prefix_database(
            PrefixDatabase(
                node,
                [PrefixEntry(IpPrefix(p), **entry_kw) for p in pfxs],
                area=area,
            )
        )
    return ps


def assert_route_db_equal(db_a, db_b):
    assert db_a is not None and db_b is not None
    assert set(db_a.unicast_entries) == set(db_b.unicast_entries)
    for prefix, entry in db_a.unicast_entries.items():
        assert db_b.unicast_entries[prefix] == entry, prefix
    assert set(db_a.mpls_entries) == set(db_b.mpls_entries)
    for label, entry in db_a.mpls_entries.items():
        assert db_b.mpls_entries[label] == entry, label


def apply_weight_event(rng, dbs, ls, links):
    """One randomized weight-only LSDB event (the classes the delta path
    serves or must correctly refuse): adjacency flap via overload, metric
    change, or node-overload toggle. Mutates dbs and ls."""
    kind = rng.choice(("flap", "metric", "node_overload"))
    if kind in ("flap", "metric"):
        a, b, _ = links[rng.randrange(len(links))]
        db = dbs[a]
        new_adjs = []
        for adj in db.adjacencies:
            if adj.other_node_name == b:
                if kind == "flap":
                    adj = dataclasses.replace(
                        adj, is_overloaded=not adj.is_overloaded
                    )
                else:
                    adj = dataclasses.replace(adj, metric=rng.randint(1, 9))
            new_adjs.append(adj)
        dbs[a] = dataclasses.replace(db, adjacencies=new_adjs)
        ls.update_adjacency_database(dbs[a])
    else:
        node = sorted(dbs)[rng.randrange(len(dbs))]
        dbs[node] = dataclasses.replace(
            dbs[node], is_overloaded=not dbs[node].is_overloaded
        )
        ls.update_adjacency_database(dbs[node])
    return kind


def set_metric(dbs, ls, a, b, metric):
    """Set the directed metric of a's adjacency toward b."""
    dbs[a] = dataclasses.replace(
        dbs[a],
        adjacencies=[
            dataclasses.replace(adj, metric=metric)
            if adj.other_node_name == b
            else adj
            for adj in dbs[a].adjacencies
        ],
    )
    ls.update_adjacency_database(dbs[a])


def set_adj_overload(dbs, ls, a, b, overloaded):
    dbs[a] = dataclasses.replace(
        dbs[a],
        adjacencies=[
            dataclasses.replace(adj, is_overloaded=overloaded)
            if adj.other_node_name == b
            else adj
            for adj in dbs[a].adjacencies
        ],
    )
    ls.update_adjacency_database(dbs[a])


class DeltaHarness:
    """TpuSpfSolver + DeltaRouteBuilder over a mutable LSDB, checked
    against a cold full rebuild after every step."""

    def __init__(self, edges, me, announcers, solver_kwargs=None, **entry_kw):
        self.me = me
        self.solver_kwargs = dict(solver_kwargs or {})
        self.dbs = build_adj_dbs(edges)
        self.ls = LinkState("0")
        for db in self.dbs.values():
            self.ls.update_adjacency_database(db)
        self.ps = make_prefix_state(announcers, **entry_kw)
        self.solver = TpuSpfSolver(me, **self.solver_kwargs)
        self.builder = DeltaRouteBuilder(self.solver)
        self.als = {"0": self.ls}
        self.db, _, used = self.builder.build(
            me, self.als, self.ps, None, force_full=True
        )
        assert not used  # first build is always full
        assert self.db is not None

    def step(self, dirty_prefixes=frozenset(), force_full=False):
        """One rebuild; asserts the result — delta-built or not — equals a
        from-scratch full rebuild of the same LSDB, and that the emitted
        update folds the previous db into the new one. Returns used_delta."""
        prev = self.db
        new_db, update, used = self.builder.build(
            self.me,
            self.als,
            self.ps,
            prev,
            dirty_prefixes=dirty_prefixes,
            force_full=force_full,
        )
        ref = TpuSpfSolver(self.me, **self.solver_kwargs).build_route_db(
            self.me, self.als, self.ps
        )
        assert_route_db_equal(ref, new_db)
        cpu_kwargs = {
            k: v
            for k, v in self.solver_kwargs.items()
            if not k.startswith("apsp")
        }
        oracle = SpfSolver(self.me, **cpu_kwargs).build_route_db(
            self.me, self.als, self.ps
        )
        assert_route_db_equal(oracle, new_db)
        folded = apply_route_delta(prev, update)
        assert_route_db_equal(new_db, folded)
        self.db = new_db
        return used


PFXS = ["10.1.0.0/16", "10.2.0.0/16", "10.3.0.0/16", "10.4.0.0/16"]


class TestDeltaDifferential:
    """Randomized flap sequences: the delta-built RouteDatabase must stay
    identical to the full-mirror rebuild (TPU) and the CPU oracle."""

    def test_grid_random_sequences(self):
        for seed in (5, 23):
            h = DeltaHarness(
                grid_edges(4),
                "g0_0",
                {
                    "g3_3": [PFXS[0]],
                    "g0_3": [PFXS[1]],
                    "g2_1": [PFXS[2]],
                    "g1_2": [PFXS[3]],
                },
            )
            rng = random.Random(seed)
            links = list(grid_edges(4))
            applied = 0
            for _ in range(14):
                before = h.ls.version
                apply_weight_event(rng, h.dbs, h.ls, links)
                if h.ls.version == before:
                    continue
                h.step()
                applied += 1
            assert applied > 0
            # the sequences mix qualifying and disqualifying events: both
            # paths must have served
            assert h.builder.delta_builds > 0
            assert h.builder.full_builds > 1

    def test_clos_random_sequence(self):
        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        )
        h = DeltaHarness(
            edges, "rsw0_0", {"rsw1_2": [PFXS[0]], "rsw0_2": [PFXS[1]]}
        )
        rng = random.Random(17)
        links = list(edges)
        for _ in range(10):
            before = h.ls.version
            apply_weight_event(rng, h.dbs, h.ls, links)
            if h.ls.version == before:
                continue
            h.step()
        assert h.builder.delta_builds > 0

    def test_batched_events_accumulate_columns(self):
        # several qualifying events between rebuilds: the accumulated
        # changed-column set must describe the union
        h = DeltaHarness(
            grid_edges(4), "g0_0", {"g3_3": [PFXS[0]], "g0_3": [PFXS[1]]}
        )
        set_metric(h.dbs, h.ls, "g3_2", "g3_3", 7)
        h.solver.poll_device_delta(h.als)  # solve event 1, delta pends
        set_metric(h.dbs, h.ls, "g2_3", "g3_3", 7)
        set_metric(h.dbs, h.ls, "g0_2", "g0_3", 5)
        assert h.step() is True
        assert h.builder.delta_builds == 1

    def test_increase_then_decrease_same_link(self):
        h = DeltaHarness(
            [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("a", "d", 9)],
            "a",
            {"d": [PFXS[0]], "c": [PFXS[1]]},
        )
        used = []
        for metric in (8, 1):  # invalidation pass, then warm decrease
            set_metric(h.dbs, h.ls, "b", "c", metric)
            used.append(h.step())
        assert used == [True, True]

    def test_partition_flap_and_heal_deletes_and_restores(self):
        edges = [
            ("a", "b", 1), ("b", "c", 1), ("c", "a", 1),
            ("c", "x", 2),  # bridge
            ("x", "y", 1), ("y", "z", 1), ("z", "x", 1),
        ]
        h = DeltaHarness(edges, "a", {"z": [PFXS[0]], "b": [PFXS[1]]})
        far = IpPrefix(PFXS[0])
        assert far in h.db.unicast_entries
        # both directions of the bridge go down: far side unreachable
        set_adj_overload(h.dbs, h.ls, "c", "x", True)
        set_adj_overload(h.dbs, h.ls, "x", "c", True)
        assert h.step() is True  # remote flap rides the delta path
        assert far not in h.db.unicast_entries
        assert IpPrefix(PFXS[1]) in h.db.unicast_entries
        set_adj_overload(h.dbs, h.ls, "c", "x", False)
        set_adj_overload(h.dbs, h.ls, "x", "c", False)
        assert h.step() is True
        assert far in h.db.unicast_entries

    def test_node_overload_toggle_takes_full_path(self):
        # a transit-mask change cannot be described by changed D columns
        # alone: the solver must refuse the delta and the full path serves
        h = DeltaHarness(
            grid_edges(3), "g0_0", {"g2_2": [PFXS[0]], "g0_2": [PFXS[1]]}
        )
        for overloaded in (True, False):
            h.dbs["g1_1"] = dataclasses.replace(
                h.dbs["g1_1"], is_overloaded=overloaded
            )
            h.ls.update_adjacency_database(h.dbs["g1_1"])
            assert h.step() is False
        assert h.builder.delta_builds == 0

    def test_event_incident_to_me_takes_full_path(self):
        # my own out-link metric is a route input no distance column
        # reflects (the nexthop triangle's weight column)
        h = DeltaHarness(
            grid_edges(3), "g0_0", {"g2_2": [PFXS[0]]}
        )
        set_metric(h.dbs, h.ls, "g0_0", "g0_1", 4)
        assert h.step() is False

    def test_patch_slots_overflow_takes_full_path(self, monkeypatch):
        import openr_tpu.solver.tpu as tpu_mod

        monkeypatch.setattr(tpu_mod, "_PATCH_SLOTS", 0)
        h = DeltaHarness(
            [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)],
            "a",
            {"d": [PFXS[0]]},
        )
        set_metric(h.dbs, h.ls, "b", "c", 6)  # overflows the 0-slot budget
        assert h.step() is False
        assert h.builder.delta_builds == 0

    def test_prefix_advertisement_change_rides_dirty_set(self):
        # a prefix event with no topology change: Decision feeds the dirty
        # prefixes explicitly; no solve delta pends, but the partial path
        # still serves it (changed_nodes is empty, not None)
        h = DeltaHarness(
            grid_edges(3), "g0_0", {"g2_2": [PFXS[0]]}
        )
        dirty = h.ps.update_prefix_database(
            PrefixDatabase(
                "g0_2", [PrefixEntry(IpPrefix(PFXS[1]))], area="0"
            )
        )
        assert dirty
        assert h.step(dirty_prefixes=dirty) is True
        assert IpPrefix(PFXS[1]) in h.db.unicast_entries
        # withdrawal deletes through the same path
        dirty = h.ps.update_prefix_database(
            PrefixDatabase("g0_2", [], area="0")
        )
        assert h.step(dirty_prefixes=dirty) is True
        assert IpPrefix(PFXS[1]) not in h.db.unicast_entries

    def test_force_full_drains_pending_delta(self):
        # a forced-full rebuild must consume the accumulated delta so a
        # stale column set never rides into a later event
        h = DeltaHarness(grid_edges(3), "g0_0", {"g2_2": [PFXS[0]]})
        set_metric(h.dbs, h.ls, "g1_2", "g2_2", 8)
        assert h.step(force_full=True) is False
        set_metric(h.dbs, h.ls, "g1_2", "g2_2", 1)
        assert h.step() is True  # re-armed, next event is delta-served


class TestTransferBudget:
    """ISSUE 6 acceptance: a warm single-link-flap event transfers
    O(changes) host<->device bytes — bounded by the changed columns'
    compaction bucket, never by n_pad."""

    def test_single_link_warm_event_d2h_is_o_changes(self):
        from openr_tpu.ops.graph import _next_bucket

        side = 12  # 144 nodes
        h = DeltaHarness(
            grid_edges(side),
            "g0_0",
            {f"g{side - 1}_{side - 1}": [PFXS[0]]},
        )
        solve = h.solver._solves[("0", "g0_0")][1]
        s_pad, n_pad = solve.d.shape
        full_mirror_bytes = s_pad * n_pad * 4
        d2h_before = solve.d2h_bytes
        extracts_before = solve.delta_extracts
        cols_before = solve.delta_columns
        # bump both far-corner in-edges (one leaves the other ECMP leg
        # equal-cost, changing nothing): exactly one column moves
        corner = f"g{side - 1}_{side - 1}"
        set_metric(h.dbs, h.ls, f"g{side - 2}_{side - 1}", corner, 9)
        set_metric(h.dbs, h.ls, f"g{side - 1}_{side - 2}", corner, 9)
        assert h.step() is True
        assert solve.delta_extracts == extracts_before + 1
        xfer = solve.d2h_bytes - d2h_before
        # the whole event's copy-back (count scalar + compacted columns +
        # nexthop rows) fits the bucket bound and is far below the mirror
        num = solve.delta_columns - cols_before
        cap = _next_bucket(num, minimum=8)
        l_pad = _next_bucket(
            max(len(solve._nh_link_arrays()[0]), 1), minimum=8
        )
        assert num < n_pad // 4
        assert xfer <= 4 + cap * (4 + 4 * s_pad + l_pad)
        assert xfer < full_mirror_bytes // 4
        # and the route build consumed the patched mirror: no full fetch
        assert solve.d2h_bytes - d2h_before == xfer

    def test_patched_mirror_matches_cold_fetch(self):
        h = DeltaHarness(
            grid_edges(6), "g0_0", {"g5_5": [PFXS[0]], "g0_5": [PFXS[1]]}
        )
        set_metric(h.dbs, h.ls, "g4_5", "g5_5", 7)
        assert h.step() is True
        warm = h.solver._solves[("0", "g0_0")][1]
        cold = TpuSpfSolver("g0_0")
        cold.build_route_db("g0_0", h.als, h.ps)
        cold_solve = cold._solves[("0", "g0_0")][1]
        np.testing.assert_array_equal(warm.d, cold_solve.d)


class TestApplyRouteDelta:
    def test_apply_is_diff_inverse(self):
        me, announcers = "g0_0", {
            "g2_2": [PFXS[0]], "g0_2": [PFXS[1]], "g1_1": [PFXS[2]]
        }
        ls_old = build_ls(grid_edges(3))
        old = SpfSolver(me).build_route_db(
            me, {"0": ls_old}, make_prefix_state(announcers)
        )
        edges_new = [
            (a, b, 9 if (a, b) == ("g1_2", "g2_2") else w)
            for a, b, w in grid_edges(3)
        ]
        new = SpfSolver(me).build_route_db(
            me,
            {"0": build_ls(edges_new)},
            make_prefix_state({"g2_2": [PFXS[0]], "g1_1": [PFXS[2]]}),
        )
        folded = apply_route_delta(old, get_route_delta(new, old))
        assert_route_db_equal(new, folded)
        assert get_route_delta(folded, new).empty()

    def test_unchanged_entries_are_shared(self):
        old = DecisionRouteDb()
        new = apply_route_delta(old, DecisionRouteUpdate())
        assert new.unicast_entries == {} and new.mpls_entries == {}


class TestSupervisorDeltaFaultDomain:
    """Breaker trips and shadow audits must force the full path."""

    def _inputs(self):
        edges = grid_edges(3)
        ls = build_ls(edges)
        ps = make_prefix_state({"g2_2": [PFXS[0]], "g0_2": [PFXS[1]]})
        return "g0_0", {"0": ls}, ps

    def test_poll_gated_while_breaker_open(self):
        me, als, ps = self._inputs()
        sup = SolverSupervisor(
            TpuSpfSolver(me), SpfSolver(me), SupervisorConfig()
        )
        sup.build_route_db(me, als, ps)
        sup.state = OPEN
        assert sup.poll_device_delta(als) is None

    def test_poll_fault_classified_and_degrades(self):
        me, als, ps = self._inputs()
        sup = SolverSupervisor(
            TpuSpfSolver(me), SpfSolver(me), SupervisorConfig()
        )
        sup.build_route_db(me, als, ps)

        def boom(_als):
            raise RuntimeError("DEVICE_LOST: chip went away")

        sup.primary.poll_device_delta = boom
        assert sup.poll_device_delta(als) is None
        assert sup.counters["decision.spf.solver_failures.device_loss"] == 1

    def test_verify_route_delta_self_heals_mismatch(self):
        me, als, ps = self._inputs()
        samples = []
        sup = SolverSupervisor(
            TpuSpfSolver(me),
            SpfSolver(me),
            SupervisorConfig(audit_interval=1),
            log_sample_fn=samples.append,
        )
        full = sup.build_route_db(me, als, ps)
        corrupted = DecisionRouteDb(
            unicast_entries=dict(
                list(full.unicast_entries.items())[:-1]  # drop one route
            ),
            mpls_entries=dict(full.mpls_entries),
        )
        corrected = sup.verify_route_delta(corrupted, me, als, ps)
        assert corrected is not None
        assert_route_db_equal(full, corrected)
        assert sup.counters["decision.spf.delta_audit_mismatches"] == 1
        assert any(
            s.get("event") == "ROUTE_DELTA_AUDIT_MISMATCH" for s in samples
        )

    def test_verify_route_delta_clean_db_passes(self):
        me, als, ps = self._inputs()
        sup = SolverSupervisor(
            TpuSpfSolver(me),
            SpfSolver(me),
            SupervisorConfig(audit_interval=1),
        )
        full = sup.build_route_db(me, als, ps)
        assert sup.verify_route_delta(full, me, als, ps) is None
        assert sup.counters["decision.spf.delta_audit_runs"] == 1
        assert "decision.spf.delta_audit_mismatches" not in sup.counters


class TestAdjacencyToMeQualification:
    """Unit suite for the narrowed direct-neighbor refusal (ISSUE 7): a
    neighbor's update forces the full path only when its adjacencies TO ME
    actually changed — far-side-only updates stay delta-eligible."""

    @staticmethod
    def db(node, adjs):
        from openr_tpu.types import AdjacencyDatabase

        return AdjacencyDatabase(this_node_name=node, adjacencies=adjs)

    @staticmethod
    def adj(other, **kw):
        from openr_tpu.types import Adjacency

        return Adjacency(
            other_node_name=other, if_name=f"if-b-{other}", **kw
        )

    def check(self, prior_adjs, new_adjs):
        from openr_tpu.decision.decision import _adjacencies_to_me_changed

        prior = self.db("b", prior_adjs) if prior_adjs is not None else None
        return _adjacencies_to_me_changed(prior, self.db("b", new_adjs), "a")

    def test_far_side_only_change_does_not_force_full(self):
        before = [self.adj("a", metric=1), self.adj("c", metric=1)]
        after = [self.adj("a", metric=1), self.adj("c", metric=7)]
        assert self.check(before, after) is False

    def test_metric_to_me_forces_full(self):
        before = [self.adj("a", metric=1), self.adj("c", metric=1)]
        after = [self.adj("a", metric=4), self.adj("c", metric=1)]
        assert self.check(before, after) is True

    def test_overload_and_nexthop_to_me_force_full(self):
        before = [self.adj("a", metric=1)]
        assert self.check(
            before, [self.adj("a", metric=1, is_overloaded=True)]
        ) is True
        assert self.check(
            before, [self.adj("a", metric=1, nexthop_v6="fe80::b")]
        ) is True

    def test_adjacency_to_me_added_or_removed_forces_full(self):
        assert self.check([self.adj("c")], [self.adj("c"), self.adj("a")])
        assert self.check([self.adj("c"), self.adj("a")], [self.adj("c")])

    def test_first_advertisement_with_adj_to_me_is_structural(self):
        assert self.check(None, [self.adj("a")]) is True

    def test_first_advertisement_without_adj_to_me_is_not(self):
        assert self.check(None, [self.adj("c")]) is False

    def test_rtt_timestamp_churn_is_ignored(self):
        # fields the route build never consumes must not poison the delta
        before = [self.adj("a", rtt=100, timestamp=1), self.adj("c")]
        after = [self.adj("a", rtt=900, timestamp=2), self.adj("c")]
        assert self.check(before, after) is False


class TestDecisionDeltaPath:
    """End to end through Decision: a qualifying remote flap must be served
    by the delta route build and emit the same update the full path would."""

    def test_remote_metric_flap_uses_delta_build(self):
        import asyncio

        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue
        from openr_tpu.types import Publication, Value, adj_key, prefix_key
        from openr_tpu.utils import serializer

        async def body():
            kv_q = RWQueue()
            route_q = ReplicateQueue()
            decision = Decision(
                DecisionConfig(
                    my_node_name="a",
                    solver_backend="tpu",
                    debounce_min=0.005,
                    debounce_max=0.02,
                ),
                RQueue(kv_q),
                route_q,
            )
            reader = route_q.get_reader()
            decision.start()
            edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("d", "e", 1)]
            dbs = build_adj_dbs(edges)
            pub = Publication(area="0")
            for db in dbs.values():
                pub.key_vals[adj_key(db.this_node_name)] = Value(
                    1, db.this_node_name, serializer.dumps(db)
                )
            pub.key_vals[prefix_key("e")] = Value(
                1, "e", serializer.dumps(
                    PrefixDatabase("e", [PrefixEntry(IpPrefix(PFXS[0]))])
                )
            )
            kv_q.push(pub)
            await asyncio.wait_for(reader.get(), 10)
            assert decision.counters.get(
                "decision.route_build_delta_runs", 0
            ) == 0  # first build is full
            # remote metric bump: c->d — c is not adjacent to me, so the
            # batch qualifies at the Decision layer too
            dbs["c"] = dataclasses.replace(
                dbs["c"],
                adjacencies=[
                    dataclasses.replace(adj, metric=5)
                    if adj.other_node_name == "d"
                    else adj
                    for adj in dbs["c"].adjacencies
                ],
            )
            pub2 = Publication(area="0")
            pub2.key_vals[adj_key("c")] = Value(
                2, "c", serializer.dumps(dbs["c"])
            )
            kv_q.push(pub2)
            delta = await asyncio.wait_for(reader.get(), 10)
            assert decision.counters["decision.route_build_delta_runs"] == 1
            routes = {e.prefix: e for e in delta.unicast_routes_to_update}
            assert IpPrefix(PFXS[0]) in routes
            entry = routes[IpPrefix(PFXS[0])]
            assert {nh.metric for nh in entry.nexthops} == {8}
            # the maintained route_db matches a from-scratch oracle build
            ls = LinkState("0")
            for db in dbs.values():
                ls.update_adjacency_database(db)
            oracle = SpfSolver("a").build_route_db(
                "a", {"0": ls}, decision.prefix_state
            )
            assert_route_db_equal(oracle, decision.route_db)
            decision.stop()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(body(), 30))
        finally:
            loop.close()

    def test_neighbor_far_side_change_stays_on_delta_path(self):
        """The narrowed refusal (ISSUE 7 satellite): my direct neighbor b
        re-advertises, but only its FAR-side link b->c changed — the
        adjacency to me is byte-identical. Decision used to force a full
        rebuild for any update containing an adjacency to me; it must now
        stay on the delta path and still match the from-scratch oracle.
        A follow-up update that touches b's adjacency TO me must still
        take the full path."""
        import asyncio

        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue
        from openr_tpu.types import Publication, Value, adj_key, prefix_key
        from openr_tpu.utils import serializer

        def bump(dbs, node, metrics, version):
            dbs[node] = dataclasses.replace(
                dbs[node],
                adjacencies=[
                    dataclasses.replace(
                        adj, metric=metrics.get(adj.other_node_name,
                                                adj.metric)
                    )
                    for adj in dbs[node].adjacencies
                ],
            )
            pub = Publication(area="0")
            pub.key_vals[adj_key(node)] = Value(
                version, node, serializer.dumps(dbs[node])
            )
            return pub

        async def body():
            kv_q = RWQueue()
            route_q = ReplicateQueue()
            decision = Decision(
                DecisionConfig(
                    my_node_name="a",
                    solver_backend="tpu",
                    debounce_min=0.005,
                    debounce_max=0.02,
                ),
                RQueue(kv_q),
                route_q,
            )
            reader = route_q.get_reader()
            decision.start()
            edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
            dbs = build_adj_dbs(edges)
            pub = Publication(area="0")
            for db in dbs.values():
                pub.key_vals[adj_key(db.this_node_name)] = Value(
                    1, db.this_node_name, serializer.dumps(db)
                )
            pub.key_vals[prefix_key("d")] = Value(
                1, "d", serializer.dumps(
                    PrefixDatabase("d", [PrefixEntry(IpPrefix(PFXS[0]))])
                )
            )
            kv_q.push(pub)
            await asyncio.wait_for(reader.get(), 10)

            def oracle():
                ls = LinkState("0")
                for db in dbs.values():
                    ls.update_adjacency_database(db)
                return SpfSolver("a").build_route_db(
                    "a", {"0": ls}, decision.prefix_state
                )

            # b is MY neighbor; only its far-side link b->c changes
            kv_q.push(bump(dbs, "b", {"c": 5}, 2))
            delta = await asyncio.wait_for(reader.get(), 10)
            assert decision.counters["decision.route_build_delta_runs"] == 1
            routes = {e.prefix: e for e in delta.unicast_routes_to_update}
            assert {nh.metric for nh in routes[IpPrefix(PFXS[0])].nexthops} \
                == {7}
            assert_route_db_equal(oracle(), decision.route_db)

            # the same batch shape, but b also touches its adjacency TO
            # me: the narrowed qualification must still refuse the delta
            # (route-affecting far-side change rides along so an update
            # is emitted either way)
            kv_q.push(bump(dbs, "b", {"a": 3, "c": 2}, 3))
            await asyncio.wait_for(reader.get(), 10)
            assert decision.counters["decision.route_build_delta_runs"] == 1
            assert_route_db_equal(oracle(), decision.route_db)
            decision.stop()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(body(), 30))
        finally:
            loop.close()


class TestDeltaUnderLfa:
    """DeltaPath with `compute_lfa_paths` on (the ISSUE 12 carry-over):
    with an APSP-capable solver the builder no longer force-disables — the
    RFC 5286 inequality's only input beyond the announcer columns is the
    ME column, which the solver poisons via poll_device_delta; randomized
    sequences must stay byte-identical to the full rebuild and the CPU
    oracle on both paths."""

    LFA_KW = {"compute_lfa_paths": True, "apsp_max_nodes": 4096}

    def test_grid_random_sequences_with_lfa(self):
        for seed in (5, 23, 41):
            h = DeltaHarness(
                grid_edges(4),
                "g0_0",
                {
                    "g3_3": [PFXS[0]],
                    "g0_3": [PFXS[1]],
                    "g2_1": [PFXS[2]],
                    "g1_2": [PFXS[3]],
                },
                solver_kwargs=self.LFA_KW,
            )
            rng = random.Random(seed)
            links = list(grid_edges(4))
            for _ in range(14):
                before = h.ls.version
                apply_weight_event(rng, h.dbs, h.ls, links)
                if h.ls.version == before:
                    continue
                h.step()
            # the delta path must have actually served under LFA — the
            # historical behavior was an unconditional force-full
            assert h.builder.delta_builds > 0, seed
            assert h.builder.full_builds > 1, seed

    def test_clos_random_sequence_with_lfa(self):
        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        )
        h = DeltaHarness(
            edges,
            "rsw0_0",
            {"rsw1_2": [PFXS[0]], "rsw0_2": [PFXS[1]]},
            solver_kwargs=self.LFA_KW,
        )
        rng = random.Random(17)
        links = list(edges)
        for _ in range(10):
            before = h.ls.version
            apply_weight_event(rng, h.dbs, h.ls, links)
            if h.ls.version == before:
                continue
            h.step()
        assert h.builder.delta_builds > 0

    def test_me_column_change_forces_full_under_lfa(self):
        # dist(neighbor, me) feeds EVERY destination's LFA threshold: an
        # event that moves the me column must refuse the delta even though
        # it qualifies under the plain rules (not sourced at me)
        h = DeltaHarness(
            grid_edges(4),
            "g0_0",
            {"g3_3": [PFXS[0]]},
            solver_kwargs=self.LFA_KW,
        )
        set_metric(h.dbs, h.ls, "g0_1", "g0_0", 9)  # far-side edge INTO me
        assert h.step() is False  # full path, still byte-identical
        # a remote event that leaves the me column alone rides the delta
        set_metric(h.dbs, h.ls, "g3_2", "g3_3", 7)
        assert h.step() is True

    def test_lfa_without_apsp_keeps_force_full(self):
        h = DeltaHarness(
            grid_edges(4),
            "g0_0",
            {"g3_3": [PFXS[0]]},
            solver_kwargs={"compute_lfa_paths": True},  # apsp off
        )
        set_metric(h.dbs, h.ls, "g3_2", "g3_3", 7)
        assert h.step() is False
        assert h.builder.delta_builds == 0

    def test_delta_vs_full_parity_includes_lfa_nexthops(self):
        # LFA widens nexthop sets beyond the shortest-path DAG; a stale
        # threshold would show as a missing/excess alternate. Drive a
        # sequence that flips an alternate in and out of qualification.
        h = DeltaHarness(
            [
                ("a", "b", 1),
                ("b", "d", 1),
                ("a", "c", 2),
                ("c", "d", 2),
            ],
            "a",
            {"d": [PFXS[0]]},
            solver_kwargs=self.LFA_KW,
        )
        entry = h.db.unicast_entries[IpPrefix(PFXS[0])]
        assert len(entry.nexthops) == 2  # b on the SP, c as the LFA
        set_metric(h.dbs, h.ls, "c", "d", 9)  # c no longer loop-free
        h.step()
        entry = h.db.unicast_entries[IpPrefix(PFXS[0])]
        nh_nodes = {nh.neighbor_node for nh in entry.nexthops}
        oracle = SpfSolver("a", compute_lfa_paths=True).build_route_db(
            "a", h.als, h.ps
        )
        assert nh_nodes == {
            nh.neighbor_node
            for nh in oracle.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
