"""Seeded fuzz of the KvStore wire-decode hardening (ISSUE 18).

The dissemination plane's decode surface — the JSON peer codecs
(openr_tpu.kvstore.wire), the native record codec
(openr_tpu.kvstore.native._unpack_records), and the TCP peer server's
request loop — must reject every hostile frame with a *typed* error
(WireDecodeError / NativeDecodeError, kind in the four-kind vocabulary)
and never let one escape as an uncaught exception. The live-server test
then proves the property that matters operationally: a connection that
feeds the server garbage keeps getting answers, the store loop never
dies, and every rejection lands on the kvstore.wire.rejected.* counters.

All generation is seeded (random.Random) so a failure replays exactly.
"""

import asyncio
import base64
import json
import random

from openr_tpu.kvstore import KvStore, KvStoreParams
from openr_tpu.kvstore.native import NativeDecodeError, _pack_records
from openr_tpu.kvstore.native import _unpack_records
from openr_tpu.kvstore.tcp import KvStoreTcpServer, TcpTransport
from openr_tpu.kvstore.wire import (
    MAX_KEY_CHARS,
    WireDecodeError,
    dual_messages_from_json,
    key_vals_from_json,
    key_vals_to_json,
    publication_from_json,
    publication_to_json,
    value_from_json,
)
from openr_tpu.types import TTL_INFINITY, Publication, Value, generate_hash

KINDS = {"oversized", "truncated", "malformed", "hash_mismatch"}


def _random_json(rng: random.Random, depth: int = 0):
    """A random JSON-ish value tree — the shapes a corrupted or hostile
    peer can actually put on the wire after json.loads succeeds."""
    roll = rng.random()
    if depth >= 3 or roll < 0.45:
        return rng.choice(
            [
                None,
                True,
                False,
                rng.randint(-(2**40), 2**40),
                rng.random() * 1e6,
                "",
                "originator",
                "not base64 !!!",
                base64.b64encode(b"payload").decode(),
                "x" * rng.choice([1, 64, MAX_KEY_CHARS + 1]),
            ]
        )
    if roll < 0.75:
        return {
            rng.choice(
                [
                    "version",
                    "originator_id",
                    "value",
                    "ttl",
                    "ttl_version",
                    "hash",
                    "key_vals",
                    "node_ids",
                    "expired_keys",
                    "perf_events",
                    "messages",
                    "src_id",
                    "k" * rng.randint(1, 8),
                ]
            ): _random_json(rng, depth + 1)
            for _ in range(rng.randint(0, 4))
        }
    return [_random_json(rng, depth + 1) for _ in range(rng.randint(0, 4))]


def _valid_publication() -> dict:
    kv = {}
    for i in range(4):
        value = f"payload-{i}".encode()
        kv[f"adj:node{i}"] = Value(
            version=i + 1,
            originator_id=f"node{i}",
            value=value,
            ttl=TTL_INFINITY,
            ttl_version=0,
            hash=generate_hash(i + 1, f"node{i}", value),
        )
    pub = Publication(
        key_vals=kv,
        expired_keys=[],
        node_ids=["node0", "node1"],
        tobe_updated_keys=None,
        area="0",
    )
    return publication_to_json(pub)


class TestJsonDecodeFuzz:
    def test_random_trees_reject_typed_only(self):
        """400 seeded random trees through every peer-facing decoder:
        success or a typed WireDecodeError — nothing else escapes."""
        rng = random.Random(1318)
        decoders = [
            value_from_json,
            key_vals_from_json,
            publication_from_json,
            dual_messages_from_json,
        ]
        for i in range(400):
            tree = _random_json(rng)
            for decode in decoders:
                try:
                    decode(tree)
                except WireDecodeError as exc:
                    assert exc.kind in KINDS, (
                        f"iter {i}: {decode.__name__} raised untyped "
                        f"kind {exc.kind!r} on {tree!r}"
                    )
                except Exception as exc:  # the property under test
                    raise AssertionError(
                        f"iter {i}: {decode.__name__} leaked "
                        f"{type(exc).__name__}: {exc} on {tree!r}"
                    ) from exc

    def test_bit_flipped_valid_frames(self):
        """Byte-level mutation of a valid hashed publication: every
        mutant either fails json.loads (the transport counts that as
        malformed), decodes with a typed rejection — including
        hash_mismatch when the flip lands inside a value body — or
        happens to still be a valid frame. No uncaught exceptions."""
        frame = json.dumps(_valid_publication()).encode()
        rng = random.Random(77)
        saw_hash_mismatch = False
        for i in range(400):
            buf = bytearray(frame)
            for _ in range(rng.randint(1, 4)):
                pos = rng.randrange(len(buf))
                buf[pos] ^= 1 << rng.randrange(8)
            try:
                tree = json.loads(bytes(buf))
            except ValueError:
                continue  # tcp.py _serve_conn: note_reject("malformed")
            try:
                publication_from_json(tree)
            except WireDecodeError as exc:
                assert exc.kind in KINDS, f"iter {i}: kind {exc.kind!r}"
                saw_hash_mismatch |= exc.kind == "hash_mismatch"
            except Exception as exc:
                raise AssertionError(
                    f"iter {i}: leaked {type(exc).__name__}: {exc} "
                    f"on {bytes(buf)!r}"
                ) from exc
        # the end-to-end integrity check must actually fire under
        # mutation (this is the path that carries corrupted bodies past
        # base64 — a regression here silently admits bit-rotted values)
        assert saw_hash_mismatch

    def test_oversized_key_and_value_rejected(self):
        with_key = {"x" * (MAX_KEY_CHARS + 1): {"version": 1,
                                                "originator_id": "a"}}
        try:
            key_vals_from_json(with_key)
            raise AssertionError("oversized key admitted")
        except WireDecodeError as exc:
            assert exc.kind == "oversized"


class TestNativeDecodeFuzz:
    def _valid_buf(self) -> bytes:
        kv = {
            f"prefix:node{i}": Value(
                i + 1, f"node{i}", b"v" * (i + 1), TTL_INFINITY, 0,
                hash=i * 7,
            )
            for i in range(5)
        }
        return _pack_records(kv)

    def test_every_truncation_is_typed(self):
        """Cut the packed record stream at every byte boundary: each
        prefix must decode or raise a typed NativeDecodeError — never an
        IndexError/struct.error from an unguarded read."""
        buf = self._valid_buf()
        assert len(_unpack_records(buf)) == 5
        for cut in range(len(buf)):
            try:
                _unpack_records(buf[:cut])
            except NativeDecodeError as exc:
                assert exc.kind in KINDS, f"cut {cut}: kind {exc.kind!r}"
            except Exception as exc:
                raise AssertionError(
                    f"cut {cut}: leaked {type(exc).__name__}: {exc}"
                ) from exc

    def test_seeded_bit_flips_are_typed(self):
        buf = self._valid_buf()
        rng = random.Random(4242)
        for i in range(500):
            mut = bytearray(buf)
            for _ in range(rng.randint(1, 6)):
                pos = rng.randrange(len(mut))
                mut[pos] ^= 1 << rng.randrange(8)
            try:
                _unpack_records(bytes(mut))
            except NativeDecodeError as exc:
                assert exc.kind in KINDS, f"iter {i}: kind {exc.kind!r}"
            except Exception as exc:
                raise AssertionError(
                    f"iter {i}: leaked {type(exc).__name__}: {exc} "
                    f"on flip of {bytes(mut)!r}"
                ) from exc


class TestTcpServerSurvivesGarbage:
    def test_garbage_then_service(self):
        """A live KvStoreTcpServer fed hostile frames on a raw socket:
        every garbage line gets an error reply (the connection and the
        store loop survive), typed rejections land on the
        kvstore.wire.rejected.* counters, and a well-formed kv.set on
        the same battered connection still updates the store."""

        async def body():
            store = KvStore(
                "victim",
                ["0"],
                TcpTransport(),
                params=KvStoreParams(node_id="victim"),
            )
            server = KvStoreTcpServer(store)
            await server.start()
            reader, writer = await asyncio.open_connection(
                server.host, server.port
            )

            async def exchange(line: bytes) -> dict:
                writer.write(line + b"\n")
                await writer.drain()
                return json.loads(await reader.readline())

            # not JSON at all
            reply = await exchange(b"\x00\xffnot json at all")
            assert "error" in reply
            # JSON, but no method
            reply = await exchange(json.dumps({"id": 1}).encode())
            assert "error" in reply
            # typed decode rejections through the kv.set dispatch path
            hostile = [
                # oversized key
                {"x" * (MAX_KEY_CHARS + 1): {"version": 1,
                                             "originator_id": "a"}},
                # truncated value frame
                {"k": {"version": 1}},
                # bad base64 body
                {"k": {"version": 1, "originator_id": "a",
                       "value": "!!! not b64"}},
                # hash over different bytes
                {"k": {"version": 1, "originator_id": "a",
                       "value": base64.b64encode(b"body").decode(),
                       "hash": 1}},
            ]
            for i, key_vals in enumerate(hostile):
                reply = await exchange(
                    json.dumps(
                        {
                            "id": 10 + i,
                            "method": "kv.set",
                            "params": {"area": "0", "key_vals": key_vals},
                        }
                    ).encode()
                )
                assert "error" in reply, f"hostile frame {i} was admitted"
            # seeded printable garbage for good measure
            rng = random.Random(9)
            for _ in range(50):
                junk = bytes(
                    rng.randrange(32, 127) for _ in range(rng.randint(1, 80))
                )
                reply = await exchange(junk)
                assert "error" in reply or "result" in reply
            counters = store.counters
            assert counters["kvstore.wire.rejected_total"] >= 4
            for kind in KINDS:
                assert counters[f"kvstore.wire.rejected.{kind}"] >= 1, kind
            # the same connection still provides service
            good = Value(1, "peer", b"alive", TTL_INFINITY, 0)
            reply = await exchange(
                json.dumps(
                    {
                        "id": 99,
                        "method": "kv.set",
                        "params": {
                            "area": "0",
                            "key_vals": key_vals_to_json({"ok": good}),
                            "node_ids": ["peer"],
                        },
                    }
                ).encode()
            )
            assert reply.get("result") == {}
            assert store.get_key("ok").value == b"alive"
            writer.close()
            await server.stop()

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(body(), 30.0)
        )
