"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.ops import INF, batched_spf, compile_graph, ecmp_dag
from openr_tpu.parallel import make_mesh, sharded_batched_spf, sharded_spf_step
from openr_tpu.topology import build_adj_dbs, grid_edges


def build_graph(edges):
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    return compile_graph(ls)


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices (set in conftest)")
    return devs[:8]


class TestShardedSpf:
    def test_row_sharded_matches_single_device(self, devices):
        graph = build_graph(grid_edges(5))
        rows = np.arange(graph.n_pad, dtype=np.int32)
        mesh = make_mesh(devices, shape=(8, 1))
        d_sharded = np.asarray(sharded_batched_spf(graph, rows, mesh))
        d_single = np.asarray(batched_spf(graph, rows))
        assert d_sharded.shape[0] >= d_single.shape[0]
        np.testing.assert_array_equal(
            d_sharded[: d_single.shape[0]], d_single
        )

    def test_two_axis_step(self, devices):
        graph = build_graph(grid_edges(4))
        rows = np.arange(graph.n_pad, dtype=np.int32)
        mesh = make_mesh(devices, shape=(4, 2))
        d, dag = sharded_spf_step(graph, rows, mesh)
        d, dag = np.asarray(d), np.asarray(dag)
        d_ref = np.asarray(batched_spf(graph, rows))
        dag_ref = np.asarray(ecmp_dag(graph, d_ref))
        np.testing.assert_array_equal(d[: d_ref.shape[0]], d_ref)
        np.testing.assert_array_equal(dag, dag_ref)

    def test_uneven_batch_padding(self, devices):
        graph = build_graph(grid_edges(3))  # 9 nodes -> 16 padded
        rows = np.arange(graph.n, dtype=np.int32)  # 9 sources, not /8
        mesh = make_mesh(devices, shape=(8, 1))
        d = np.asarray(sharded_batched_spf(graph, rows, mesh))
        assert d.shape[0] == 16  # padded to multiple of 8
        d_ref = np.asarray(batched_spf(graph, rows))
        np.testing.assert_array_equal(d[: graph.n], d_ref)
