"""Smoke tests: every benchmark module runs end-to-end at tiny sizes and
prints parseable JSON result lines (the contract bench.py also follows)."""

import json

import pytest


def run_and_parse(capsys, main, env, monkeypatch):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    main([])
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "no JSON lines emitted"
    results = [json.loads(line) for line in out]
    for r in results:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(r)
        assert isinstance(r["value"], (int, float))
    return results


def test_decision_bench(capsys, monkeypatch):
    from benchmarks.decision_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "DECISION_GRID_SIDES": "3",
            "DECISION_FABRIC_PODS": "1",
            "DECISION_KSP2_SIDES": "3",
            "DECISION_EVENTS": "2",
            "DECISION_KSP2_PREFIXES": "3",
        },
        monkeypatch,
    )
    assert len(results) == 3


def test_kvstore_bench(capsys, monkeypatch):
    from benchmarks.kvstore_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "KVSTORE_MERGE_SIZES": "50:10",
            "KVSTORE_DUMP_SIZES": "50",
        },
        monkeypatch,
    )
    assert len(results) == 2
    assert all(r["value"] > 0 for r in results)


def test_scale_bench(capsys, monkeypatch):
    from benchmarks.scale_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "SCALE_CLOS_PODS": "1",
            "SCALE_WAN_N": "64",
            "SCALE_KSP_N": "64",
            "SCALE_SOURCES": "8",
            "SCALE_METRICS": "2",
        },
        monkeypatch,
    )
    assert len(results) == 4


def test_fib_bench(capsys, monkeypatch):
    from benchmarks.fib_bench import main

    results = run_and_parse(
        capsys, main, {"FIB_ROUTES": "400", "FIB_BATCH": "100"}, monkeypatch
    )
    assert results[0]["metric"] == "fib_program_routes_per_sec"


def test_incremental_bench(capsys, monkeypatch):
    from benchmarks.incremental_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "INC_PODS": "2",
            "INC_PLANES": "2",
            "INC_SSW": "2",
            "INC_FSW": "2",
            "INC_RSW": "4",
            "INC_EVENTS": "6",
        },
        monkeypatch,
    )
    r = results[0]
    # the warm-start win must be visible in relaxation round counts, the
    # hardware-independent half of the metric (the bench asserts this too)
    assert r["rounds_warm_mean"] < r["rounds_cold_mean"]
    assert r["p99_ms"] > 0
    assert r["baseline"] == "cold-solve"


def test_bench_py_smoke(capsys, monkeypatch):
    """`python bench.py` end-to-end under BENCH_SMOKE=1: tiny topology,
    reps 1/2 — bench bitrot fails tier-1 instead of zeroing BENCH rounds."""
    import bench

    monkeypatch.setenv("BENCH_SMOKE", "1")
    bench.main([])
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "bench.py printed no JSON line"
    result = json.loads(out[-1])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
    assert result["value"] > 0
    # conftest pins JAX_PLATFORMS=cpu, so the probe reports a native run
    assert "backend" not in result
    assert "degraded" not in result


def test_bench_py_marks_fallback_degraded(capsys, monkeypatch):
    """A cpu-fallback run measures a reduced workload on the wrong
    hardware: the JSON line must say so explicitly so BENCH consumers
    treat it as an availability signal, never as a perf regression."""
    import bench

    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setattr(bench, "_probe_backend", lambda: "cpu-fallback")
    bench.main([])
    out = capsys.readouterr().out.strip().splitlines()
    result = json.loads(out[-1])
    assert result["backend"] == "cpu-fallback"
    assert result["degraded"] is True
    # the availability-signal contract: a degraded line still carries the
    # full metric shape, so dashboards can plot uptime without special
    # cases — only perf comparisons must skip it
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)


def test_config_store_bench(capsys, monkeypatch):
    from benchmarks.config_store_bench import main

    results = run_and_parse(
        capsys, main, {"CS_KEYS": "50", "CS_VALUE_BYTES": "64"}, monkeypatch
    )
    assert results[0]["metric"] == "config_store_writes_per_sec"
