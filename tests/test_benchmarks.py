"""Smoke tests: every benchmark module runs end-to-end at tiny sizes and
prints parseable JSON result lines (the contract bench.py also follows)."""

import json
import os

import pytest


def run_and_parse(capsys, main, env, monkeypatch):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    main([])
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "no JSON lines emitted"
    results = [json.loads(line) for line in out]
    for r in results:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(r)
        assert isinstance(r["value"], (int, float))
    return results


def test_decision_bench(capsys, monkeypatch):
    from benchmarks.decision_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "DECISION_GRID_SIDES": "3",
            "DECISION_FABRIC_PODS": "1",
            "DECISION_KSP2_SIDES": "3",
            "DECISION_EVENTS": "2",
            "DECISION_KSP2_PREFIXES": "3",
        },
        monkeypatch,
    )
    assert len(results) == 3


def test_kvstore_bench(capsys, monkeypatch):
    from benchmarks.kvstore_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "KVSTORE_MERGE_SIZES": "50:10",
            "KVSTORE_DUMP_SIZES": "50",
        },
        monkeypatch,
    )
    assert len(results) == 2
    assert all(r["value"] > 0 for r in results)


def test_scale_bench(capsys, monkeypatch):
    from benchmarks.scale_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "SCALE_CLOS_PODS": "1",
            "SCALE_WAN_N": "64",
            "SCALE_KSP_N": "64",
            "SCALE_SOURCES": "8",
            "SCALE_METRICS": "2",
        },
        monkeypatch,
    )
    assert len(results) == 4


def test_fib_bench(capsys, monkeypatch):
    from benchmarks.fib_bench import main

    results = run_and_parse(
        capsys, main, {"FIB_ROUTES": "400", "FIB_BATCH": "100"}, monkeypatch
    )
    assert results[0]["metric"] == "fib_program_routes_per_sec"


def test_incremental_bench(capsys, monkeypatch):
    from benchmarks.incremental_bench import main

    results = run_and_parse(
        capsys,
        main,
        {
            "INC_PODS": "2",
            "INC_PLANES": "2",
            "INC_SSW": "2",
            "INC_FSW": "2",
            "INC_RSW": "4",
            "INC_EVENTS": "6",
        },
        monkeypatch,
    )
    r = results[0]
    # the warm-start win must be visible in relaxation round counts, the
    # hardware-independent half of the metric (the bench asserts this too)
    assert r["rounds_warm_mean"] < r["rounds_cold_mean"]
    assert r["p99_ms"] > 0
    assert r["baseline"] == "cold-solve"


def test_bench_py_smoke(capsys, monkeypatch):
    """`python bench.py` end-to-end under BENCH_SMOKE=1: tiny topology,
    reps 1/2 — bench bitrot fails tier-1 instead of zeroing BENCH rounds.
    Every stdout line must be parseable JSON: the SPF/s headline, the
    p95 hello-to-programmed-route convergence line from the emulator flap
    run (the ROADMAP 'second bench metric line'), and the what-if TE
    optimization line (ISSUE 7 'third metric line')."""
    import bench

    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setenv("BENCH_CONV_NODES", "4")
    monkeypatch.setenv("BENCH_CONV_FLAPS", "1")
    bench.main([])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) >= 10, (
        "bench.py must print SPF+convergence+TE+scale+exporter+stream+apsp"
        "+fleet+journal+loss JSON lines"
    )
    results = [json.loads(line) for line in out]
    for result in results:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
        assert result["value"] > 0
        # conftest pins JAX_PLATFORMS=cpu, so the probe reports native
        assert "backend" not in result
        assert "degraded" not in result
        # artifact provenance stamp (ISSUE 17): every line is traceable
        # to the exact code + field contract that produced it
        assert result["schema_version"] >= 1
        assert result["build"]
    assert results[0]["metric"].endswith("spf_recomputes_per_sec")
    # device-memory columns (docs/Monitoring.md "Device-memory
    # observatory"): the SPF, TE, scale-tiled and APSP lines each report
    # the ledger's peak resident bytes for the line's working set next to
    # the predict_fit forward model — the delta column is the standing
    # record of how tight the admission arithmetic tracks reality
    for idx in (0, 2, 3, 6):
        line = results[idx]
        assert line["mem_peak_bytes"] > 0, line["metric"]
        assert line["mem_predicted_bytes"] > 0, line["metric"]
        assert line["mem_predicted_vs_live_bytes"] == (
            line["mem_predicted_bytes"] - line["mem_peak_bytes"]
        ), line["metric"]
    # phase-split contract (ISSUE 13): the SPF line carries per-phase
    # attribution columns measured with explicit barriers, so the first
    # hardware round lands with h2d/relax/d2h split out of the headline
    spf_phases = results[0]["phases"]
    assert set(spf_phases) == {"h2d_ms", "relax_ms", "d2h_ms"}
    for value in spf_phases.values():
        assert value >= 0.0
    assert spf_phases["relax_ms"] > 0.0
    assert results[1]["metric"] == "convergence_e2e_p95_ms"
    assert results[1]["spans"] > 0
    assert results[2]["metric"] == "te_optimize_ms"
    assert results[2]["initial_max_util"] >= results[2]["optimized_max_util"]
    # the destination-tiled scale line: per-device tile bytes must sit a
    # full graph-axis factor under the replica bytes it replaces
    scale = results[3]
    assert scale["metric"].startswith("scale")
    assert scale["metric"].endswith("_tiled_cold_solve_ms")
    assert scale["warm_flap_ms"] > 0
    b_ax, g_ax = scale["mesh"]
    assert (
        scale["tile_bytes_per_device"] * b_ax * g_ax
        == scale["replica_bytes_per_device"]
    )
    # the scale line's phase split (warm flap event under barriers; halo
    # traffic rides inside relax, split by the rounds gauges)
    scale_phases = scale["phases"]
    assert set(scale_phases) == {"h2d_ms", "relax_ms", "d2h_ms"}
    assert scale_phases["relax_ms"] > 0.0
    # the exporter-overhead line (continuous-telemetry cost on the same
    # flap batch as the convergence line): a parse-validated render and a
    # measured per-span rollup fold cost must both be present and nonzero
    exporter = results[4]
    assert exporter["metric"] == "exporter_scrape_render_ms"
    assert exporter["rollup_record_us"] > 0
    assert exporter["metrics_series"] > 0
    # the streaming fan-out line (ISSUE 11 'sixth metric line'): sustained
    # delta-delivery rate across concurrent subscribeKvStore subscribers
    # on the flap batch, with the convergence p95 of the subscriber run
    # reported next to the zero-subscriber baseline (bench.py asserts the
    # held-flat envelope itself; the contract here pins the line's shape)
    stream = results[5]
    assert stream["metric"] == "stream_fanout_events_s"
    assert stream["subscribers"] > 0
    assert stream["deliveries"] > 0
    assert stream["value"] > 0
    assert stream["e2e_p95_ms"] > 0
    assert stream["baseline_e2e_p95_ms"] > 0
    # shared-encode columns (ISSUE 16): the encode-share meter and the
    # class-level sharing evidence ride the line, plus the subscriber
    # sweep (BENCH_STREAM_SWEEP; the smoke env pins one extra point)
    assert 0.0 <= stream["encode_share"] < 1.0
    assert stream["encode_classes"] > 0
    assert 0.0 <= stream["class_hit_rate"] <= 1.0
    assert isinstance(stream["sweep"], list) and stream["sweep"]
    for point in stream["sweep"]:
        assert point["subscribers"] > 0
        assert point["events_s"] > 0
        assert 0.0 <= point["encode_share"] < 1.0
        assert 0.0 <= point["class_hit_rate"] <= 1.0
    # the blocked-FW APSP line (ISSUE 12 'seventh metric line'): cold
    # close plus the warm re-close of a single-link event and the
    # FW-vs-batched-Dijkstra crossover sweep; the warm path must report
    # its restricted re-close rounds (the O(dirty-blocks) machinery ran)
    apsp = results[6]
    assert apsp["metric"] == "fw_apsp_close_ms"
    assert apsp["warm_reclose_ms"] > 0
    assert apsp["reclose_rounds"] >= 1
    assert len(apsp["crossover"]) >= 2
    for point in apsp["crossover"]:
        assert point["fw_close_ms"] > 0
        assert point["batched_dijkstra_ms"] > 0
    # the fleet-observation line (ISSUE 15 'eighth metric line'): the
    # flap batch re-run with the fleet observer attached over real ctrl
    # sockets — mean SLO-watchdog tick cost, with the attached run's
    # convergence p95 next to the detached baseline's (bench.py asserts
    # the held-flat envelope itself; the contract here pins the shape)
    fleet = results[7]
    assert fleet["metric"] == "fleet_watch_overhead_ms"
    assert fleet["value"] > 0
    assert fleet["fleet_ticks"] > 0
    assert fleet["fleet_scrapes"] > 0
    assert fleet["attached_e2e_p95_ms"] > 0
    assert fleet["baseline_e2e_p95_ms"] > 0
    # the journal-recording line (ISSUE 17 'ninth metric line'): the flap
    # batch re-run with every node journaling publications + RIB deltas —
    # mean sampled per-record cost, replay-verified on every node against
    # the CPU oracle, with the journal-on run's convergence p95 next to
    # the journal-off baseline's (bench.py asserts the held-flat envelope
    # and full verification itself; the contract here pins the shape)
    journal = results[8]
    assert journal["metric"] == "journal_record_us"
    assert journal["value"] > 0
    assert journal["journal_records"] > 0
    assert journal["journal_nodes"] > 0
    assert journal["journal_replay_verified"] == journal["journal_nodes"]
    assert journal["attached_e2e_p95_ms"] > 0
    assert journal["baseline_e2e_p95_ms"] > 0
    # the convergence-under-loss line (ISSUE 18 'tenth metric line'): the
    # flap batch re-run behind a seeded chaos mesh dropping KvStore RPCs —
    # the dissemination plane must still converge, and the dropped-RPC
    # count proves the mesh actually interfered (bench.py asserts the
    # bounded-degradation envelope itself; the contract pins the shape)
    loss = results[9]
    assert loss["metric"] == "convergence_under_loss_p95_ms"
    assert loss["value"] > 0
    assert loss["chaos_loss"] > 0
    assert loss["chaos_kv_dropped"] >= 0
    assert loss["spans"] > 0
    assert loss["clean_e2e_p95_ms"] > 0


def test_bench_py_marks_fallback_degraded(capsys, monkeypatch):
    """A cpu-fallback run measures a reduced workload on the wrong
    hardware: every JSON line must say so explicitly so BENCH consumers
    treat it as an availability signal, never as a perf regression."""
    import bench

    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setenv("BENCH_CONVERGENCE", "0")
    monkeypatch.setattr(bench, "_probe_backend", lambda: "cpu-fallback")
    bench.main([])
    out = capsys.readouterr().out.strip().splitlines()
    for line in out:
        result = json.loads(line)
        assert result["backend"] == "cpu-fallback"
        assert result["degraded"] is True
        # the availability-signal contract: a degraded line still carries
        # the full metric shape, so dashboards can plot uptime without
        # special cases — only perf comparisons must skip it
        assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
        # phase-split columns are degraded-aware: the SPF/scale lines
        # keep their attribution fields on cpu-fallback rounds too
        if result["metric"].endswith("spf_recomputes_per_sec") or (
            result["metric"].endswith("_tiled_cold_solve_ms")
        ):
            assert {"h2d_ms", "relax_ms", "d2h_ms"} == set(result["phases"])
            # mem columns are degraded-aware too: a cpu-fallback round
            # still accounts its (reduced) working set on the ledger
            assert result["mem_peak_bytes"] > 0
            assert result["mem_predicted_bytes"] > 0


def test_bench_py_dead_backend_degrades_never_raises():
    """The BENCH_r02–r05 failure mode: a backend that passes the probe but
    dies inside the workload (jax.devices() raising mid-bench). The bench
    must route it through the breaker's degrade semantics — re-exec on
    JAX_PLATFORMS=cpu, exit 0, and emit `"degraded": true` JSON — never
    crash the round."""
    import subprocess
    import sys as _sys
    from pathlib import Path

    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",  # probe short-circuits; fault injected
            "BENCH_FAULT": "backend_unavailable",
            "BENCH_SMOKE": "1",
            "BENCH_CONVERGENCE": "0",  # keep the re-exec child lean
        }
    )
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    proc = subprocess.run(
        [_sys.executable, str(bench_path)],
        env=env,
        capture_output=True,
        timeout=500,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert lines, proc.stderr[-2000:]
    for line in lines:
        result = json.loads(line)
        assert result["degraded"] is True
        assert result["backend"] == "cpu-fallback"
        assert result["fault_kind"]
        assert {"metric", "value", "unit", "vs_baseline"} <= set(result)


def test_config_store_bench(capsys, monkeypatch):
    from benchmarks.config_store_bench import main

    results = run_and_parse(
        capsys, main, {"CS_KEYS": "50", "CS_VALUE_BYTES": "64"}, monkeypatch
    )
    assert results[0]["metric"] == "config_store_writes_per_sec"
