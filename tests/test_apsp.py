"""Blocked min-plus Floyd–Warshall APSP differential suite (docs/Apsp.md).

The resident all-pairs matrix must match the CPU Dijkstra oracle EXACTLY —
cold closes and warm re-closes alike — across randomized event sequences
on grid / Clos / random-chord WAN topologies, including partition/heal
(link flaps to INF and back), overload toggles, and INF-sentinel edge
cases; the staleness guard, numpy-FW fault fallback, shadow audit, KSP
warm layer seeding and the TE matrix borrow ride the same fixtures.
"""

import dataclasses
import random

import numpy as np
import pytest

from openr_tpu.apsp import (
    ApspState,
    build_allow_matrix,
    build_weight_matrix,
    np_floyd_warshall,
)
from openr_tpu.apsp.kernels import _fw_solver, fw_block_shape
from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.ops.graph import INF, compile_graph, refresh_graph
from openr_tpu.solver import SpfSolver, SolverSupervisor, SupervisorConfig, TpuSpfSolver
from openr_tpu.solver.supervisor import OPEN
from openr_tpu.testing.faults import FaultInjected, injected
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges, wan_edges
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def build_ls(edges, area="0"):
    dbs = build_adj_dbs(edges, area=area)
    ls = LinkState(area)
    for db in dbs.values():
        ls.update_adjacency_database(db)
    return dbs, ls


def oracle_apsp(ls: LinkState, graph) -> np.ndarray:
    """CPU Dijkstra oracle: per-source LinkState SPF metrics arranged in
    the compiled graph's node numbering, INF-padded."""
    n = graph.n_pad
    d = np.full((n, n), INF, dtype=np.int32)
    np.fill_diagonal(d, 0)
    for src, i in graph.node_index.items():
        res = ls.get_spf_result(src)
        for dst, node in res.items():
            j = graph.node_index.get(dst)
            if j is not None:
                d[i, j] = node.metric
    return d


def set_metric(dbs, ls, a, b, metric):
    dbs[a] = dataclasses.replace(
        dbs[a],
        adjacencies=[
            dataclasses.replace(adj, metric=metric)
            if adj.other_node_name == b
            else adj
            for adj in dbs[a].adjacencies
        ],
    )
    ls.update_adjacency_database(dbs[a])


def set_adj_overload(dbs, ls, a, b, overloaded):
    dbs[a] = dataclasses.replace(
        dbs[a],
        adjacencies=[
            dataclasses.replace(adj, is_overloaded=overloaded)
            if adj.other_node_name == b
            else adj
            for adj in dbs[a].adjacencies
        ],
    )
    ls.update_adjacency_database(dbs[a])


def set_node_overload(dbs, ls, node, overloaded):
    dbs[node] = dataclasses.replace(dbs[node], is_overloaded=overloaded)
    ls.update_adjacency_database(dbs[node])


TOPOLOGIES = [
    ("grid", lambda: grid_edges(4)),
    (
        "clos",
        lambda: fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        ),
    ),
    ("wan", lambda: wan_edges(24, degree=3, seed=11)),
]


class TestApspDifferential:
    """Cold + warm re-close vs the CPU Dijkstra oracle."""

    @pytest.mark.parametrize("name,mk", TOPOLOGIES, ids=[t[0] for t in TOPOLOGIES])
    def test_randomized_event_sequences(self, name, mk):
        dbs, ls = build_ls(mk())
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=4096)
        assert apsp.ensure(graph)
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))
        assert apsp.cold_closes == 1

        rng = random.Random(hash(name) & 0xFFFF)
        links = [
            (link.n1, link.n2) for link in sorted(ls.all_links)
        ]
        warm_seen = 0
        for _ in range(12):
            a, b = links[rng.randrange(len(links))]
            kind = rng.choice(("metric", "flap"))
            if kind == "metric":
                set_metric(dbs, ls, a, b, rng.randint(1, 9))
            else:
                # adjacency overload = the link drops to INF (partition
                # when it is a cut edge) and later heals
                up = any(
                    adj.other_node_name == b and not adj.is_overloaded
                    for adj in dbs[a].adjacencies
                )
                set_adj_overload(dbs, ls, a, b, up)
            graph = refresh_graph(graph, ls)
            assert apsp.ensure(graph)
            assert np.array_equal(apsp.d, oracle_apsp(ls, graph)), (
                name,
                kind,
                (a, b),
            )
            warm_seen = max(warm_seen, apsp.warm_closes)
        # the sequences are weight-only events: the warm path must have
        # actually served (a suite that silently cold-closes every event
        # would still pass the parity checks)
        assert warm_seen > 0

    def test_partition_and_heal(self):
        # line topology: dropping a middle link partitions the graph
        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
        dbs, ls = build_ls(edges)
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64)
        apsp.ensure(graph)
        set_adj_overload(dbs, ls, "b", "c", True)
        graph = refresh_graph(graph, ls)
        apsp.ensure(graph)
        d = apsp.d
        idx = graph.node_index
        assert d[idx["a"], idx["d"]] >= INF  # partitioned: sentinel holds
        assert np.array_equal(d, oracle_apsp(ls, graph))
        set_adj_overload(dbs, ls, "b", "c", False)
        graph = refresh_graph(graph, ls)
        apsp.ensure(graph)
        assert apsp.d[idx["a"], idx["d"]] == 3  # healed
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))

    def test_node_overload_toggle_recloses_and_matches(self):
        dbs, ls = build_ls(grid_edges(3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64)
        apsp.ensure(graph)
        cold0 = apsp.cold_closes
        set_node_overload(dbs, ls, "g1_1", True)
        graph = refresh_graph(graph, ls)
        apsp.ensure(graph)
        # a transit-mask change re-masks every pair: must close cold
        assert apsp.cold_closes == cold0 + 1
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))
        set_node_overload(dbs, ls, "g1_1", False)
        graph = refresh_graph(graph, ls)
        apsp.ensure(graph)
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))

    def test_inf_sentinel_never_wraps(self):
        # two components: every cross-pair must sit exactly at INF after
        # the blocked close (a wrapped sentinel would show as negative or
        # a huge-but-finite value)
        edges = [("a", "b", 1), ("c", "d", 1)]
        _, ls = build_ls(edges)
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64)
        apsp.ensure(graph)
        d = apsp.d
        idx = graph.node_index
        assert d[idx["a"], idx["c"]] == INF
        assert d[idx["c"], idx["b"]] == INF
        assert d.min() >= 0
        assert d.max() == INF

    def test_kernel_matches_numpy_fw_on_random_matrices(self):
        # kernel-level differential, independent of LinkState: random
        # direct-edge matrices with INF holes and overloaded nodes
        rng = np.random.default_rng(7)
        for _ in range(5):
            n_pad = int(rng.choice([8, 16, 32]))
            w = np.full((n_pad, n_pad), INF, dtype=np.int32)
            mask = rng.random((n_pad, n_pad)) < 0.3
            w[mask] = rng.integers(1, 50, size=int(mask.sum()))
            np.fill_diagonal(w, 0)
            ov = rng.random(n_pad) < 0.2
            import jax.numpy as jnp

            nb, bsz = fw_block_shape(n_pad)
            d, _ = _fw_solver((nb, bsz))(
                jnp.asarray(w), jnp.asarray(build_allow_matrix(ov))
            )
            assert np.array_equal(np.array(d), np_floyd_warshall(w, ov))


class TestStalenessGuard:
    """Any event that poisons the warm solve also invalidates the matrix."""

    def _solver_and_state(self, edges, me):
        dbs, ls = build_ls(edges)
        solver = TpuSpfSolver(me, apsp_max_nodes=4096)
        ps = PrefixState()
        solver.build_route_db(me, {"0": ls}, ps)
        solve = solver._solves[("0", me)][1]
        solve.ensure_apsp()
        return dbs, ls, ps, solver, solve

    def test_batch_cold_solve_invalidates(self):
        dbs, ls, ps, solver, solve = self._solver_and_state(
            grid_edges(3), "g0_0"
        )
        assert solve.apsp.resident()
        # an adjacency flap incident to me changes the source batch rows
        # and forces the batch solve cold — the guard must drop the matrix
        set_adj_overload(dbs, ls, "g0_0", "g0_1", True)
        solver.build_route_db("g0_0", {"0": ls}, ps)
        solve = solver._solves[("0", "g0_0")][1]
        assert solve.apsp.invalidations >= 1
        assert not solve.apsp.resident() or solve.apsp.stale_reason is None
        # ... and the next ensure() serves a correct matrix again
        assert solve.ensure_apsp()
        graph = solve.graph
        assert np.array_equal(solve.apsp.d, oracle_apsp(ls, graph))

    def test_patch_overflow_forces_cold_close(self):
        dbs, ls = build_ls(wan_edges(40, degree=4, seed=3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=4096)
        apsp.ensure(graph)
        # bulk event: raise more pair minima than the warm patch budget
        # (every directed pair increases, well past _APSP_PATCH_SLOTS)
        rng = random.Random(5)
        for link in sorted(ls.all_links):
            set_metric(dbs, ls, link.n1, link.n2, rng.randint(200, 260))
            set_metric(dbs, ls, link.n2, link.n1, rng.randint(200, 260))
        graph = refresh_graph(graph, ls)
        cold0 = apsp.cold_closes
        apsp.ensure(graph)
        assert apsp.cold_closes == cold0 + 1  # overflow -> cold, not warm
        assert apsp.invalidations >= 1
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))

    def test_graph_too_large_disables(self):
        _, ls = build_ls(grid_edges(3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=4)  # 9-node grid exceeds the cap
        assert not apsp.ensure(graph)
        assert not apsp.resident()


class TestFaultDomain:
    """Device-close faults degrade to numpy FW and feed the breaker."""

    def test_injected_fault_falls_back_to_numpy(self):
        _, ls = build_ls(grid_edges(3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64)
        with injected() as inj:
            inj.arm("solver.apsp.close", times=1)
            assert apsp.ensure(graph)
        assert apsp.backend == "numpy"
        assert apsp.fallback_closes == 1
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))
        # next event: device path recovers (numpy-resident closes cold)
        apsp.invalidate("test")
        assert apsp.ensure(graph)
        assert apsp.backend == "device"

    def test_supervised_close_faults_feed_the_breaker(self):
        dbs, ls = build_ls(grid_edges(3))
        primary = TpuSpfSolver("g0_0", apsp_max_nodes=64)
        sup = SolverSupervisor(
            primary,
            SpfSolver("g0_0"),
            SupervisorConfig(failure_threshold=2, max_attempts=1),
        )
        ps = PrefixState()
        sup.build_route_db("g0_0", {"0": ls}, ps)
        solve = primary._solves[("0", "g0_0")][1]
        with injected() as inj:
            inj.arm("solver.apsp.close", times=3, exc=FaultInjected)
            assert solve.ensure_apsp()  # degraded to numpy, no raise
            assert solve.apsp.backend == "numpy"
            assert sup.consecutive_failures >= 1
            # a second faulted close reaches the threshold: breaker opens
            solve.apsp.invalidate("test")
            solve.ensure_apsp()
        assert sup.state == OPEN
        assert sup.counters["decision.spf.solver_failures"] >= 2

    def test_shadow_audit_detects_and_heals_corruption(self):
        dbs, ls = build_ls(grid_edges(3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64, audit_interval=1)
        apsp.ensure(graph)
        assert apsp.audit_runs == 1 and apsp.audit_mismatches == 0
        # corrupt the resident matrix behind the state's back, then push a
        # real weight event through: the warm re-close seeds from the
        # corrupted matrix, and the every-Nth audit must catch the
        # divergence and self-heal with a cold close in the same ensure
        import jax.numpy as jnp

        apsp._d_dev = jnp.asarray(apsp.d + 1)
        apsp._d_host = None
        set_metric(dbs, ls, "g2_2", "g2_1", 7)
        graph = refresh_graph(graph, ls)
        apsp.ensure(graph)
        assert apsp.audit_mismatches >= 1
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))

    def test_audit_mismatch_counter_and_selfheal(self):
        _, ls = build_ls(grid_edges(3))
        graph = compile_graph(ls)
        apsp = ApspState(max_nodes=64, audit_interval=1)
        apsp.ensure(graph)
        import jax.numpy as jnp

        corrupted = apsp.d.copy()
        corrupted[0, -1] = 5  # fabricate a distance
        apsp._d_dev = jnp.asarray(corrupted)
        apsp._d_host = None
        apsp._maybe_audit(graph)
        assert apsp.audit_mismatches == 1
        assert np.array_equal(apsp.d, oracle_apsp(ls, graph))


class TestConsumers:
    """LFA/_spf views, KSP warm seeding, TE borrow."""

    def test_arbitrary_source_spf_view_matches_oracle(self):
        dbs, ls = build_ls(wan_edges(18, degree=3, seed=9))
        me = sorted(dbs)[0]
        solver = TpuSpfSolver(me, apsp_max_nodes=4096)
        solver.build_route_db(me, {"0": ls}, PrefixState())
        for src in sorted(dbs)[1:]:
            view = solver._spf(ls, src)
            ref = ls.get_spf_result(src)
            for dest in sorted(dbs):
                assert (dest in view) == (dest in ref), (src, dest)
                if dest in ref:
                    assert view[dest].metric == ref[dest].metric
                    assert view[dest].next_hops == ref[dest].next_hops

    def test_arbitrary_pair_dist_matches_oracle(self):
        dbs, ls = build_ls(grid_edges(4))
        solver = TpuSpfSolver("g0_0", apsp_max_nodes=4096)
        solver.build_route_db("g0_0", {"0": ls}, PrefixState())
        rng = random.Random(2)
        nodes = sorted(dbs)
        for _ in range(20):
            a, b = rng.choice(nodes), rng.choice(nodes)
            assert solver._dist(ls, a, b) == ls.get_metric_from_a_to_b(a, b)

    def _ksp_route_db(self, warm_start):
        dbs, ls = build_ls(grid_edges(4))
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase(
                "g3_3",
                [
                    PrefixEntry(
                        IpPrefix("10.9.0.0/16"),
                        forwarding_type=PrefixForwardingType.SR_MPLS,
                        forwarding_algorithm=(
                            PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        ),
                    )
                ],
                area="0",
            )
        )
        solver = TpuSpfSolver("g0_0", warm_start=warm_start)
        db = solver.build_route_db("g0_0", {"0": ls}, ps)
        solve = solver._solves[("0", "g0_0")][1]
        return db, solve, ls, ps

    def test_ksp_warm_seeding_matches_cold_and_oracle(self):
        warm_db, warm_solve, ls, ps = self._ksp_route_db(True)
        cold_db, cold_solve, _, _ = self._ksp_route_db(False)
        assert warm_solve.ksp_warm_batches > 0
        assert cold_solve.ksp_warm_batches == 0
        oracle = SpfSolver("g0_0").build_route_db("g0_0", {"0": ls}, ps)
        for db in (cold_db, oracle):
            assert set(warm_db.unicast_entries) == set(db.unicast_entries)
            for prefix, entry in warm_db.unicast_entries.items():
                assert db.unicast_entries[prefix] == entry, prefix

    def test_te_borrow_serves_exact_matrix(self):
        from openr_tpu.te import TeService

        dbs, ls = build_ls(grid_edges(3))
        me = "g0_0"
        solver = TpuSpfSolver(me, apsp_max_nodes=4096)
        solver.build_route_db(me, {"0": ls}, PrefixState())
        svc = TeService(me, {"0": ls}, solver=solver)
        report = svc.optimize({"steps": 4, "scenarios": 1})
        assert svc.counters.get("decision.te.apsp_borrows", 0) == 1
        # identical run without a borrowing solver: same hard scores
        svc_plain = TeService(me, {"0": ls})
        ref = svc_plain.optimize({"steps": 4, "scenarios": 1})
        assert report["initial_max_util"] == ref["initial_max_util"]
        assert report["top_links"]["initial"] == ref["top_links"]["initial"]

    def test_borrow_refuses_stale_or_drained(self):
        dbs, ls = build_ls(grid_edges(3))
        solver = TpuSpfSolver("g0_0", apsp_max_nodes=4096)
        solver.build_route_db("g0_0", {"0": ls}, PrefixState())
        assert solver.borrow_apsp("0", ls.version) is not None
        assert solver.borrow_apsp("0", ls.version + 1) is None  # stale
        assert solver.borrow_apsp("missing", ls.version) is None
        set_node_overload(dbs, ls, "g1_1", True)
        solver.build_route_db("g0_0", {"0": ls}, PrefixState())
        assert solver.borrow_apsp("0", ls.version) is None  # drained

    def test_apsp_counters_flow_through_sync(self):
        dbs, ls = build_ls(grid_edges(3))
        solver = TpuSpfSolver("g0_0", apsp_max_nodes=4096)
        ps = PrefixState()
        solver.build_route_db("g0_0", {"0": ls}, ps)
        solve = solver._solves[("0", "g0_0")][1]
        solve.ensure_apsp()
        set_metric(dbs, ls, "g2_2", "g2_1", 5)
        solver.build_route_db("g0_0", {"0": ls}, ps)
        solve.ensure_apsp()
        # a cross-pair read outside the batch fetches the mirror (d2h)
        assert solver._dist(ls, "g2_2", "g0_1") is not None
        # one more rebuild so the post-ensure deltas fold into counters
        solver.build_route_db("g0_0", {"0": ls}, ps)
        assert solver.counters.get("decision.spf.apsp_closes", 0) >= 2
        assert solver.counters.get("decision.spf.apsp_cold_closes", 0) >= 1
        assert "decision.spf.apsp_close_ms" in solver._ensure_histograms()
        assert solver.counters.get("decision.spf.apsp_d2h_bytes", 0) > 0
