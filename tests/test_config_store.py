"""PersistentStore tests (openr/config-store/tests/PersistentStoreTest.cpp
equivalents): store/load/erase roundtrip, restart durability, obj helpers,
corrupt-file tolerance."""

import asyncio
import os

from openr_tpu.configstore import PersistentStore
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType


def test_store_load_erase(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    assert store.load("missing") is None
    store.store("key1", b"value1")
    store.store("key2", b"value2")
    assert store.load("key1") == b"value1"
    assert store.erase("key1") is True
    assert store.erase("key1") is False
    assert store.load("key1") is None
    assert store.load("key2") == b"value2"


def test_survives_restart(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    store.store("drain-state", b"DRAINED")
    store.store("metric", b"42")
    store.erase("metric")
    store.flush()
    assert store.num_writes_to_disk >= 1

    reopened = PersistentStore(path)
    assert reopened.load("drain-state") == b"DRAINED"
    assert reopened.load("metric") is None


def test_obj_helpers_roundtrip(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    entry = PrefixEntry(prefix=IpPrefix("10.0.0.0/24"), type=PrefixType.BGP)
    store.store_obj("obj", {"entries": [entry], "index": 7})
    store.flush()

    reopened = PersistentStore(path)
    loaded = reopened.load_obj("obj")
    assert loaded["index"] == 7
    assert loaded["entries"][0] == entry


def test_corrupt_file_tolerated(tmp_path):
    path = str(tmp_path / "store.bin")
    with open(path, "wb") as f:
        f.write(b"garbage not a store")
    store = PersistentStore(path)
    assert store.data == {}
    store.store("k", b"v")
    store.flush()
    assert PersistentStore(path).load("k") == b"v"


def test_write_behind_on_event_loop(tmp_path):
    path = str(tmp_path / "store.bin")

    async def body():
        store = PersistentStore(path)
        for i in range(20):
            store.store(f"k{i}", str(i).encode())
        # write-behind: not yet flushed (backoff pending)
        await asyncio.sleep(0.3)
        assert store.num_writes_to_disk >= 1
        # debounce batched all 20 writes into few disk writes
        assert store.num_writes_to_disk <= 3
        store.stop()

    asyncio.new_event_loop().run_until_complete(body())
    assert PersistentStore(path).load("k19") == b"19"


def test_dryrun_writes_nothing(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path, dryrun=True)
    store.store("k", b"v")
    store.flush()
    assert not os.path.exists(path)
