"""PersistentStore tests (openr/config-store/tests/PersistentStoreTest.cpp
equivalents): store/load/erase roundtrip, restart durability, obj helpers,
corrupt-file tolerance, and the crash-consistency fuzz suite (truncated
journal tail, torn snapshot record, mid-compaction kill, fault-injected
save/load) pinning recovery to the last durable state."""

import asyncio
import os

from openr_tpu.configstore import PersistentStore
from openr_tpu.testing.faults import injected
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType


def test_store_load_erase(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    assert store.load("missing") is None
    store.store("key1", b"value1")
    store.store("key2", b"value2")
    assert store.load("key1") == b"value1"
    assert store.erase("key1") is True
    assert store.erase("key1") is False
    assert store.load("key1") is None
    assert store.load("key2") == b"value2"


def test_survives_restart(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    store.store("drain-state", b"DRAINED")
    store.store("metric", b"42")
    store.erase("metric")
    store.flush()
    assert store.num_writes_to_disk >= 1

    reopened = PersistentStore(path)
    assert reopened.load("drain-state") == b"DRAINED"
    assert reopened.load("metric") is None


def test_obj_helpers_roundtrip(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    entry = PrefixEntry(prefix=IpPrefix("10.0.0.0/24"), type=PrefixType.BGP)
    store.store_obj("obj", {"entries": [entry], "index": 7})
    store.flush()

    reopened = PersistentStore(path)
    loaded = reopened.load_obj("obj")
    assert loaded["index"] == 7
    assert loaded["entries"][0] == entry


def test_corrupt_file_tolerated(tmp_path):
    path = str(tmp_path / "store.bin")
    with open(path, "wb") as f:
        f.write(b"garbage not a store")
    store = PersistentStore(path)
    assert store.data == {}
    store.store("k", b"v")
    store.flush()
    assert PersistentStore(path).load("k") == b"v"


def test_write_behind_on_event_loop(tmp_path):
    path = str(tmp_path / "store.bin")

    async def body():
        store = PersistentStore(path)
        for i in range(20):
            store.store(f"k{i}", str(i).encode())
        # write-behind: not yet flushed (backoff pending)
        await asyncio.sleep(0.3)
        assert store.num_writes_to_disk >= 1
        # debounce batched all 20 writes into few disk writes
        assert store.num_writes_to_disk <= 3
        store.stop()

    asyncio.new_event_loop().run_until_complete(body())
    assert PersistentStore(path).load("k19") == b"19"


def test_dryrun_writes_nothing(tmp_path):
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path, dryrun=True)
    store.store("k", b"v")
    store.flush()
    assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# crash-consistency fuzz (graceful-restart warm boot depends on these)
# ---------------------------------------------------------------------------


def _journaled_store(path, n=6):
    """A store whose file holds one snapshot + n separate journal
    appends (each flush is its own fsynced append)."""
    store = PersistentStore(path)
    store.flush()  # snapshot the empty store
    for i in range(n):
        store.store(f"k{i}", f"v{i}".encode())
        store.flush()
    assert store.num_journal_appends >= 1, "appends must exercise"
    return store


def test_journal_appends_not_rewrites(tmp_path):
    """Consecutive flushes append journal records instead of rewriting
    the snapshot; a journal outgrowing the snapshot compacts."""
    path = str(tmp_path / "store.bin")
    store = _journaled_store(path)
    assert store.num_compactions >= 1  # the initial snapshot
    reopened = PersistentStore(path)
    for i in range(6):
        assert reopened.load(f"k{i}") == f"v{i}".encode()
    # grow the journal well past the snapshot: compaction happens
    big = b"x" * 4096
    store.store("big", big)
    store.flush()
    store.store("big2", big)
    store.flush()
    assert store.num_compactions >= 2
    assert PersistentStore(path).load("big2") == big


def test_truncated_journal_tail_recovers_prefix(tmp_path):
    """Fuzz: truncate the file at EVERY byte offset. Load must never
    crash and must always recover a prefix of the applied operations —
    the last durable state, not an empty store."""
    path = str(tmp_path / "store.bin")
    _journaled_store(path).stop()
    raw = open(path, "rb").read()
    # the historical states after each prefix of operations
    history = [
        {f"k{j}": f"v{j}".encode() for j in range(i)} for i in range(7)
    ]
    for cut in range(len(raw)):
        with open(path, "wb") as f:
            f.write(raw[:cut])
        reopened = PersistentStore(path)
        assert reopened.data in history, (cut, reopened.data)
    # after a truncated load, the store must keep working: the next
    # flush compacts (never appends after garbage)
    with open(path, "wb") as f:
        f.write(raw[: len(raw) - 3])
    survivor = PersistentStore(path)
    assert survivor.num_load_truncations == 1
    survivor.store("fresh", b"F")
    survivor.flush()
    assert survivor.num_compactions >= 1
    final = PersistentStore(path)
    assert final.load("fresh") == b"F"
    assert final.num_load_truncations == 0


def test_torn_snapshot_record_recovers(tmp_path):
    """A corrupted snapshot body (torn sector) must not crash load; the
    journal after it is unreachable, so recovery is the pre-snapshot
    state (empty), and the store stays usable."""
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    store.store("a", b"1")
    store.flush()  # snapshot with data
    raw = bytearray(open(path, "rb").read())
    # flip bytes in the middle of the snapshot payload
    mid = len(raw) // 2
    raw[mid] ^= 0xFF
    raw[mid + 1] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))
    reopened = PersistentStore(path)
    assert reopened.data == {}
    reopened.store("b", b"2")
    reopened.flush()
    assert PersistentStore(path).load("b") == b"2"


def test_mid_compaction_kill_keeps_previous_file(tmp_path):
    """tmp+rename discipline: a kill between writing the .tmp and the
    rename leaves the previous file authoritative; the stray .tmp is
    ignored by load and the next flush replaces it."""
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    store.store("durable", b"YES")
    store.flush()
    # simulate the kill: a partial compaction artifact next to the file
    with open(path + ".tmp", "wb") as f:
        f.write(b"ONRPS1\n\x00partial-garbage")
    reopened = PersistentStore(path)
    assert reopened.load("durable") == b"YES"
    reopened.store("more", b"M")
    reopened.flush()
    assert PersistentStore(path).load("more") == b"M"
    # the snapshot path reuses (and atomically replaces via) the tmp name
    assert not os.path.exists(path + ".tmp") or os.path.getsize(
        path + ".tmp"
    ) == 0 or PersistentStore(path).load("durable") == b"YES"


def test_save_fault_keeps_journal_and_retries(tmp_path):
    """configstore.save fault point: an injected write failure keeps the
    journal pending (nothing lost) and a later flush lands it."""
    path = str(tmp_path / "store.bin")

    async def body():
        store = PersistentStore(path)
        with injected() as inj:
            inj.arm("configstore.save", times=1)
            store.store("k", b"v")
            # wait out the write-behind debounce + the retry backoff
            for _ in range(200):
                await asyncio.sleep(0.02)
                if store.num_writes_to_disk >= 1:
                    break
        assert store.num_write_failures == 1
        assert store.num_writes_to_disk >= 1
        store.stop()

    asyncio.new_event_loop().run_until_complete(body())
    assert PersistentStore(path).load("k") == b"v"


def test_load_fault_degrades_to_empty(tmp_path):
    """configstore.load fault point: an injected read failure is the
    corrupt-database case — empty store, daemon boots anyway."""
    path = str(tmp_path / "store.bin")
    store = PersistentStore(path)
    store.store("k", b"v")
    store.stop()
    with injected() as inj:
        inj.arm("configstore.load", times=1)
        degraded = PersistentStore(path)
    assert degraded.data == {}
    assert degraded.num_load_errors == 1
    # and the file itself was untouched: a clean reopen still has it
    assert PersistentStore(path).load("k") == b"v"
