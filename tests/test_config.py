"""Config tests (openr/config/tests/ConfigTest.cpp equivalents): JSON load,
defaults, validation, area regex matching, feature predicates."""

import json

import pytest

from openr_tpu.config import Config, OpenrConfig
from openr_tpu.types import PrefixForwardingAlgorithm, PrefixForwardingType


def test_defaults_match_reference():
    cfg = Config.from_dict({"node_name": "n1"})
    c = cfg.config
    assert c.openr_ctrl_port == 2018
    assert c.kvstore_config.key_ttl_ms == 300_000
    assert c.kvstore_config.sync_interval_s == 60
    assert c.spark_config.hello_time_s == 20.0
    assert c.spark_config.keepalive_time_s == 2.0
    assert c.spark_config.hold_time_s == 10.0
    assert c.spark_config.graceful_restart_time_s == 30.0
    assert c.spark_config.fastinit_hello_time_ms == 500.0
    assert c.link_monitor_config.linkflap_initial_backoff_ms == 60_000
    assert c.link_monitor_config.linkflap_max_backoff_ms == 300_000
    assert c.watchdog_config.thread_timeout_s == 300
    assert c.watchdog_config.max_memory_mb == 800
    assert c.prefix_forwarding_type == PrefixForwardingType.IP
    assert (
        c.prefix_forwarding_algorithm == PrefixForwardingAlgorithm.SP_ECMP
    )


def test_node_name_required():
    with pytest.raises(ValueError):
        Config.from_dict({})


def test_unknown_field_rejected():
    with pytest.raises(ValueError, match="unknown config field"):
        Config.from_dict({"node_name": "n1", "not_a_field": 1})


def test_load_file(tmp_path):
    path = tmp_path / "openr.json"
    path.write_text(
        json.dumps(
            {
                "node_name": "node-7",
                "domain": "test",
                "openr_ctrl_port": 3018,
                "enable_segment_routing": True,
                "kvstore_config": {"key_ttl_ms": 60000},
                "spark_config": {"hello_time_s": 5},
                "areas": [
                    {
                        "area_id": "pod-1",
                        "interface_regexes": ["eth[0-9]+"],
                        "neighbor_regexes": ["rsw.*"],
                    }
                ],
            }
        )
    )
    cfg = Config.load_file(str(path))
    assert cfg.node_name == "node-7"
    assert cfg.config.openr_ctrl_port == 3018
    assert cfg.is_segment_routing_enabled()
    assert cfg.config.kvstore_config.key_ttl_ms == 60000
    assert cfg.config.kvstore_config.sync_interval_s == 60  # default kept
    assert cfg.config.spark_config.hello_time_s == 5


def test_area_matching():
    cfg = Config.from_dict(
        {
            "node_name": "n1",
            "areas": [
                {
                    "area_id": "spine",
                    "interface_regexes": [],
                    "neighbor_regexes": ["ssw.*"],
                },
                {
                    "area_id": "rack",
                    "interface_regexes": ["eth[0-9]"],
                    "neighbor_regexes": [],
                },
            ],
        }
    )
    assert cfg.get_area_ids() == ["spine", "rack"]
    assert cfg.get_area_for(neighbor_name="ssw001") == "spine"
    assert cfg.get_area_for(if_name="eth0") == "rack"
    assert cfg.get_area_for(if_name="po1", neighbor_name="fsw1") is None


def test_no_areas_default():
    cfg = Config.from_dict({"node_name": "n1"})
    assert cfg.get_area_ids() == ["0"]
    assert cfg.get_area_for(if_name="anything") == "0"
