"""Test configuration.

Tests run on a virtual 8-device CPU platform so that multi-chip sharding code
paths (jax.sharding.Mesh over 8 devices) are exercised without TPU hardware,
mirroring how the driver dry-runs the multichip path.

A pytest plugin pre-imports jax before this file runs, so setting
JAX_PLATFORMS in os.environ is not enough — the jax config must be updated
directly (safe because no backend is initialized yet at collection time).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # the environment presets axon (real TPU)
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
