"""Native FIB agent integration: the standalone onl_fib_agent binary
(native/platform/onl_fib_agent.cpp, the platform_linux equivalent) driven
end-to-end over its JSON wire protocol by RemoteFibService and by the full
Fib module, in --dryrun mode (no kernel writes, no privileges needed)."""

import asyncio
import os
import subprocess

import pytest

from openr_tpu.fib import Fib, FibConfig
from openr_tpu.messaging import RWQueue
from openr_tpu.platform import FIB_CLIENT_OPENR, PlatformError
from openr_tpu.platform.remote import AGENT_PATH, RemoteFibService, spawn_agent
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.solver.routes import RibMplsEntry, RibUnicastEntry
from openr_tpu.types import (
    IpPrefix,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
)


def _ensure_agent():
    if not os.path.exists(AGENT_PATH):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            subprocess.run(
                ["make", "-C", os.path.join(root, "native")],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception as exc:  # pragma: no cover - toolchain missing
            pytest.skip(f"native agent unavailable: {exc}")


@pytest.fixture
def agent():
    _ensure_agent()
    proc, port = spawn_agent(dryrun=True)
    yield port
    proc.kill()
    proc.wait()


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def nh(addr, iface="eth0", label=None, push=None):
    action = None
    if label is not None:
        action = MplsAction(MplsActionCode.SWAP, swap_label=label)
    elif push is not None:
        action = MplsAction(MplsActionCode.PUSH, push_labels=tuple(push))
    return NextHop(address=addr, iface=iface, mpls_action=action)


class TestWireProtocol:
    def test_unicast_roundtrip(self, agent):
        async def body():
            svc = RemoteFibService(port=agent)
            t0 = await svc.alive_since()
            assert t0 > 0

            routes = [
                UnicastRoute(
                    IpPrefix("10.1.0.0/24"),
                    (nh("fe80::1"), nh("fe80::2", "eth1")),
                ),
                UnicastRoute(IpPrefix("10.2.0.0/24"), (nh("fe80::3"),)),
            ]
            await svc.add_unicast_routes(FIB_CLIENT_OPENR, routes)
            got = await svc.get_route_table_by_client(FIB_CLIENT_OPENR)
            assert {str(r.dest) for r in got} == {
                "10.1.0.0/24",
                "10.2.0.0/24",
            }
            two = next(r for r in got if str(r.dest) == "10.1.0.0/24")
            assert {(n.address, n.iface) for n in two.nexthops} == {
                ("fe80::1", "eth0"),
                ("fe80::2", "eth1"),
            }

            await svc.delete_unicast_routes(
                FIB_CLIENT_OPENR, [IpPrefix("10.2.0.0/24")]
            )
            got = await svc.get_route_table_by_client(FIB_CLIENT_OPENR)
            assert {str(r.dest) for r in got} == {"10.1.0.0/24"}

            # syncFib drops everything not in the desired set
            await svc.sync_fib(
                FIB_CLIENT_OPENR,
                [UnicastRoute(IpPrefix("10.9.0.0/16"), (nh("fe80::9"),))],
            )
            got = await svc.get_route_table_by_client(FIB_CLIENT_OPENR)
            assert {str(r.dest) for r in got} == {"10.9.0.0/16"}
            await svc.close()

        run(body())

    def test_mpls_roundtrip(self, agent):
        async def body():
            svc = RemoteFibService(port=agent)
            await svc.add_mpls_routes(
                FIB_CLIENT_OPENR,
                [
                    MplsRoute(100001, (nh("fe80::1", label=100002),)),
                    MplsRoute(100003, (nh("fe80::2", push=[1, 2, 3]),)),
                ],
            )
            got = await svc.get_mpls_route_table_by_client(FIB_CLIENT_OPENR)
            by_label = {r.top_label: r for r in got}
            assert set(by_label) == {100001, 100003}
            swap = next(iter(by_label[100001].nexthops))
            assert swap.mpls_action.action == MplsActionCode.SWAP
            assert swap.mpls_action.swap_label == 100002
            push = next(iter(by_label[100003].nexthops))
            assert push.mpls_action.push_labels == (1, 2, 3)

            await svc.sync_mpls_fib(
                FIB_CLIENT_OPENR, [MplsRoute(100001, (nh("fe80::1"),))]
            )
            got = await svc.get_mpls_route_table_by_client(FIB_CLIENT_OPENR)
            assert [r.top_label for r in got] == [100001]
            await svc.close()

        run(body())

    def test_error_on_unknown_method(self, agent):
        async def body():
            svc = RemoteFibService(port=agent)
            with pytest.raises(PlatformError, match="unknown method"):
                await svc._call("noSuchMethod")
            # connection still usable
            assert await svc.alive_since() > 0
            await svc.close()

        run(body())

    def test_agent_unreachable(self):
        async def body():
            svc = RemoteFibService(port=1)  # nothing listens there
            with pytest.raises(PlatformError, match="unreachable"):
                await svc.alive_since()

        run(body())


class TestFibModuleOverAgent:
    def test_full_fib_pipeline(self, agent):
        async def body():
            svc = RemoteFibService(port=agent)
            route_q, if_q = RWQueue(), RWQueue()
            fib = Fib(
                FibConfig(my_node_name="node-1"), svc, route_q, if_q
            )
            fib.start()

            async def synced():
                deadline = asyncio.get_event_loop().time() + 5
                while not fib.has_synced_fib:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.01)

            await synced()
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        RibUnicastEntry(
                            prefix=IpPrefix("10.0.0.0/24"),
                            nexthops={nh("fe80::1")},
                        )
                    ],
                    mpls_routes_to_update=[
                        RibMplsEntry(
                            label=100100,
                            nexthops={nh("fe80::1", label=100101)},
                        )
                    ],
                )
            )
            deadline = asyncio.get_event_loop().time() + 5
            while True:
                got = await svc.get_route_table_by_client(FIB_CLIENT_OPENR)
                if {str(r.dest) for r in got} == {"10.0.0.0/24"}:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            fib.stop()
            await svc.close()

        run(body())

    def test_agent_restart_detection(self):
        _ensure_agent()

        async def body():
            proc, port = spawn_agent(dryrun=True)
            try:
                svc = RemoteFibService(port=port)
                first = await svc.alive_since()
                assert first > 0
                proc.kill()
                proc.wait()
                # next call fails (connection lost)
                with pytest.raises(PlatformError):
                    await svc.alive_since()
                    await svc.alive_since()
                # agent comes back on the same port with a new aliveSince
                await asyncio.sleep(1.1)  # ensure clock tick
                proc2, _ = spawn_agent(port=port, dryrun=True)
                try:
                    second = await svc.alive_since()
                    assert second != first
                finally:
                    proc2.kill()
                    proc2.wait()
                await svc.close()
            finally:
                if proc.poll() is None:
                    proc.kill()

        run(body())
