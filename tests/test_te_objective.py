"""Temperature-annealing consistency suite for the differentiable TE core.

The softmin relaxation (openr_tpu/te/objective.py) is only trustworthy as
a TE objective if it provably approaches the routing the network actually
runs: as tau -> 0 the softmin distance matrix must converge to the hard
SPF oracle's distances (solver/cpu.py semantics — LinkState.run_spf's
Dijkstra — differentially, on randomized grid and Clos topologies), and
the soft traffic splits must approach exact fractional ECMP. The hard
numpy counterparts are pinned against the same oracle first, so the
optimizer's acceptance metric and the relaxation are anchored to one
ground truth.
"""

import random

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.ops.graph import INF, compile_graph
from openr_tpu.te.objective import (
    F_INF,
    hard_distances,
    hard_utilization,
    softmin_distances,
    soft_utilization,
    te_edge_arrays,
)
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def randomized(edges, seed, lo=1, hi=9):
    rng = random.Random(seed)
    return [(a, b, rng.randint(lo, hi)) for a, b, _ in edges]


def small_clos():
    return fabric_edges(2, planes=2, ssw_per_plane=2, fsw_per_pod=2,
                        rsw_per_pod=3)


def oracle_distance_matrix(ls: LinkState, graph) -> np.ndarray:
    """D[v, t] from the CPU oracle's Dijkstra (unreachable = INF)."""
    d = np.full((graph.n, graph.n), np.int64(INF))
    np.fill_diagonal(d, 0)
    for v, name in enumerate(graph.names):
        for dest, res in ls.get_spf_result(name).items():
            d[v, graph.node_index[dest]] = res.metric
    return d


TOPOLOGIES = [
    pytest.param(lambda s: randomized(grid_edges(4), s), id="grid4"),
    pytest.param(lambda s: randomized(small_clos(), s), id="clos2pod"),
]


class TestHardOracle:
    @pytest.mark.parametrize("topo", TOPOLOGIES)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_hard_distances_match_cpu_oracle(self, topo, seed):
        ls = build_ls(topo(seed))
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        got = hard_distances(w0, src_e, dst_e, up, graph.n)
        np.testing.assert_array_equal(got, oracle_distance_matrix(ls, graph))

    def test_down_link_never_relaxes(self):
        # flap a link down (overloaded adjacency -> INF weight in the
        # compiled arrays): the hard BF must route around it like Dijkstra
        import dataclasses

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        dbs = build_adj_dbs(edges)
        dbs["a"] = dataclasses.replace(
            dbs["a"],
            adjacencies=[
                dataclasses.replace(adj, is_overloaded=True)
                if adj.other_node_name == "b"
                else adj
                for adj in dbs["a"].adjacencies
            ],
        )
        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        d = hard_distances(w0, src_e, dst_e, up, graph.n)
        np.testing.assert_array_equal(
            d, oracle_distance_matrix(ls, graph)
        )
        assert d[graph.node_index["a"], graph.node_index["c"]] == 5


class TestAnnealing:
    @pytest.mark.parametrize("topo", TOPOLOGIES)
    @pytest.mark.parametrize("seed", [3, 7])
    def test_softmin_converges_to_hard_spf(self, topo, seed):
        """As tau -> 0 the softmin distances approach the oracle's, and the
        approximation error shrinks monotonically along the anneal — the
        property the optimizer's temperature schedule relies on."""
        ls = build_ls(topo(seed))
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        hard = oracle_distance_matrix(ls, graph).astype(np.float64)
        reachable = hard < INF

        taus = (2.0, 0.5, 0.1, 0.02)
        errors = []
        for tau in taus:
            soft = np.asarray(
                softmin_distances(
                    w0.astype(np.float32), src_e, dst_e, up,
                    tau, n=graph.n, rounds=graph.n,
                )
            ).astype(np.float64)
            # softmin is a lower bound on the hard min everywhere
            assert (soft[reachable] <= hard[reachable] + 1e-3).all()
            errors.append(float(np.abs(soft - hard)[reachable].max()))
        assert errors == sorted(errors, reverse=True)
        # error scale is tau * log(#near-shortest path combinations); pin
        # the constant so a regression that breaks convergence (e.g. a
        # wrong stabilization) cannot hide behind "still decreasing"
        assert errors[-1] <= 10 * taus[-1], errors
        # metrics are integers: at the end of the anneal, rounding the
        # relaxed distances must recover the oracle's matrix EXACTLY
        np.testing.assert_array_equal(np.rint(soft)[reachable],
                                      hard[reachable])

    @pytest.mark.parametrize("seed", [11])
    def test_unreachable_pairs_stay_at_sentinel(self, seed):
        # two disconnected components: cross-component softmin distances
        # must hold at the finite sentinel at every temperature
        edges = randomized(
            [("a", "b", 1), ("b", "c", 1), ("x", "y", 1)], seed
        )
        ls = build_ls(edges)
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        ia, ix = graph.node_index["a"], graph.node_index["x"]
        for tau in (2.0, 0.1):
            soft = np.asarray(
                softmin_distances(
                    w0.astype(np.float32), src_e, dst_e, up,
                    tau, n=graph.n, rounds=graph.n,
                )
            )
            assert soft[ia, ix] >= F_INF / 2
            assert soft[ix, ia] >= F_INF / 2

    @pytest.mark.parametrize("topo", TOPOLOGIES)
    def test_soft_utilization_converges_to_hard_ecmp(self, topo):
        """At low temperature the soft splits reproduce exact fractional
        ECMP link utilizations (the acceptance metric's routing model)."""
        ls = build_ls(topo(5))
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        rng = np.random.default_rng(5)
        demands = (
            rng.uniform(0.0, 2.0, size=(graph.n, graph.n))
            * (1.0 - np.eye(graph.n))
        ).astype(np.float32)
        caps = np.ones(graph.e, dtype=np.float32)
        hard = hard_utilization(
            w0, demands, caps, src_e, dst_e, up, graph.n
        )
        soft = np.asarray(
            soft_utilization(
                w0.astype(np.float32), demands, caps, src_e, dst_e, up,
                0.01, n=graph.n, rounds=graph.n,
            )
        )
        np.testing.assert_allclose(soft, hard, atol=0.02, rtol=0.02)
