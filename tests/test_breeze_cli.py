"""breeze CLI tests (openr/py/openr/cli equivalents): commands drive a real
ctrl server over TCP and print human-readable output."""

import asyncio
import threading

import pytest

from openr_tpu.cli.breeze import main as breeze_main
from openr_tpu.ctrl import CtrlServer
from openr_tpu.kvstore import InProcessTransport, KvStore
from openr_tpu.monitor import Monitor
from openr_tpu.types import AdjacencyDatabase, Adjacency, Value, adj_key
from openr_tpu.utils import serializer
from openr_tpu.utils.counters import Histogram


@pytest.fixture
def ctrl_endpoint():
    """Ctrl server on a background event loop thread; yields (host, port)."""
    started = threading.Event()
    state = {}

    def run_server():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        store = KvStore("cli-node", ["0"], InProcessTransport())
        adj_db = AdjacencyDatabase(
            this_node_name="cli-node",
            adjacencies=[
                Adjacency(
                    other_node_name="peer-1", if_name="eth0", metric=10
                )
            ],
        )
        store.set_key(
            adj_key("cli-node"),
            Value(1, "cli-node", serializer.dumps(adj_db)),
        )
        monitor = Monitor("cli-node")

        class _Hists:
            """Module exposing latency histograms (Decision stand-in)."""

            histograms = {}

        hist = Histogram()
        for v in (1.0, 2.0, 4.0):
            hist.record(v)
        _Hists.histograms = {"decision.spf.solve_ms": hist}
        monitor.register_module("decision", _Hists())

        class _FakeDecision:
            """Solver-health + TE surfaces only: `decision adj` must still
            error (no get_adjacency_databases), which test_decision_adj
            pins."""

            te_params = {}  # last runTeOptimize params, for assertions

            @staticmethod
            def get_solver_health():
                return {
                    "degraded": True,
                    "breaker_state": "open",
                    "fallback_active": 1,
                    "last_fault_kind": "device_loss",
                }

            @classmethod
            def run_te_optimize(cls, params):
                cls.te_params = dict(params)
                return {
                    "node": "cli-node",
                    "area": "0",
                    "nodes": 7,
                    "links": 18,
                    "scenarios": params.get("scenarios", 1),
                    "steps": params.get("steps", 80),
                    "best_step": 12,
                    "backend": "primary",
                    "degraded": False,
                    "improved": True,
                    "initial_max_util": 6.0,
                    "optimized_max_util": 2.0,
                    "max_util_delta": -4.0,
                    "weight_changes": [
                        {
                            "node": "l0_0",
                            "neighbor": "l1_0",
                            "iface": "if-l0_0-l1_0",
                            "metric_before": 1,
                            "metric_after": 3,
                        }
                    ],
                    "top_links": {
                        "initial": [
                            {"src": "l0_0", "dst": "l1_0", "util": 6.0}
                        ],
                        "optimized": [
                            {"src": "l0_0", "dst": "l1_0", "util": 2.0}
                        ],
                    },
                    "loss_first": 5.1,
                    "loss_last": 2.2,
                    "solve_ms": 41.5,
                }

        state["fake_decision"] = _FakeDecision

        server = CtrlServer(
            "cli-node",
            port=0,
            kvstore=store,
            monitor=monitor,
            decision=_FakeDecision(),
        )
        state["loop"] = loop
        state["port"] = loop.run_until_complete(server.start())
        started.set()
        loop.run_forever()
        loop.run_until_complete(server.stop())
        loop.close()

    thread = threading.Thread(target=run_server, daemon=True)
    thread.start()
    assert started.wait(10)
    yield "127.0.0.1", state["port"]
    state["loop"].call_soon_threadsafe(state["loop"].stop)
    thread.join(timeout=10)


def breeze(host, port, *argv):
    return breeze_main(["--host", host, "--port", str(port), *argv])


def test_openr_version(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "openr", "version") == 0
    out = capsys.readouterr().out
    assert "openr-tpu" in out
    assert "cli-node" in out


def test_kvstore_keys(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "kvstore", "keys") == 0
    out = capsys.readouterr().out
    assert "adj:cli-node" in out
    assert "cli-node" in out


def test_kvstore_peer_health(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "kvstore", "peer-health") == 0
    out = capsys.readouterr().out
    # no peers on the fixture store: the table renders headers only
    assert "Health" in out
    assert "Quarantined(ms)" in out


def test_kvstore_keys_prefix_filter(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "kvstore", "keys", "--prefix", "zzz") == 0
    out = capsys.readouterr().out
    assert "adj:cli-node" not in out


def test_decision_adj(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    # decision module not attached -> ctrl surfaces the assert as an error
    with pytest.raises(Exception):
        breeze(host, port, "decision", "adj")


def test_monitor_counters(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "monitor", "counters") == 0
    out = capsys.readouterr().out
    assert "process.uptime.seconds" in out


def test_monitor_histograms(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "monitor", "histograms") == 0
    out = capsys.readouterr().out
    # table header + the registered histogram with its stats rendered
    for token in ("Histogram", "Count", "p50", "p99"):
        assert token in out
    line = next(
        l for l in out.splitlines() if "decision.spf.solve_ms" in l
    )
    assert " 3 " in f" {line} "  # count column
    # p50 of {1, 2, 4} interpolates inside the 2.0 bucket
    assert "2." in line


def test_decision_solver_health(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "decision", "solver-health") == 0
    out = capsys.readouterr().out
    assert "solver: DEGRADED (breaker: open)" in out
    assert "device_loss" in out


def test_decision_te_optimize(ctrl_endpoint, capsys, tmp_path):
    host, port = ctrl_endpoint
    spec = tmp_path / "demands.json"
    spec.write_text(
        '{"demands": [["l0_0", "l1_0", 6.0]], "scenarios": 2}'
    )
    assert breeze(
        host, port, "decision", "te-optimize",
        "--demands", str(spec), "--steps", "17",
    ) == 0
    out = capsys.readouterr().out
    assert "max link util 6.000 -> 2.000" in out
    # the proposed-change table maps to `breeze lm set-link-metric` inputs
    for token in ("l0_0", "l1_0", "if-l0_0-l1_0", "Proposed"):
        assert token in out
    assert "hottest links" in out


def test_decision_te_optimize_json_and_param_passthrough(
    ctrl_endpoint, capsys
):
    import json as json_mod

    host, port = ctrl_endpoint
    assert breeze(
        host, port, "decision", "te-optimize", "--steps", "9",
        "--scenarios", "3", "--json",
    ) == 0
    report = json_mod.loads(capsys.readouterr().out)
    assert report["steps"] == 9
    assert report["scenarios"] == 3
    assert report["weight_changes"][0]["metric_after"] == 3


def test_monitor_histograms_reset(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    # --reset exports the window AND clears the sources
    assert breeze(host, port, "monitor", "histograms", "--reset") == 0
    line = next(
        l
        for l in capsys.readouterr().out.splitlines()
        if "decision.spf.solve_ms" in l
    )
    assert " 3 " in f" {line} "
    # the next window starts empty
    assert breeze(host, port, "monitor", "histograms") == 0
    line = next(
        l
        for l in capsys.readouterr().out.splitlines()
        if "decision.spf.solve_ms" in l
    )
    assert " 0 " in f" {line} "


def test_connection_refused_exit_code(capsys):
    assert breeze("127.0.0.1", 1, "openr", "version") == 1
    assert "cannot connect" in capsys.readouterr().err


def test_config_show_and_tech_support(ctrl_endpoint, capsys):
    host, port = ctrl_endpoint
    assert breeze(host, port, "config", "show") == 0
    capsys.readouterr()
    assert breeze(host, port, "tech-support") == 0
    out = capsys.readouterr().out
    assert "==== version ====" in out
    assert "==== kvstore-keys ====" in out
    assert "adj:cli-node" in out


def test_all_shortest_paths_enumeration():
    from openr_tpu.cli.breeze import _all_shortest_paths

    # square: a-b-d and a-c-d equal cost; a-d direct is more expensive
    graph = {
        "a": {"b": (1, "if-ab"), "c": (1, "if-ac"), "d": (5, "if-ad")},
        "b": {"d": (1, "if-bd")},
        "c": {"d": (1, "if-cd")},
        "d": {},
    }
    paths = _all_shortest_paths(graph, "a", "d")
    assert [(c, p) for c, p in paths] == [
        (2, ["a", "b", "d"]),
        (2, ["a", "c", "d"]),
    ]
    assert _all_shortest_paths(graph, "d", "a") == []


def test_perf_view_renders_events(capsys):
    from openr_tpu.cli.breeze import cmd_perf
    from openr_tpu.ctrl.client import encode_obj
    from openr_tpu.types import PerfEvent, PerfEvents

    perf = PerfEvents(
        events=[
            PerfEvent("node-a", "DECISION_RECEIVED", 1000),
            PerfEvent("node-a", "ROUTE_UPDATE", 1003),
        ]
    )

    class StubClient:
        def call(self, method, **params):
            assert method == "getPerfDb"
            return [encode_obj(perf)]

    cmd_perf(StubClient(), None)
    out = capsys.readouterr().out
    assert "DECISION_RECEIVED" in out
    assert "+0ms" in out
    assert "+3ms" in out


def test_monitor_scrape_renders_exposition(ctrl_endpoint, capsys):
    """`breeze monitor scrape` prints the registry in Prometheus text
    exposition format — the same bytes GET /metrics serves."""
    from openr_tpu.monitor.exporter import parse_metrics_text

    host, port = ctrl_endpoint
    assert breeze(host, port, "monitor", "scrape") == 0
    out = capsys.readouterr().out
    parsed = parse_metrics_text(out)
    assert "openr_process_uptime_seconds" in parsed["gauges"]
    hist = parsed["histograms"]["openr_decision_spf_solve_ms"]
    assert hist["count"] == 3


def test_perf_soak_report_renders_offline(capsys, tmp_path):
    """`breeze perf soak-report FILE` renders a judged soak report from
    disk without dialing any daemon (no ctrl endpoint in this test)."""
    import json as json_mod

    report = {
        "verdict": {
            "pass": True,
            "checks": {
                "no_eviction_loss": {
                    "ok": True,
                    "detail": "rollup counted 40 of 40 spans",
                },
                "scrape_health": {"ok": True, "detail": "12 scrapes"},
            },
        },
        "events": {
            "total": 40,
            "windowed": 38,
            "evicted_window_events": 2,
            "spans_in_rings": 9,
        },
        "waves": [
            {
                "index": 0,
                "added": ["n0-n2"],
                "removed": [],
                "faulted": True,
                "converged": True,
                "converge_ms": 41.2,
            }
        ],
        "windows": [
            {
                "start": 1000.0,
                "events": 38,
                "faulted": True,
                "e2e_p50_ms": 12.5,
                "e2e_p95_ms": 31.0,
                "e2e_max_ms": 44.0,
            }
        ],
        "attribution": {
            "clean_windows": 0,
            "faulted_windows": 1,
            "clean_e2e_ms": {"p95": 0.0},
            "faulted_e2e_ms": {"p95": 31.0},
        },
    }
    path = tmp_path / "soak.json"
    path.write_text(json_mod.dumps(report))
    assert breeze_main(["perf", "soak-report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "soak verdict: PASS (2 check(s))" in out
    assert "no_eviction_loss" in out
    assert "40 total = 38 windowed + 2 window-evicted" in out
    assert "n0-n2" in out
    assert "windowed convergence trend:" in out
    assert "31.00" in out
    assert "attribution: clean 0 window(s)" in out
