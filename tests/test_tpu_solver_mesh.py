"""Production mesh-sharded solver: the SAME parity contract as
tests/test_tpu_solver.py, but with TpuSpfSolver(mesh=...) sharding the
source batch over the virtual 8-device CPU mesh (conftest.py).

This is the daemon's multi-chip path (DecisionConfig.solver_mesh), not a
bespoke demo step: _AreaSolve places its persistent buffers with the
shardings openr_tpu/parallel/mesh.py defines, and every route the meshed
solver produces must match the CPU Dijkstra oracle byte for byte.
"""

import random

import pytest

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.solver import SpfSolver, TpuSpfSolver
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)

MESHES = [(4, 2), (8, 1), (2, 2)]


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def make_prefix_state(announcers, area="0", **entry_kw):
    ps = PrefixState()
    for node, pfxs in announcers.items():
        ps.update_prefix_database(
            PrefixDatabase(
                node,
                [PrefixEntry(IpPrefix(p), **entry_kw) for p in pfxs],
                area=area,
            )
        )
    return ps


def assert_route_db_equal(db_cpu, db_tpu):
    assert db_cpu is not None and db_tpu is not None
    assert set(db_cpu.unicast_entries) == set(db_tpu.unicast_entries)
    for prefix, entry in db_cpu.unicast_entries.items():
        assert db_tpu.unicast_entries[prefix] == entry, prefix
    assert set(db_cpu.mpls_entries) == set(db_tpu.mpls_entries)
    for label, entry in db_cpu.mpls_entries.items():
        assert db_tpu.mpls_entries[label] == entry, label


def run_parity(edges, announcers, me, mesh, overloaded=None, lfa=False,
               **entry_kw):
    ls_cpu = build_ls(edges, overloaded_nodes=overloaded)
    ls_tpu = build_ls(edges, overloaded_nodes=overloaded)
    ps = make_prefix_state(announcers, **entry_kw)
    cpu = SpfSolver(me, compute_lfa_paths=lfa)
    tpu = TpuSpfSolver(me, compute_lfa_paths=lfa, mesh=mesh)
    db_cpu = cpu.build_route_db(me, {"0": ls_cpu}, ps)
    db_tpu = tpu.build_route_db(me, {"0": ls_tpu}, ps)
    assert_route_db_equal(db_cpu, db_tpu)
    assert tpu.device_solves >= 1
    # the solve really ran sharded: its distance rows live on every mesh
    # device (row-sharded D was gathered to host, buffers are committed)
    solve = tpu._solves[("0", me)][1]
    assert solve.mesh is tpu.mesh
    if solve._dev is not None:
        buf = solve._dev["ov"]
        assert len(buf.sharding.device_set) == mesh[0] * mesh[1]
    return tpu


PFXS = ["10.1.0.0/16", "10.2.0.0/16", "10.3.0.0/16"]


class TestMeshedRouteDbParity:
    @pytest.mark.parametrize("mesh", MESHES)
    def test_grid(self, mesh):
        run_parity(
            grid_edges(5),
            {"g4_4": [PFXS[0]], "g0_4": [PFXS[1]], "g2_2": [PFXS[2]]},
            "g0_0",
            mesh,
        )

    @pytest.mark.parametrize("mesh", MESHES[:2])
    def test_fabric_lfa(self, mesh):
        edges = fabric_edges(4, 4, 8)
        nodes = sorted({n for a, b, _ in edges for n in (a, b)})
        run_parity(
            edges,
            {nodes[-1]: [PFXS[0]], nodes[-2]: [PFXS[1]]},
            nodes[0],
            mesh,
            lfa=True,
        )

    def test_overloaded_transit(self):
        run_parity(
            [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)],
            {"c": [PFXS[0]]},
            "a",
            (4, 2),
            overloaded={"b"},
        )

    def test_ksp2(self):
        run_parity(
            grid_edges(4),
            {"g3_3": [PFXS[0]], "g0_3": [PFXS[1]]},
            "g0_0",
            (4, 2),
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )

    def test_random_graphs(self):
        rng = random.Random(7)
        for _ in range(6):
            n = rng.randint(5, 14)
            nodes = [f"n{i}" for i in range(n)]
            edges = []
            for i in range(1, n):
                edges.append(
                    (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 5))
                )
            for _ in range(rng.randint(1, n)):
                a, b = rng.sample(nodes, 2)
                if not any({a, b} == {x, y} for x, y, _ in edges):
                    edges.append((a, b, rng.randint(1, 5)))
            announcers = {
                nodes[i]: [PFXS[i % 3]] for i in range(1, n) if i % 2
            }
            overloaded = {
                nodes[i] for i in range(1, n) if rng.random() < 0.15
            }
            run_parity(edges, announcers, nodes[0], (4, 2),
                       overloaded=overloaded)


class TestMeshedIncremental:
    def test_flap_patches_sharded_buffers(self):
        """Metric change after the first solve must ride the fused
        patch+solve path against the replicated device buffers and still
        match a fresh CPU oracle."""
        import dataclasses

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = make_prefix_state({"c": [PFXS[0]]})
        tpu = TpuSpfSolver("a", mesh=(4, 2))
        db1 = tpu.build_route_db("a", {"0": ls}, ps)
        nh1 = {
            nh.neighbor_node
            for nh in db1.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh1 == {"b"}
        solves_before = tpu.device_solves

        # raise a-b so the direct a-c link wins: weight patch, same shapes
        db = dbs["a"]
        db = dataclasses.replace(
            db,
            adjacencies=[
                dataclasses.replace(adj, metric=9)
                if adj.other_node_name == "b"
                else adj
                for adj in db.adjacencies
            ],
        )
        ls.update_adjacency_database(db)
        db2 = tpu.build_route_db("a", {"0": ls}, ps)
        nh2 = {
            nh.neighbor_node
            for nh in db2.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh2 == {"c"}
        assert tpu.device_solves == solves_before + 1

        ls_cpu = LinkState("0")
        for name in sorted(dbs):
            src = db if name == "a" else dbs[name]
            ls_cpu.update_adjacency_database(src)
        assert_route_db_equal(
            SpfSolver("a").build_route_db("a", {"0": ls_cpu}, ps), db2
        )


class TestMeshedWarmStart:
    """The warm-start incremental path under a solver mesh: same shardings
    as the cold path (sources row-sharded over 'batch', layout and D
    replicated/row-sharded per the existing scheme), same bit-identical
    differential contract as the single-device suite."""

    def _resolve(self, shape):
        from openr_tpu.parallel import resolve_mesh

        return resolve_mesh(shape)

    def test_grid_random_sequence(self):
        from test_tpu_solver import run_warm_differential

        warm = run_warm_differential(
            grid_edges(4), "g0_0", 13, 10, mesh=self._resolve((4, 2))
        )
        assert warm.incremental_solves > 0
        # D stayed sharded across the whole mesh through warm solves
        assert len(warm._d_dev.sharding.device_set) == 8

    def test_clos_random_sequence(self):
        from test_tpu_solver import run_warm_differential

        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        )
        warm = run_warm_differential(
            edges, "rsw0_0", 5, 8, mesh=self._resolve((2, 2))
        )
        assert warm.incremental_solves > 0

    def test_increase_then_decrease_route_parity(self):
        """Meshed end-to-end: metric increase then decrease of the same
        link through TpuSpfSolver(mesh=...), route dbs matching a fresh
        CPU oracle each time, with the warm counter advancing."""
        import dataclasses

        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("a", "d", 9)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = make_prefix_state({"d": [PFXS[0]]})
        tpu = TpuSpfSolver("a", mesh=(4, 2))
        tpu.build_route_db("a", {"0": ls}, ps)
        for metric in (7, 1):
            db = dbs["b"]
            db = dataclasses.replace(
                db,
                adjacencies=[
                    dataclasses.replace(adj, metric=metric)
                    if adj.other_node_name == "c"
                    else adj
                    for adj in db.adjacencies
                ],
            )
            dbs["b"] = db
            ls.update_adjacency_database(db)
            db_tpu = tpu.build_route_db("a", {"0": ls}, ps)
            ls_cpu = LinkState("0")
            for name in sorted(dbs):
                ls_cpu.update_adjacency_database(dbs[name])
            db_cpu = SpfSolver("a").build_route_db("a", {"0": ls_cpu}, ps)
            assert_route_db_equal(db_cpu, db_tpu)
        assert tpu.counters["decision.spf.incremental_solves"] == 2
        assert tpu.counters["decision.spf.rounds_last"] >= 1


class TestMeshedKsp:
    def test_all_pairs_ksp_grid(self):
        ls_oracle = build_ls(grid_edges(4))
        ls_dev = build_ls(grid_edges(4))
        solver = TpuSpfSolver("g0_0", mesh=(4, 2))
        me = "g0_0"
        dests = sorted(set(ls_oracle.node_names()) - {me})
        for k in (1, 2):
            solver._prefetch_kth_paths(ls_dev, me, dests, k)
            for dest in dests:
                got = solver._kth_paths(ls_dev, me, dest, k)
                want = ls_oracle.get_kth_paths(me, dest, k)
                assert got == want, (me, dest, k)


class TestDecisionWithMesh:
    """The daemon path: DecisionConfig(solver_backend='tpu',
    solver_mesh=(4, 2)) must emit the same route delta as the CPU
    backend from live KvStore publications."""

    def test_route_delta_parity(self):
        from openr_tpu.testing import (
            lsdb_publication,
            run_decision_backend_parity,
        )

        pub = lsdb_publication(
            build_adj_dbs(grid_edges(3)).values(),
            announcers={"g2_2": ["10.9.0.0/16"]},
        )
        n_uni, n_mpls = run_decision_backend_parity("g0_0", pub, (4, 2))
        assert n_uni == 1
        assert n_mpls == 9  # one node label route per grid node


class TestMeshedEdgeListVw:
    def test_batched_spf_vw_meshed_matches_single_device(self):
        """The non-sliced per-row-weights solve (KSP fallback for graphs
        that disqualify sliced-ELL) must honor the mesh and agree with the
        single-device result."""
        import numpy as np

        from openr_tpu.ops import compile_graph
        from openr_tpu.ops.graph import INF
        from openr_tpu.ops.spf import batched_spf_vw
        from openr_tpu.parallel import resolve_mesh

        ls = build_ls(grid_edges(4))
        g = compile_graph(ls)
        mesh = resolve_mesh((4, 2))
        s = 8
        rows = np.arange(s, dtype=np.int32)
        w_rows = np.tile(g.w, (s, 1))
        w_rows[3, :4] = INF  # one penalized row
        d_single = np.asarray(batched_spf_vw(g, rows, w_rows))
        d_meshed = np.asarray(batched_spf_vw(g, rows, w_rows, mesh=mesh))
        np.testing.assert_array_equal(d_single, d_meshed)
