"""ExponentialBackoff: reference doubling semantics (the backward-compat
default) and the opt-in decorrelated jitter used by Fib full-sync
scheduling to break up synchronized resync storms."""

import random

from openr_tpu.utils.backoff import ExponentialBackoff


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class TestDoublingDefault:
    def test_doubles_and_caps(self):
        clock = FakeClock()
        b = ExponentialBackoff(1.0, 8.0, clock=clock)
        expected = [1.0, 2.0, 4.0, 8.0, 8.0]
        for want in expected:
            b.report_error()
            assert b.get_current_backoff() == want
        assert b.at_max_backoff()

    def test_success_clears(self):
        b = ExponentialBackoff(1.0, 8.0, clock=FakeClock())
        b.report_error()
        b.report_success()
        assert b.get_current_backoff() == 0.0
        assert b.can_try_now()

    def test_time_remaining(self):
        clock = FakeClock()
        b = ExponentialBackoff(1.0, 8.0, clock=clock)
        b.report_error()
        assert b.get_time_remaining_until_retry() == 1.0
        clock.t = 0.5
        assert b.get_time_remaining_until_retry() == 0.5
        clock.t = 1.5
        assert b.can_try_now()


class TestDecorrelatedJitter:
    def test_bounds_hold_over_many_draws(self):
        # every draw lands in [initial, min(max, 3*prev)] — the jitter
        # never undercuts the floor nor overshoots the cap
        rng = random.Random(42)
        b = ExponentialBackoff(
            0.008, 4.096, clock=FakeClock(), jitter=True, rng=rng
        )
        prev = 0.008
        for _ in range(200):
            b.report_error()
            cur = b.get_current_backoff()
            assert 0.008 <= cur <= 4.096
            assert cur <= min(4.096, prev * 3) + 1e-12
            prev = cur

    def test_draws_are_actually_spread(self):
        # two agents failing in lockstep with different seeds must NOT
        # produce the same retry schedule — that is the whole point
        def schedule(seed):
            b = ExponentialBackoff(
                1.0, 64.0, clock=FakeClock(),
                jitter=True, rng=random.Random(seed),
            )
            out = []
            for _ in range(8):
                b.report_error()
                out.append(b.get_current_backoff())
            return out

        assert schedule(1) != schedule(2)
        # and a fixed seed is fully deterministic (replayable tests)
        assert schedule(3) == schedule(3)

    def test_success_resets_jittered_state(self):
        b = ExponentialBackoff(
            1.0, 8.0, clock=FakeClock(), jitter=True,
            rng=random.Random(0),
        )
        b.report_error()
        b.report_success()
        assert b.get_current_backoff() == 0.0
        b.report_error()
        # after a reset the next draw is back in the first-error range
        assert 1.0 <= b.get_current_backoff() <= 3.0

    def test_default_has_no_jitter(self):
        # backward compat: absent the opt-in flag, behavior is bit-exact
        # deterministic doubling, no RNG consumed
        b = ExponentialBackoff(1.0, 8.0, clock=FakeClock())
        b.report_error()
        b.report_error()
        assert b.get_current_backoff() == 2.0


class TestFibUsesJitter:
    def test_fib_full_sync_backoff_is_jittered_by_default(self):
        from openr_tpu.fib import Fib, FibConfig
        from openr_tpu.messaging import RWQueue
        from openr_tpu.platform import MockFibHandler

        fib = Fib(
            FibConfig(my_node_name="n", backoff_seed=123),
            MockFibHandler(),
            RWQueue(),
        )
        assert fib._backoff._jitter is True
        # injectable seed → deterministic schedule across restarts
        fib._backoff.report_error()
        first = fib._backoff.get_current_backoff()
        fib2 = Fib(
            FibConfig(my_node_name="n", backoff_seed=123),
            MockFibHandler(),
            RWQueue(),
        )
        fib2._backoff.report_error()
        assert fib2._backoff.get_current_backoff() == first

    def test_fib_jitter_can_be_disabled(self):
        from openr_tpu.fib import Fib, FibConfig
        from openr_tpu.messaging import RWQueue
        from openr_tpu.platform import MockFibHandler

        fib = Fib(
            FibConfig(my_node_name="n", backoff_jitter=False),
            MockFibHandler(),
            RWQueue(),
        )
        fib._backoff.report_error()
        assert fib._backoff.get_current_backoff() == 0.008
