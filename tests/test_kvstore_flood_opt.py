"""KvStore DUAL flood-topology optimization tests, mirroring the
flood-optimization scenarios of openr/kvstore/tests/KvStoreTest.cpp: SPT
formation across stores, SPT-restricted flooding still reaching everyone,
fallback to full flooding when the tree is not ready."""

import asyncio

import pytest

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreParams,
    PeerSpec,
)
from openr_tpu.types import Value


def run(coro, timeout=20.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


async def wait_until(predicate, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        assert asyncio.get_event_loop().time() < deadline, "timed out"
        await asyncio.sleep(0.02)


def make_mesh(names, root=None, transport=None):
    transport = transport or InProcessTransport()
    stores = {}
    for name in names:
        stores[name] = KvStore(
            name,
            ["0"],
            transport,
            KvStoreParams(
                node_id=name,
                enable_flood_optimization=True,
                is_flood_root=(name == root),
            ),
        )
    return stores, transport


def full_mesh_peering(stores):
    for name, store in stores.items():
        store.add_peers(
            {other: PeerSpec(other) for other in stores if other != name}
        )


class TestSptFormation:
    def test_spt_forms_around_root(self):
        async def body():
            stores, _ = make_mesh(["r", "a", "b"], root="r")
            full_mesh_peering(stores)
            # allow DUAL message exchange to quiesce
            await wait_until(
                lambda: all(
                    s.db("0").dual.get_spt_root_id() == "r"
                    for s in stores.values()
                )
            )
            # non-root nodes have parent r (full mesh, unit metrics)
            for name in ("a", "b"):
                dual = stores[name].db("0").dual.get_dual("r")
                assert dual.distance == 1
                assert dual.nexthop == "r"
            # root's children cover a and b
            await wait_until(
                lambda: stores["r"].db("0").dual.get_dual("r").children()
                == {"a", "b"}
            )
            # flood peers of a: only its SPT parent
            assert stores["a"].db("0").get_flood_peers() == ["r"]
            infos = stores["r"].db("0").get_spt_infos()
            assert infos["flood_root_id"] == "r"
            assert infos["spt_infos"]["r"]["passive"]
            await asyncio.sleep(0)

        run(body())

    def test_flood_via_spt_reaches_everyone(self):
        async def body():
            names = ["r", "a", "b", "c"]
            stores, _ = make_mesh(names, root="r")
            full_mesh_peering(stores)
            await wait_until(
                lambda: all(
                    s.db("0").dual.get_spt_root_id() == "r"
                    for s in stores.values()
                )
            )
            await wait_until(
                lambda: len(
                    stores["r"].db("0").dual.get_dual("r").children()
                )
                == 3
            )
            stores["a"].set_key("k-flood", Value(1, "a", b"payload"))
            # reaches every store through the tree
            for store in stores.values():
                await wait_until(
                    lambda s=store: s.get_key("k-flood") is not None
                )
            # SPT flooding was actually used
            assert (
                stores["a"].db("0").counters.get("kvstore.flood_via_spt", 0)
                > 0
            )

        run(body())

    def test_no_root_falls_back_to_full_flood(self):
        async def body():
            stores, _ = make_mesh(["a", "b"], root=None)  # no root anywhere
            full_mesh_peering(stores)
            await asyncio.sleep(0.1)
            assert stores["a"].db("0").dual.get_spt_root_id() is None
            assert set(stores["a"].db("0").get_flood_peers()) == {"b"}
            stores["a"].set_key("k1", Value(1, "a", b"x"))
            await wait_until(lambda: stores["b"].get_key("k1") is not None)

        run(body())

    def test_root_failure_tree_reconverges(self):
        async def body():
            # line r - a - b plus backup root rb connected to b; when r
            # dies the tree re-roots at rb
            transport = InProcessTransport()
            stores, _ = make_mesh(
                ["r0", "a", "b", "r9"], root=None, transport=transport
            )
            # two roots: r0 (preferred, smaller id) and r9
            stores["r0"] = KvStore(
                "r0",
                ["0"],
                transport,
                KvStoreParams(
                    node_id="r0",
                    enable_flood_optimization=True,
                    is_flood_root=True,
                ),
            )
            stores["r9"] = KvStore(
                "r9",
                ["0"],
                transport,
                KvStoreParams(
                    node_id="r9",
                    enable_flood_optimization=True,
                    is_flood_root=True,
                ),
            )
            # line topology: r0 - a - b - r9
            def peer(x, y):
                stores[x].add_peers({y: PeerSpec(y)})
                stores[y].add_peers({x: PeerSpec(x)})

            peer("r0", "a")
            peer("a", "b")
            peer("b", "r9")
            await wait_until(
                lambda: all(
                    stores[n].db("0").dual.get_spt_root_id() == "r0"
                    for n in ("a", "b")
                )
            )
            # r0 dies: a loses its only path to r0
            stores["a"].del_peers(["r0"])
            for name in ("a", "b"):
                await wait_until(
                    lambda n=name: stores[n]
                    .db("0")
                    .dual.get_spt_root_id()
                    == "r9",
                    timeout=10,
                )

        run(body())
