"""KvStoreClient persist semantics (KvStoreClientInternal parity,
openr/kvstore/KvStoreClientInternal.{h,cpp}): re-advertise on overwrite,
ttl-version refresh, unset, and key subscriptions."""

import asyncio

import pytest

from openr_tpu.kvstore import KvStore, KvStoreClient, KvStoreParams
from openr_tpu.kvstore.transport import InProcessTransport
from openr_tpu.types import TTL_INFINITY, Value


def run(coro, timeout=10.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def wait_for(predicate, timeout=5.0, interval=0.01):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while not predicate():
        if loop.time() >= deadline:
            raise AssertionError("condition not reached")
        await asyncio.sleep(interval)


def make_store(node="n1"):
    return KvStore(node, ["0"], InProcessTransport())


class TestPersistKey:
    def test_persist_then_overwrite_readvertises(self):
        async def body():
            store = make_store()
            client = KvStoreClient(store)
            client.persist_key("adj:n1", b"mine")
            v = store.get_key("adj:n1")
            assert v.version == 1 and v.originator_id == "n1"

            # a higher-version write from another originator lands...
            store.set_key(
                "adj:n1",
                Value(version=5, originator_id="zz", value=b"theirs"),
            )
            # ...and the client re-advertises above it
            await wait_for(
                lambda: (
                    (cur := store.get_key("adj:n1")) is not None
                    and cur.originator_id == "n1"
                    and cur.version > 5
                    and cur.value == b"mine"
                )
            )
            client.stop()

        run(body())

    def test_unset_stops_readvertising(self):
        async def body():
            store = make_store()
            client = KvStoreClient(store)
            client.persist_key("k", b"mine")
            client.unset_key("k")
            store.set_key(
                "k", Value(version=9, originator_id="zz", value=b"theirs")
            )
            await asyncio.sleep(0.1)  # give _watch a chance to (not) react
            cur = store.get_key("k")
            assert cur.originator_id == "zz" and cur.version == 9
            client.stop()

        run(body())

    def test_ttl_refresh_bumps_ttl_version(self):
        async def body():
            store = make_store()
            client = KvStoreClient(store)
            client.persist_key("k", b"mine", ttl=200)  # refresh at ~50ms
            v0 = store.get_key("k")
            # capture ints: the store hands back its live Value object and
            # ttl refreshes mutate it in place
            ttl_version0, version0 = v0.ttl_version, v0.version
            await wait_for(
                lambda: store.get_key("k").ttl_version > ttl_version0,
                timeout=5,
            )
            cur = store.get_key("k")
            assert cur.value == b"mine" and cur.version == version0
            client.stop()

        run(body())

    def test_subscription_fires_on_update(self):
        async def body():
            store = make_store()
            client = KvStoreClient(store)
            seen = []
            client.subscribe_key("watched", lambda k, v: seen.append((k, v)))
            store.set_key(
                "watched",
                Value(version=1, originator_id="zz", value=b"x"),
            )
            await wait_for(lambda: len(seen) >= 1)
            key, value = seen[0]
            assert key == "watched" and value.value == b"x"
            client.stop()

        run(body())
