"""TRACE_SMOKE tier-1 harness entry (the observability sibling of the
FAULT_SMOKE test in test_faults.py): a 5-node line-topology emulator run
with one link flap must yield a complete spark→fib convergence span on
every node, flood hop counts matching topology distance, and a sane
network-wide convergence report (ISSUE 5 acceptance)."""

def test_trace_smoke(monkeypatch):
    monkeypatch.setenv("TRACE_SMOKE", "1")
    monkeypatch.setenv("TRACE_SMOKE_NODES", "5")
    from openr_tpu.testing.decision_harness import run_trace_smoke

    summary = run_trace_smoke()
    assert summary["nodes"] == 5
    # at least one finished span per node (cold convergence + the flap)
    assert summary["spans_total"] >= 5
    assert 0.0 < summary["e2e_p50_ms"] <= summary["e2e_max_ms"]
    # slowest-hop attribution names a real (node, stage) pair
    assert summary["slowest_stage"]["node"].startswith("n")
    assert summary["slowest_stage"]["ms"] > 0.0
    # the line topology's flood distances: n2/n3/n4 saw n1's flap
    # publication after exactly 1/2/3 hops
    assert summary["hop_evidence"] == {"n2": 1, "n3": 2, "n4": 3}
    assert summary["flood_received"] > 0
    assert 0.0 <= summary["flood_duplicate_ratio"] < 1.0
