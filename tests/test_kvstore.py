"""KvStore tests mirroring openr/kvstore/tests/KvStoreTest.cpp core scenarios:
CRDT merge semantics, TTL expiry, 3-way full sync, flooding with loop
prevention, peer FSM, rate limiting."""

import asyncio

import pytest

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreFilters,
    KvStoreParams,
    PeerSpec,
    PeerState,
    compare_values,
    merge_key_values,
)
from openr_tpu.types import TTL_INFINITY, Publication, Value, generate_hash


def v(
    version=1,
    originator="node1",
    value=b"data",
    ttl=TTL_INFINITY,
    ttl_version=0,
    with_hash=False,
):
    val = Value(version, originator, value, ttl, ttl_version)
    if with_hash:
        val.hash = generate_hash(version, originator, value)
    return val


class TestMergeKeyValues:
    def test_new_key(self):
        store = {}
        updates = merge_key_values(store, {"k": v()})
        assert "k" in updates and "k" in store
        assert store["k"].hash is not None  # hash filled in

    def test_higher_version_wins(self):
        store = {"k": v(version=1, value=b"old")}
        updates = merge_key_values(store, {"k": v(version=2, value=b"new")})
        assert updates and store["k"].value == b"new"

    def test_lower_version_ignored(self):
        store = {"k": v(version=5, value=b"cur")}
        updates = merge_key_values(store, {"k": v(version=4, value=b"old")})
        assert not updates and store["k"].value == b"cur"

    def test_originator_tiebreak(self):
        store = {"k": v(originator="a", value=b"x")}
        assert merge_key_values(store, {"k": v(originator="b", value=b"y")})
        assert store["k"].originator_id == "b"
        # lower originator loses
        assert not merge_key_values(
            store, {"k": v(originator="a", value=b"z")}
        )

    def test_value_tiebreak_same_originator(self):
        # same version+originator, higher value bytes win (deterministic
        # reconciliation after restart, KvStore.cpp:316-334)
        store = {"k": v(value=b"aaa")}
        assert merge_key_values(store, {"k": v(value=b"bbb")})
        assert store["k"].value == b"bbb"
        assert not merge_key_values(store, {"k": v(value=b"aaa")})

    def test_ttl_version_refresh(self):
        store = {"k": v(ttl=10000, ttl_version=0)}
        # ttl refresh has no value body
        refresh = Value(1, "node1", None, 20000, 1)
        updates = merge_key_values(store, {"k": refresh})
        assert updates
        assert store["k"].ttl == 20000
        assert store["k"].ttl_version == 1
        assert store["k"].value == b"data"  # body preserved
        # stale ttl version ignored
        assert not merge_key_values(store, {"k": Value(1, "node1", None, 30000, 1)})

    def test_invalid_ttl_skipped(self):
        assert not merge_key_values({}, {"k": v(ttl=0)})
        assert not merge_key_values({}, {"k": v(ttl=-5)})
        assert merge_key_values({}, {"k": v(ttl=1000)})

    def test_filters(self):
        filters = KvStoreFilters(key_prefixes=["adj:"])
        store = {}
        updates = merge_key_values(
            store, {"adj:n1": v(), "prefix:n1": v()}, filters
        )
        assert set(updates) == {"adj:n1"}

    def test_same_value_same_ttlversion_noop(self):
        store = {"k": v(with_hash=True)}
        assert not merge_key_values(store, {"k": v(with_hash=True)})


class TestCompareValues:
    def test_version(self):
        assert compare_values(v(version=2), v(version=1)) == 1
        assert compare_values(v(version=1), v(version=2)) == -1

    def test_originator(self):
        assert compare_values(v(originator="b"), v(originator="a")) == 1

    def test_hash_equal_ttl_version(self):
        a = v(with_hash=True, ttl_version=2)
        b = v(with_hash=True, ttl_version=1)
        assert compare_values(a, b) == 1
        b2 = v(with_hash=True, ttl_version=2)
        assert compare_values(a, b2) == 0

    def test_value_compare(self):
        assert compare_values(v(value=b"b"), v(value=b"a")) == 1

    def test_unknown(self):
        a = v(with_hash=True)
        b = Value(1, "node1", None, TTL_INFINITY, 0, hash=12345)
        assert compare_values(a, b) == -2


def run(coro, timeout=10.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def make_stores(names, transport=None, areas=("0",), **params_kw):
    transport = transport or InProcessTransport()
    stores = {
        name: KvStore(
            name,
            list(areas),
            transport,
            params=KvStoreParams(node_id=name, **params_kw),
        )
        for name in names
    }
    return stores, transport


async def settle(delay=0.05):
    await asyncio.sleep(delay)


class TestFullSync:
    def test_peer_add_triggers_sync(self):
        async def body():
            stores, _ = make_stores(["a", "b"])
            stores["a"].set_key("k1", v(originator="a", value=b"va"))
            stores["b"].set_key("k2", v(originator="b", value=b"vb"))
            # a peers with b: 3-way sync both directions
            stores["a"].add_peers({"b": PeerSpec("b")})
            await settle()
            assert stores["a"].get_key("k2").value == b"vb"
            assert stores["b"].get_key("k1").value == b"va"  # finalize leg
            assert stores["a"].db().peer_state("b") == PeerState.INITIALIZED

        run(body())

    def test_conflict_resolution_via_sync(self):
        async def body():
            stores, _ = make_stores(["a", "b"])
            stores["a"].set_key("k", v(version=3, originator="a", value=b"a3"))
            stores["b"].set_key("k", v(version=5, originator="b", value=b"b5"))
            stores["a"].add_peers({"b": PeerSpec("b")})
            await settle()
            assert stores["a"].get_key("k").value == b"b5"
            assert stores["b"].get_key("k").value == b"b5"

        run(body())

    def test_sync_failure_backoff_to_idle(self):
        async def body():
            transport = InProcessTransport()
            stores, _ = make_stores(["a", "b"], transport)
            transport.partition("a", "b")
            stores["a"].add_peers({"b": PeerSpec("b")})
            await settle()
            assert stores["a"].db().peer_state("b") == PeerState.IDLE
            # heal: retry task should eventually re-sync
            stores["b"].set_key("k", v(originator="b"))
            transport.heal("a", "b")
            await settle(0.3)  # initial backoff 64ms
            assert stores["a"].db().peer_state("b") == PeerState.INITIALIZED
            assert stores["a"].get_key("k") is not None

        run(body())


class TestFlooding:
    def test_chain_propagation(self):
        async def body():
            stores, _ = make_stores(["a", "b", "c"])
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a"), "c": PeerSpec("c")})
            stores["c"].add_peers({"b": PeerSpec("b")})
            await settle()
            stores["a"].set_key("k", v(originator="a", value=b"flood"))
            await settle()
            assert stores["c"].get_key("k").value == b"flood"

        run(body())

    def test_loop_prevention_in_ring(self):
        async def body():
            stores, _ = make_stores(["a", "b", "c"])
            ring = {"a": ["b", "c"], "b": ["a", "c"], "c": ["a", "b"]}
            for name, peers in ring.items():
                stores[name].add_peers(
                    {p: PeerSpec(p) for p in peers}
                )
            await settle()
            for s in stores.values():
                s.db().counters.clear()
            stores["a"].set_key("k", v(originator="a", value=b"ring"))
            await settle()
            for s in stores.values():
                assert s.get_key("k").value == b"ring"

        run(body())

    def test_path_vector_loop_drop(self):
        # a publication whose nodeIds already contains our id is dropped
        # before merging, even if it carries a newer value
        stores, _ = make_stores(["a"])
        db = stores["a"].db()
        db.set_key_vals({"k": v(version=1, originator="a")})
        db.handle_set_key_vals(
            {"k": v(version=9, originator="z", value=b"loop")},
            node_ids=["z", "a", "b"],
        )
        assert stores["a"].get_key("k").version == 1
        assert db.counters.get("kvstore.looped_publications") == 1

    def test_internal_subscribers_see_updates(self):
        async def body():
            stores, _ = make_stores(["a", "b"])
            reader = stores["b"].updates_queue.get_reader()
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await settle()
            stores["a"].set_key("k", v(originator="a"))
            await settle()
            seen = []
            while True:
                pub = reader.try_get()
                if pub is None:
                    break
                seen.append(pub)
            # b's queue saw at least one publication containing k
            # (from sync or flood)
            assert any("k" in p.key_vals for p in seen)

        run(body())

    def test_rate_limit_buffers_and_merges(self):
        async def body():
            stores, _ = make_stores(
                ["a", "b"], flood_rate=2.0, flood_burst=2.0,
                flood_buffer_delay=0.05,
            )
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await settle()
            for i in range(20):
                stores["a"].set_key(
                    f"k{i}", v(originator="a", value=b"x%d" % i)
                )
            assert stores["a"].db().counters.get(
                "kvstore.rate_limit_suppress", 0
            ) > 0
            await settle(0.5)
            # all keys eventually arrive despite rate limiting
            for i in range(20):
                assert stores["b"].get_key(f"k{i}") is not None

        run(body())


class TestTtl:
    def test_key_expires(self):
        async def body():
            stores, _ = make_stores(["a"])
            stores["a"].set_key("k", v(ttl=50))  # 50ms
            assert stores["a"].get_key("k") is not None
            await settle(0.2)
            assert stores["a"].get_key("k") is None
            assert stores["a"].db().counters.get(
                "kvstore.expired_key_vals"
            ) == 1

        run(body())

    def test_ttl_refresh_extends(self):
        async def body():
            stores, _ = make_stores(["a"])
            stores["a"].set_key("k", v(ttl=80))
            await settle(0.05)
            # refresh before expiry with higher ttlVersion
            stores["a"].db().set_key_vals(
                {"k": Value(1, "node1", None, 200, 1)}
            )
            await settle(0.1)  # original would have expired by now
            assert stores["a"].get_key("k") is not None
            await settle(0.2)
            assert stores["a"].get_key("k") is None

        run(body())

    def test_forwarded_ttl_decremented(self):
        async def body():
            stores, _ = make_stores(["a", "b"])
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await settle()
            stores["a"].set_key("k", v(ttl=10000))
            await settle()
            assert stores["b"].get_key("k").ttl < 10000

        run(body())


class TestDumpApis:
    def test_dump_with_filters(self):
        stores, _ = make_stores(["a"])
        db = stores["a"].db()
        db.set_key_vals({"adj:x": v(originator="x")})
        db.set_key_vals({"prefix:y": v(originator="y")})
        pub = db.dump_all(KvStoreFilters(key_prefixes=["adj:"]))
        assert set(pub.key_vals) == {"adj:x"}
        pub = db.dump_all(
            KvStoreFilters(originator_ids={"y"})
        )
        assert set(pub.key_vals) == {"prefix:y"}
        # AND semantics
        pub = db.dump_all(
            KvStoreFilters(key_prefixes=["adj:"], originator_ids={"y"}),
            match_all=True,
        )
        assert pub.key_vals == {}

    def test_dump_hashes_strips_values(self):
        stores, _ = make_stores(["a"])
        db = stores["a"].db()
        db.set_key_vals({"k": v()})
        pub = db.dump_hashes()
        assert pub.key_vals["k"].value is None
        assert pub.key_vals["k"].hash is not None

    def test_get_key_vals_subset(self):
        stores, _ = make_stores(["a"])
        db = stores["a"].db()
        db.set_key_vals({"k1": v(), "k2": v()})
        pub = db.get_key_vals(["k1", "nope"])
        assert set(pub.key_vals) == {"k1"}

    def test_multi_area_isolation(self):
        async def body():
            transport = InProcessTransport()
            stores, _ = make_stores(
                ["a", "b"], transport, areas=("red", "blue")
            )
            stores["a"].set_key("k", v(originator="a"), area="red")
            stores["a"].add_peers({"b": PeerSpec("b")}, area="red")
            await settle()
            assert stores["b"].get_key("k", area="red") is not None
            assert stores["b"].get_key("k", area="blue") is None

        run(body())
