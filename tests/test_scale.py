"""Emulation-scale tests (openr/docs/Emulator.md:4-8: "at-least a 1000 node
topology before code changes can be checked in").

Two layers, mirroring how the reference splits the bar:
  - a 1000+-node LSDB driven through the real Decision module (publication
    stream -> debounce -> solver -> RouteDb delta), checked against the
    CPU oracle route pipeline on both solver backends;
  - a wider full-stack ring of OpenrWrapper nodes over the mock fabric
    (discovery -> flood -> SPF -> FIB), bounded-time convergence.

"""

import asyncio
import time

import pytest

from openr_tpu.decision.decision import Decision, DecisionConfig
from openr_tpu.lsdb import LinkState
from openr_tpu.lsdb.prefix_state import PrefixState
from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue
from openr_tpu.solver import SpfSolver
from openr_tpu.topology import build_adj_dbs, fabric_edges
from openr_tpu.types import (
    IpPrefix,
    Publication,
    PrefixDatabase,
    PrefixEntry,
    Value,
    adj_key,
    prefix_key,
)
from openr_tpu.utils import serializer




def run(coro, timeout=300.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def clos_1000():
    """3-tier fabric > 1000 nodes (pods sized to cross the bar)."""
    edges = fabric_edges(18)  # 18 pods x (8 fsw + 48 rsw) + spines > 1000
    dbs = build_adj_dbs(edges)
    assert len(dbs) >= 1000, len(dbs)
    return edges, dbs


def prefix_db_of(i, node):
    return PrefixDatabase(
        node,
        [PrefixEntry(IpPrefix(f"10.{i // 250}.{i % 250}.0/24"))],
        area="0",
    )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_decision_converges_on_1000_node_lsdb(backend):
    edges, dbs = clos_1000()
    me = "rsw0_0"

    async def body():
        kv_q = RWQueue()
        route_q = ReplicateQueue()
        decision = Decision(
            DecisionConfig(
                my_node_name=me,
                solver_backend=backend,
                debounce_min=0.005,
                debounce_max=0.05,
            ),
            RQueue(kv_q),
            route_q,
        )
        reader = route_q.get_reader()
        decision.start()

        # one publication per node, as a KvStore full-sync would deliver
        t0 = time.time()
        for i, (node, db) in enumerate(sorted(dbs.items())):
            pub = Publication(area="0")
            pub.key_vals[adj_key(node)] = Value(
                1, node, serializer.dumps(db)
            )
            pdb = prefix_db_of(i, node)
            pub.key_vals[prefix_key(node)] = Value(
                1, node, serializer.dumps(pdb)
            )
            kv_q.push(pub)

        delta = await reader.get()
        elapsed = time.time() - t0
        # the debouncer may split the stream into a few batches; drain
        # until the route table covers every other node's loopback
        routes = {e.prefix: e for e in delta.unicast_routes_to_update}
        deadline = time.time() + 240
        while len(routes) < len(dbs) - 1 and time.time() < deadline:
            try:
                more = await asyncio.wait_for(reader.get(), 30)
            except asyncio.TimeoutError:
                break
            routes.update(
                {e.prefix: e for e in more.unicast_routes_to_update}
            )
            for pfx in more.unicast_routes_to_delete:
                routes.pop(pfx, None)
        assert len(routes) == len(dbs) - 1, (len(routes), len(dbs))

        # spot-check against the oracle route pipeline
        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        ps = PrefixState()
        for i, node in enumerate(sorted(dbs)):
            ps.update_prefix_database(prefix_db_of(i, node))
        oracle = SpfSolver(me).build_route_db(me, {"0": ls}, ps)
        assert set(routes) == set(oracle.unicast_entries)
        for pfx in list(oracle.unicast_entries)[:50]:
            assert routes[pfx] == oracle.unicast_entries[pfx], pfx

        decision.stop()
        return elapsed

    elapsed = run(body())
    # generous bound: first full-sync ingest of 1000+ nodes end-to-end
    assert elapsed < 240, elapsed


def _ring_convergence(n: int, timeout_s: float = 0.0) -> float:
    """n full-protocol nodes (Spark+KvStore+Decision+Fib each) in a ring
    over the mock fabric; returns wall seconds to full route convergence.

    Scale recipe (mirrors real cold-start deployments, Runbook eor
    guidance): hold timers sized so one node's route-build burst cannot
    expire a neighbor (hold 10s vs ~16ms/build), and the cold-start EOR
    hold staggered across nodes so the first build wave interleaves with
    keepalives instead of stalling the loop in one block — without it, a
    mid-fill rebuild storm melts the fabric down (measured: 256-node ring
    DNF at eor=6s vs ~23s converged with a post-fill eor).

    Measured path to the reference's 1000-node bar (Emulator.md:4-8):
    LSDB fill is ~O(n^2.3) on a ring (n keys x n hops, growing stores):
    measured 5s @ 192, 10s @ 256, 60s @ 512; projected ~280s @ 1000; the
    staggered build wave adds n x ~16ms. 1000 nodes ~ 9-10 min wall — run
    via OPENR_SCALE_RING=1000 (env-gated below), CI keeps 256.
    """
    from openr_tpu.testing import VirtualNetwork
    from openr_tpu.testing.wrapper import wait_until

    # the eor hold must land past the local LSDB fill (measured above,
    # with margin) or the mid-fill rebuild storm melts the fabric
    eor_base = max(4.0, n * n / 3800.0)
    if not timeout_s:
        # scale with the projected fill+eor+wave so OPENR_SCALE_RING=1000
        # isn't failed by a fixed deadline while converging normally
        timeout_s = max(480.0, 2.2 * eor_base + 0.1 * n + 180.0)

    async def body():
        net = VirtualNetwork()
        for i in range(n):
            ov = {
                "eor_time_s": eor_base + (i % 16) * 0.25,
                "spark_config": {
                    "hello_time_s": 2.0,
                    "fastinit_hello_time_ms": 50.0,
                    "keepalive_time_s": 0.5,
                    "hold_time_s": 10.0,
                    "graceful_restart_time_s": 30.0,
                },
                "decision_config": {
                    "debounce_min_ms": 20.0,
                    "debounce_max_ms": 250.0,
                },
            }
            net.add_node(
                f"node-{i}",
                loopback_prefix=f"10.{i // 250}.{i % 250}.0/24",
                config_overrides=ov,
            )
        for i in range(n):
            j = (i + 1) % n
            net.connect(
                f"node-{i}", f"if-{i}-{j}", f"node-{j}", f"if-{j}-{i}"
            )
        t0 = time.time()
        await net.start_all()

        # phase 1: LSDB fill everywhere (cheap O(1) predicate)
        want = 2 * n  # adj + prefix key per node
        def filled():
            return all(
                w.kvstore_key_count() >= want
                for w in net.wrappers.values()
            )

        await wait_until(filled, timeout=timeout_s, interval=0.25)
        t_fill = time.time() - t0

        # phase 2: routes programmed end-to-end on every node
        def converged():
            for w in net.wrappers.values():
                if len(w.programmed_prefixes()) < n - 1:
                    return False
            return True

        await wait_until(converged, timeout=timeout_s, interval=0.25)
        print(f"ring {n}: fill {t_fill:.1f}s", end=" ")
        dt = time.time() - t0
        # ring shortest paths really programmed end-to-end
        w0 = net.wrappers["node-0"]
        half = n // 2
        assert f"10.{half // 250}.{half % 250}.0/24" in w0.programmed_prefixes()
        await net.stop_all()
        return dt

    return run(body(), timeout=timeout_s + 120)


def test_full_stack_ring_256():
    """The emulation bar: 256 full-protocol nodes converging in-process
    (the reference's pre-checkin requirement is a 1000-node topology on a
    multi-process emulator fleet, Emulator.md:4-8; this is a tenth of a
    fleet's hardware on one event loop)."""
    import os

    n = int(os.environ.get("OPENR_SCALE_RING", "256"))
    dt = _ring_convergence(n)
    print(f"ring {n}: converged in {dt:.1f}s")


def test_full_stack_ring_convergence_at_width():
    """24 full protocol nodes (Spark+KvStore+Decision+Fib each) converge
    end-to-end over the mock fabric."""
    from openr_tpu.testing import VirtualNetwork
    from openr_tpu.testing.wrapper import wait_until

    n = 24

    async def body():
        net = VirtualNetwork()
        for i in range(n):
            net.add_node(f"node-{i}", loopback_prefix=f"10.{i}.0.0/24")
        for i in range(n):
            j = (i + 1) % n
            net.connect(f"node-{i}", f"if-{i}-{j}", f"node-{j}", f"if-{j}-{i}")
        await net.start_all()

        def converged():
            for i in range(n):
                w = net.wrappers[f"node-{i}"]
                if len(w.adjacent_nodes()) != 2:
                    return False
                if len(w.programmed_prefixes()) < n - 1:
                    return False
            return True

        await wait_until(converged, timeout=180)
        # ring shortest paths: node-0 reaches node-12's loopback
        w0 = net.wrappers["node-0"]
        assert f"10.{n // 2}.0.0/24" in w0.programmed_prefixes()
        await net.stop_all()

    run(body())


# ---------------------------------------------------------------------------
# bulk cold-start ingest (LinkState.bulk_update_adjacency_databases)
# ---------------------------------------------------------------------------


def assert_link_state_equal(a: LinkState, b: LinkState, spf_sources=()):
    """Structural equality: same nodes, links, per-direction attributes,
    overloads — and identical SPF answers from sampled sources."""
    assert set(a.get_adjacency_databases()) == set(b.get_adjacency_databases())
    links_a = {l.key: l for l in a.all_links}
    links_b = {l.key: l for l in b.all_links}
    assert set(links_a) == set(links_b)
    for key, la in links_a.items():
        lb = links_b[key]
        for node in (la.n1, la.n2):
            assert la.metric_from_node(node) == lb.metric_from_node(node)
            assert la.overload_from_node(node) == lb.overload_from_node(node)
            assert la.adj_label_from_node(node) == lb.adj_label_from_node(node)
            assert la.nh_v4_from_node(node) == lb.nh_v4_from_node(node)
            assert la.nh_v6_from_node(node) == lb.nh_v6_from_node(node)
        assert la.is_up() == lb.is_up()
    for node in a.get_adjacency_databases():
        assert a.is_node_overloaded(node) == b.is_node_overloaded(node)
    for src in spf_sources:
        ra, rb = a.get_spf_result(src), b.get_spf_result(src)
        assert set(ra) == set(rb)
        for dest in ra:
            assert ra[dest].metric == rb[dest].metric, (src, dest)
            assert ra[dest].next_hops == rb[dest].next_hops, (src, dest)


class TestBulkIngest:
    def test_clos_bulk_equals_incremental(self):
        edges, dbs = clos_1000()
        inc = LinkState("0")
        for db in dbs.values():
            inc.update_adjacency_database(db)
        bulk = LinkState("0")
        change = bulk.bulk_update_adjacency_databases(list(dbs.values()))
        assert change.topology_changed and change.node_label_changed
        assert_link_state_equal(
            inc, bulk, spf_sources=["rsw0_0", "fsw0_0", "ssw0_0"]
        )

    def test_bulk_peers_with_preexisting_nodes(self):
        edges = [("a", "b", 1), ("b", "c", 2), ("c", "d", 3), ("d", "a", 4),
                 ("a", "c", 9)]
        dbs = build_adj_dbs(edges, overloaded_nodes={"c"})
        inc = LinkState("0")
        for db in dbs.values():
            inc.update_adjacency_database(db)
        # bulk: 'a' pre-exists, the rest arrive as one batch
        mixed = LinkState("0")
        mixed.update_adjacency_database(dbs["a"])
        mixed.bulk_update_adjacency_databases(
            [dbs[n] for n in ("b", "c", "d")]
        )
        assert_link_state_equal(inc, mixed, spf_sources=["a", "b"])

    def test_bulk_falls_back_on_overlap(self):
        edges = [("a", "b", 1), ("b", "c", 2)]
        dbs = build_adj_dbs(edges)
        inc = LinkState("0")
        for db in dbs.values():
            inc.update_adjacency_database(db)
        over = LinkState("0")
        over.update_adjacency_database(dbs["b"])
        # batch includes 'b' again -> incremental fallback, same result
        over.bulk_update_adjacency_databases(list(dbs.values()))
        assert_link_state_equal(inc, over, spf_sources=["a"])

    def test_unidirectional_adjacency_makes_no_link(self):
        dbs = build_adj_dbs([("a", "b", 1)])
        # strip b's reverse adjacency: no bidirectional match
        dbs["b"].adjacencies.clear()
        bulk = LinkState("0")
        bulk.bulk_update_adjacency_databases(list(dbs.values()))
        assert bulk.num_links() == 0
        inc = LinkState("0")
        for db in dbs.values():
            inc.update_adjacency_database(db)
        assert_link_state_equal(inc, bulk)

    def test_decision_full_sync_publication_uses_bulk(self):
        """One publication carrying the whole LSDB (a KvStore full sync)
        must ride the bulk path and produce oracle-identical routes."""
        edges, dbs = clos_1000()
        me = "rsw0_0"

        async def body():
            kv_q = RWQueue()
            route_q = ReplicateQueue()
            decision = Decision(
                DecisionConfig(
                    my_node_name=me,
                    debounce_min=0.005,
                    debounce_max=0.05,
                ),
                RQueue(kv_q),
                route_q,
            )
            reader = route_q.get_reader()
            decision.start()
            pub = Publication(area="0")
            for i, (node, db) in enumerate(sorted(dbs.items())):
                pub.key_vals[adj_key(node)] = Value(
                    1, node, serializer.dumps(db)
                )
                pub.key_vals[prefix_key(node)] = Value(
                    1, node, serializer.dumps(prefix_db_of(i, node))
                )
            t0 = time.time()
            kv_q.push(pub)
            delta = await asyncio.wait_for(reader.get(), 120)
            elapsed = time.time() - t0
            assert decision.counters.get("decision.bulk_adj_ingests") == 1
            routes = {e.prefix: e for e in delta.unicast_routes_to_update}
            assert len(routes) == len(dbs) - 1

            ls = LinkState("0")
            ls.bulk_update_adjacency_databases(list(dbs.values()))
            ps = PrefixState()
            for i, node in enumerate(sorted(dbs)):
                ps.update_prefix_database(prefix_db_of(i, node))
            oracle = SpfSolver(me).build_route_db(me, {"0": ls}, ps)
            assert set(routes) == set(oracle.unicast_entries)
            for pfx in list(oracle.unicast_entries)[:50]:
                assert routes[pfx] == oracle.unicast_entries[pfx], pfx
            decision.stop()
            return elapsed

        elapsed = run(body())
        assert elapsed < 60, elapsed
