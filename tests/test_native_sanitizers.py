"""Sanitizer pass over the native C++ components (SURVEY.md §5: the
reference ships no TSAN/ASAN CI; the rebuild adds one). Builds the C++
assert suites under AddressSanitizer+UBSan and runs them; any leak,
overflow, or UB aborts the test."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
OUT = os.path.join(REPO, "openr_tpu", "_native")


def _asan_supported() -> bool:
    if shutil.which("g++") is None:
        return False
    probe = subprocess.run(
        ["g++", "-fsanitize=address", "-x", "c++", "-", "-o", "/dev/null"],
        input=b"int main(){return 0;}",
        capture_output=True,
    )
    return probe.returncode == 0


pytestmark = pytest.mark.skipif(
    not _asan_supported(), reason="ASan toolchain unavailable"
)


@pytest.fixture(scope="module")
def asan_binaries():
    subprocess.run(
        ["make", "-C", NATIVE, "asan"],
        check=True,
        capture_output=True,
        timeout=180,
    )
    return OUT


@pytest.mark.parametrize(
    "binary", ["onl_kvstore_test_asan", "onl_spf_test_asan"]
)
def test_native_suite_clean_under_asan(asan_binaries, binary):
    proc = subprocess.run(
        [os.path.join(asan_binaries, binary)],
        capture_output=True,
        timeout=120,
        env={
            **os.environ,
            "ASAN_OPTIONS": "detect_leaks=1:abort_on_error=1",
            "UBSAN_OPTIONS": "halt_on_error=1",
        },
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b"OK" in proc.stdout
