"""PrefixManager tests, mirroring
openr/prefix-manager/tests/PrefixManagerTest.cpp core scenarios: advertise/
withdraw/sync per type, type preference, per-prefix keys in KvStore,
tombstone on withdraw, persistence across restart, update-request queue,
cross-area redistribution."""

import asyncio

import pytest

from openr_tpu.configstore import PersistentStore
from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreClient,
)
from openr_tpu.messaging import RWQueue
from openr_tpu.prefixmanager import (
    PrefixEventCommand,
    PrefixManager,
    PrefixManagerConfig,
    PrefixUpdateRequest,
)
from openr_tpu.solver.routes import RibUnicastEntry
from openr_tpu.types import (
    IpPrefix,
    NextHop,
    PrefixEntry,
    PrefixType,
    prefix_key,
)
from openr_tpu.utils import serializer


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


async def wait_until(predicate, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not predicate():
        assert asyncio.get_event_loop().time() < deadline, "timed out"
        await asyncio.sleep(0.01)


def entry(prefix, ptype=PrefixType.LOOPBACK):
    return PrefixEntry(prefix=IpPrefix(prefix), type=ptype)


def make_pm(areas=("0",), config_store=None, with_queues=False):
    store = KvStore("n1", list(areas), InProcessTransport())
    client = KvStoreClient(store)
    prefix_q = RWQueue() if with_queues else None
    route_q = RWQueue() if with_queues else None
    pm = PrefixManager(
        PrefixManagerConfig(
            node_name="n1", areas=list(areas), sync_throttle=0.001
        ),
        client,
        config_store=config_store,
        prefix_updates=prefix_q,
        route_updates=route_q,
    )
    return pm, store, client, prefix_q, route_q


def kv_prefix_db(store, key, area="0"):
    value = store.get_key(key, area=area)
    if value is None or value.value is None:
        return None
    return serializer.loads(value.value)


class TestAdvertiseWithdraw:
    def test_advertise_creates_per_prefix_key(self):
        async def body():
            pm, store, client, _, _ = make_pm()
            pm.start()
            assert pm.advertise_prefixes([entry("10.0.0.0/24")])
            await asyncio.sleep(0.05)
            key = prefix_key("n1", IpPrefix("10.0.0.0/24"), "0")
            db = kv_prefix_db(store, key)
            assert db is not None and not db.delete_prefix
            assert db.prefix_entries[0].prefix == IpPrefix("10.0.0.0/24")
            assert pm.get_prefixes() == [entry("10.0.0.0/24")]
            pm.stop()
            client.stop()

        run(body())

    def test_withdraw_emits_tombstone(self):
        async def body():
            pm, store, client, _, _ = make_pm()
            pm.start()
            pm.advertise_prefixes([entry("10.0.0.0/24")])
            await asyncio.sleep(0.05)
            assert pm.withdraw_prefixes([entry("10.0.0.0/24")])
            await asyncio.sleep(0.05)
            key = prefix_key("n1", IpPrefix("10.0.0.0/24"), "0")
            db = kv_prefix_db(store, key)
            assert db is not None and db.delete_prefix
            assert pm.get_prefixes() == []
            # withdrawing again is a no-op
            assert not pm.withdraw_prefixes([entry("10.0.0.0/24")])
            pm.stop()
            client.stop()

        run(body())

    def test_withdraw_and_sync_by_type(self):
        async def body():
            pm, store, client, _, _ = make_pm()
            pm.start()
            pm.advertise_prefixes(
                [
                    entry("10.0.0.0/24", PrefixType.BGP),
                    entry("10.0.1.0/24", PrefixType.BGP),
                    entry("10.0.2.0/24", PrefixType.LOOPBACK),
                ]
            )
            assert len(pm.get_prefixes_by_type(PrefixType.BGP)) == 2
            assert pm.sync_prefixes_by_type(
                PrefixType.BGP, [entry("10.0.9.0/24", PrefixType.BGP)]
            )
            assert pm.get_prefixes_by_type(PrefixType.BGP) == [
                entry("10.0.9.0/24", PrefixType.BGP)
            ]
            assert pm.withdraw_prefixes_by_type(PrefixType.BGP)
            assert pm.get_prefixes_by_type(PrefixType.BGP) == []
            # LOOPBACK untouched
            assert len(pm.get_prefixes_by_type(PrefixType.LOOPBACK)) == 1
            pm.stop()
            client.stop()

        run(body())

    def test_lowest_type_wins_for_same_prefix(self):
        async def body():
            pm, store, client, _, _ = make_pm()
            pm.start()
            pm.advertise_prefixes([entry("10.0.0.0/24", PrefixType.BGP)])
            pm.advertise_prefixes(
                [entry("10.0.0.0/24", PrefixType.LOOPBACK)]
            )
            await asyncio.sleep(0.05)
            key = prefix_key("n1", IpPrefix("10.0.0.0/24"), "0")
            db = kv_prefix_db(store, key)
            # LOOPBACK precedes BGP in PrefixType order
            assert db.prefix_entries[0].type == PrefixType.LOOPBACK
            # withdrawing the winning type falls back to the other
            pm.withdraw_prefixes([entry("10.0.0.0/24", PrefixType.LOOPBACK)])
            await asyncio.sleep(0.05)
            db = kv_prefix_db(store, key)
            assert db.prefix_entries[0].type == PrefixType.BGP
            pm.stop()
            client.stop()

        run(body())


class TestQueueAndPersistence:
    def test_update_request_queue(self):
        async def body():
            pm, store, client, prefix_q, _ = make_pm(with_queues=True)
            pm.start()
            prefix_q.push(
                PrefixUpdateRequest(
                    cmd=PrefixEventCommand.ADD_PREFIXES,
                    prefixes=[entry("10.1.0.0/24")],
                )
            )
            await wait_until(lambda: pm.get_prefixes())
            prefix_q.push(
                PrefixUpdateRequest(
                    cmd=PrefixEventCommand.WITHDRAW_PREFIXES_BY_TYPE,
                    type=PrefixType.LOOPBACK,
                )
            )
            await wait_until(lambda: not pm.get_prefixes())
            pm.stop()
            client.stop()

        run(body())

    def test_prefixes_survive_restart(self, tmp_path):
        async def body():
            cs = PersistentStore(str(tmp_path / "cs.bin"))
            pm, store, client, _, _ = make_pm(config_store=cs)
            pm.start()
            pm.advertise_prefixes([entry("10.2.0.0/24", PrefixType.CONFIG)])
            await asyncio.sleep(0.05)
            pm.stop()
            client.stop()
            cs.flush()

            pm2, store2, client2, _, _ = make_pm(
                config_store=PersistentStore(str(tmp_path / "cs.bin"))
            )
            pm2.start()
            await asyncio.sleep(0.05)
            assert pm2.get_prefixes() == [
                entry("10.2.0.0/24", PrefixType.CONFIG)
            ]
            # re-advertised into the fresh kvstore
            key = prefix_key("n1", IpPrefix("10.2.0.0/24"), "0")
            assert kv_prefix_db(store2, key) is not None
            pm2.stop()
            client2.stop()

        run(body())

    def test_stale_keys_from_previous_incarnation_cleared(self):
        async def body():
            # a prior incarnation's key sits in the store
            store = KvStore("n1", ["0"], InProcessTransport())
            from openr_tpu.types import PrefixDatabase, Value

            stale_key = prefix_key("n1", IpPrefix("10.9.0.0/24"), "0")
            stale_db = PrefixDatabase(
                this_node_name="n1",
                prefix_entries=[entry("10.9.0.0/24")],
                area="0",
            )
            store.set_key(
                stale_key,
                Value(1, "n1", serializer.dumps(stale_db), ttl=60000),
            )
            client = KvStoreClient(store)
            pm = PrefixManager(
                PrefixManagerConfig(
                    node_name="n1", areas=["0"], sync_throttle=0.001
                ),
                client,
            )
            pm.start()
            pm.advertise_prefixes([entry("10.8.0.0/24")])
            await asyncio.sleep(0.05)
            db = kv_prefix_db(store, stale_key)
            assert db is not None and db.delete_prefix  # tombstoned
            pm.stop()
            client.stop()

        run(body())


class TestRedistribution:
    def test_cross_area_route_redistribution(self):
        async def body():
            pm, store, client, _, route_q = make_pm(
                areas=("area1", "area2"), with_queues=True
            )
            pm.start()
            # a route learned from area1 gets re-originated into area2
            route_q.push(
                type(
                    "U",
                    (),
                    {
                        "unicast_routes_to_update": [
                            RibUnicastEntry(
                                prefix=IpPrefix("10.3.0.0/24"),
                                nexthops={
                                    NextHop("fe80::1", area="area1")
                                },
                                best_prefix_entry=entry("10.3.0.0/24"),
                                best_area="area1",
                            )
                        ],
                        "unicast_routes_to_delete": [],
                    },
                )()
            )
            await wait_until(
                lambda: pm.get_prefixes_by_type(PrefixType.RIB)
            )
            rib = pm.get_prefixes_by_type(PrefixType.RIB)[0]
            assert rib.area_stack == ("area1",)
            await asyncio.sleep(0.05)
            key2 = prefix_key("n1", IpPrefix("10.3.0.0/24"), "area2")
            assert kv_prefix_db(store, key2, area="area2") is not None
            # NOT advertised back into area1
            key1 = prefix_key("n1", IpPrefix("10.3.0.0/24"), "area1")
            assert kv_prefix_db(store, key1, area="area1") is None
            pm.stop()
            client.stop()

        run(body())

    def test_single_area_no_redistribution(self):
        async def body():
            pm, store, client, _, route_q = make_pm(with_queues=True)
            pm.start()
            route_q.push(
                type(
                    "U",
                    (),
                    {
                        "unicast_routes_to_update": [
                            RibUnicastEntry(
                                prefix=IpPrefix("10.3.0.0/24"),
                                nexthops={NextHop("fe80::1", area="0")},
                                best_prefix_entry=entry("10.3.0.0/24"),
                                best_area="0",
                            )
                        ],
                        "unicast_routes_to_delete": [],
                    },
                )()
            )
            await asyncio.sleep(0.1)
            assert pm.get_prefixes_by_type(PrefixType.RIB) == []
            pm.stop()
            client.stop()

        run(body())
