"""Spark discovery over real UDP multicast sockets.

The reference discovers neighbors with UDP multicast hellos on ff02::1:6666
(openr/common/Constants.h:132); these tests run the same 3-message protocol
(hello / handshake / heartbeat) through UdpIoProvider on a loopback IPv4
multicast group — first two Spark instances in one process (distinct
sockets in one SO_REUSEPORT group), then against a Spark in a separate OS
process, proving the packets really cross the kernel.
"""

import asyncio
import os
import subprocess
import sys

import pytest

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.spark import NeighborEventType, Spark, SparkConfig
from openr_tpu.spark.io_provider import UdpIoProvider
from openr_tpu.spark.messages import (
    SparkHelloMsg,
    SparkHelloPacket,
    packet_from_bytes,
    packet_to_bytes,
)

GROUP = "239.88.77.66"


def run(coro, timeout=30.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def fast_config(name, **kw):
    return SparkConfig(
        node_name=name,
        fastinit_hello_time=0.02,
        hello_time=0.5,
        handshake_time=0.02,
        keepalive_time=0.05,
        hold_time=0.5,
        graceful_restart_time=0.5,
        negotiate_hold_time=0.3,
        **kw,
    )


async def wait_event(reader, event_type, timeout=10.0):
    while True:
        ev = await asyncio.wait_for(reader.get(), timeout)
        if ev.event_type == event_type:
            return ev


def test_packet_codec_roundtrip():
    packet = SparkHelloPacket(
        hello_msg=SparkHelloMsg(
            domain_name="d",
            node_name="n",
            if_name="lo",
            seq_num=7,
            sent_ts_in_us=123,
        )
    )
    decoded = packet_from_bytes(packet_to_bytes(packet))
    assert decoded == packet


class TestUdpDiscovery:
    def test_two_instances_same_host(self):
        async def body():
            port = 26660 + os.getpid() % 1000
            providers, sparks, readers = [], [], []
            for name in ("a", "b"):
                io = UdpIoProvider(port=port, group=GROUP)
                await io.add_interface("lo")
                q = ReplicateQueue()
                spark = Spark(fast_config(name), io, q)
                providers.append(io)
                sparks.append(spark)
                readers.append(q.get_reader())
                spark.update_interfaces(["lo"])
            up_a = await wait_event(readers[0], NeighborEventType.NEIGHBOR_UP)
            up_b = await wait_event(readers[1], NeighborEventType.NEIGHBOR_UP)
            assert up_a.node_name == "b"
            assert up_b.node_name == "a"
            assert up_a.local_if_name == "lo"
            for spark in sparks:
                spark.stop()
            for io in providers:
                io.close()

        run(body())

    def test_neighbor_down_on_process_exit(self):
        """Cross-process: discover a Spark in another OS process, then see
        it expire (hold timer) when that process dies."""
        port = 27660 + os.getpid() % 1000
        child_script = f"""
import asyncio
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.spark import Spark, SparkConfig
from openr_tpu.spark.io_provider import UdpIoProvider


async def main():
    io = UdpIoProvider(port={port}, group="{GROUP}")
    await io.add_interface("lo")
    q = ReplicateQueue()
    spark = Spark(
        SparkConfig(
            node_name="remote",
            fastinit_hello_time=0.02,
            hello_time=0.5,
            handshake_time=0.02,
            keepalive_time=0.05,
            hold_time=0.5,
            graceful_restart_time=0.5,
            negotiate_hold_time=0.3,
        ),
        io,
        q,
    )
    reader = q.get_reader()
    spark.update_interfaces(["lo"])
    while True:
        ev = await reader.get()
        if ev.event_type.name == "NEIGHBOR_UP":
            print("UP", ev.node_name, flush=True)
            await asyncio.sleep(3600)


asyncio.new_event_loop().run_until_complete(main())
"""
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            filter(None, [os.getcwd(), env.get("PYTHONPATH")])
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        child = subprocess.Popen(
            [sys.executable, "-c", child_script],
            stdout=subprocess.PIPE,
            env=env,
            text=True,
        )
        try:

            async def body():
                io = UdpIoProvider(port=port, group=GROUP)
                await io.add_interface("lo")
                q = ReplicateQueue()
                spark = Spark(fast_config("local"), io, q)
                reader = q.get_reader()
                spark.update_interfaces(["lo"])
                up = await wait_event(reader, NeighborEventType.NEIGHBOR_UP)
                assert up.node_name == "remote"
                # the child saw us too
                line = child.stdout.readline().strip()
                assert line == "UP local", line
                # kill the child; its heartbeats stop; hold timer expires
                child.kill()
                down = await wait_event(
                    reader, NeighborEventType.NEIGHBOR_DOWN
                )
                assert down.node_name == "remote"
                spark.stop()
                io.close()

            run(body())
        finally:
            child.kill()
            child.wait(timeout=10)
