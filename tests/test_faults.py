"""Deterministic fault-injection harness (openr_tpu/testing/faults.py):
schedule semantics (trigger counts, skip, seeded probability, actions,
instance targeting), the named fault points threaded through production
modules, and the FAULT_SMOKE tier-1 end-to-end degraded-convergence run."""

import asyncio

import pytest

from openr_tpu.testing.faults import (
    FaultInjected,
    FaultInjector,
    fault_point,
    injected,
    install,
    installed,
    uninstall,
)


class TestSchedules:
    def test_uninstalled_fault_point_is_a_noop(self):
        uninstall()
        fault_point("anything.at.all")  # must not raise
        assert installed() is None

    def test_times_budget_is_exact(self):
        with injected() as inj:
            inj.arm("p", times=2)
            with pytest.raises(FaultInjected):
                fault_point("p")
            with pytest.raises(FaultInjected):
                fault_point("p")
            fault_point("p")  # budget exhausted
            assert inj.fired("p") == 2
            assert inj.hits("p") == 3

    def test_after_skips_initial_hits(self):
        with injected() as inj:
            inj.arm("p", times=1, after=2)
            fault_point("p")
            fault_point("p")
            with pytest.raises(FaultInjected):
                fault_point("p")

    def test_unlimited_times(self):
        with injected() as inj:
            inj.arm("p", times=None)
            for _ in range(5):
                with pytest.raises(FaultInjected):
                    fault_point("p")

    def test_probability_is_seed_deterministic(self):
        def pattern(seed):
            out = []
            with injected(FaultInjector(seed=seed)) as inj:
                inj.arm("p", times=None, probability=0.5)
                for _ in range(32):
                    try:
                        fault_point("p")
                        out.append(0)
                    except FaultInjected:
                        out.append(1)
            return out

        a, b = pattern(7), pattern(7)
        assert a == b  # same seed → identical fault pattern
        assert 0 < sum(a) < 32  # actually probabilistic
        assert pattern(8) != a  # and seed-sensitive

    def test_action_mutates_instead_of_raising(self):
        box = []
        with injected() as inj:
            inj.arm("p", action=box.append, times=1)
            fault_point("p", "ctx-object")  # no raise
            fault_point("p", "again")
        assert box == ["ctx-object"]

    def test_when_predicate_targets_one_instance(self):
        target = object()
        other = object()
        with injected() as inj:
            inj.arm("p", times=1, when=lambda ctx: ctx is target)
            fault_point("p", other)  # ignored entirely
            with pytest.raises(FaultInjected):
                fault_point("p", target)
            assert inj.fired("p") == 1

    def test_custom_exception_factory(self):
        class DeviceGone(RuntimeError):
            def __init__(self, point):
                super().__init__(f"DEVICE_LOST at {point}")

        with injected() as inj:
            inj.arm("p", exc=DeviceGone)
            with pytest.raises(DeviceGone):
                fault_point("p")

    def test_injected_context_uninstalls_on_error(self):
        with pytest.raises(FaultInjected):
            with injected() as inj:
                inj.arm("p")
                fault_point("p")
        assert installed() is None

    def test_install_returns_injector_and_disarm(self):
        inj = install(FaultInjector())
        try:
            inj.arm("p")
            inj.disarm("p")
            fault_point("p")  # disarmed
            assert inj.spec("p") is None
        finally:
            uninstall()


class TestThreadedFaultPoints:
    """The named seams in production modules actually fire."""

    def test_solver_tpu_solve_seam(self):
        from openr_tpu.lsdb import LinkState
        from openr_tpu.solver.tpu import _AreaSolve
        from openr_tpu.topology import build_adj_dbs, grid_edges

        ls = LinkState("0")
        for db in build_adj_dbs(grid_edges(2)).values():
            ls.update_adjacency_database(db)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=1)
            with pytest.raises(FaultInjected):
                _AreaSolve(ls, "g0_0")
            _AreaSolve(ls, "g0_0")  # budget spent: next solve is clean

    def test_ops_batched_spf_seam(self):
        import numpy as np

        from openr_tpu.lsdb import LinkState
        from openr_tpu.ops import batched_spf, compile_graph
        from openr_tpu.topology import build_adj_dbs, grid_edges

        ls = LinkState("0")
        for db in build_adj_dbs(grid_edges(2)).values():
            ls.update_adjacency_database(db)
        graph = compile_graph(ls)
        rows = np.array([0], dtype=np.int32)
        with injected() as inj:
            inj.arm("ops.spf.batched_spf", times=1)
            with pytest.raises(FaultInjected):
                batched_spf(graph, rows)

    def test_kvstore_flood_send_seam(self):
        """An injected per-peer flood failure rides the API_ERROR path:
        the failure counter bumps and the store stays usable."""
        from openr_tpu.kvstore import (
            InProcessTransport,
            KvStore,
            KvStoreParams,
            PeerSpec,
        )
        from openr_tpu.types import TTL_INFINITY, Value

        async def body():
            transport = InProcessTransport()
            stores = {
                name: KvStore(
                    name,
                    ["0"],
                    transport,
                    params=KvStoreParams(node_id=name),
                )
                for name in ("a", "b")
            }
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"a": PeerSpec("a")})
            await asyncio.sleep(0.05)
            with injected() as inj:
                inj.arm(
                    "kvstore.flood_send", times=1, when=lambda p: p == "b"
                )
                stores["a"].set_key(
                    "k", Value(1, "a", b"x", TTL_INFINITY, 0)
                )
                await asyncio.sleep(0.1)
                assert inj.fired("kvstore.flood_send") == 1
            counters = stores["a"].db().counters
            assert counters.get("kvstore.thrift.num_flood_pub_failure") == 1
            # the peer recovers via the retry/full-sync machinery; a later
            # key still floods through
            stores["a"].set_key("k2", Value(1, "a", b"y", TTL_INFINITY, 0))
            deadline = asyncio.get_event_loop().time() + 5.0
            while stores["b"].get_key("k2") is None:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)

        asyncio.new_event_loop().run_until_complete(body())

    def test_kvstore_full_sync_seam(self):
        """An injected full-sync dump failure rides the retry/backoff FSM:
        the failure counter bumps, the peer drops to IDLE, and the retry
        task eventually syncs anyway."""
        from openr_tpu.kvstore import (
            InProcessTransport,
            KvStore,
            KvStoreParams,
            PeerSpec,
        )
        from openr_tpu.types import TTL_INFINITY, Value

        async def body():
            transport = InProcessTransport()
            stores = {
                name: KvStore(
                    name,
                    ["0"],
                    transport,
                    params=KvStoreParams(node_id=name),
                )
                for name in ("a", "b")
            }
            stores["b"].set_key("k", Value(1, "b", b"x", TTL_INFINITY, 0))
            with injected() as inj:
                inj.arm("kvstore.full_sync", times=1)
                stores["a"].add_peers({"b": PeerSpec("b")})
                deadline = asyncio.get_event_loop().time() + 5.0
                while stores["a"].get_key("k") is None:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.02)
                assert inj.fired("kvstore.full_sync") == 1
            assert (
                stores["a"].db().counters.get("kvstore.full_sync_failure")
                == 1
            )

        asyncio.new_event_loop().run_until_complete(body())

    def test_spark_packet_seams_drop_datagrams(self):
        """Injected packet-I/O faults are dropped datagrams: counted, not
        raised into Spark's timer callbacks."""
        from openr_tpu.messaging import ReplicateQueue
        from openr_tpu.spark.io_provider import MockIoNetwork
        from openr_tpu.spark.spark import Spark, SparkConfig

        async def body():
            network = MockIoNetwork()
            network.connect(("a", "eth0"), ("b", "eth0"), latency_ms=0.1)
            sparks = {
                name: Spark(
                    SparkConfig(
                        node_name=name,
                        fastinit_hello_time=0.02,
                        keepalive_time=0.05,
                    ),
                    network.provider(name),
                    ReplicateQueue(),
                )
                for name in ("a", "b")
            }
            with injected() as inj:
                inj.arm("spark.packet_send", times=3)
                inj.arm("spark.packet_recv", times=2)
                for spark in sparks.values():
                    spark.update_interfaces(["eth0"])
                deadline = asyncio.get_event_loop().time() + 10.0
                while not (
                    sparks["a"].get_neighbors() and sparks["b"].get_neighbors()
                ):
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.02)
                assert inj.fired("spark.packet_send") == 3
                assert inj.fired("spark.packet_recv") == 2
            counters = {}
            for spark in sparks.values():
                for key, value in spark.counters.items():
                    counters[key] = counters.get(key, 0) + value
                spark.stop()
            assert counters.get("spark.packet_send_failures", 0) == 3
            assert counters.get("spark.packet_recv_failures", 0) == 2
            # despite the losses, discovery proceeded (retransmit timers)
            assert counters["spark.hello_packet_recv"] > 0

        asyncio.new_event_loop().run_until_complete(body())


class TestChaosSchedule:
    """Satellite: a randomized multi-point FaultInjector schedule — seeded
    probability arms on the Spark packet seams, KvStore flood sends and
    full-syncs, all with bounded budgets — over a whole-stack 3-node
    emulator run that must converge anyway (drops retransmit, flood
    failures ride the peer FSM retry, failed syncs back off and retry)."""

    def test_randomized_multi_point_schedule_converges(self):
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            with injected(FaultInjector(seed=1234)) as inj:
                inj.arm("spark.packet_send", probability=0.2, times=8)
                inj.arm("spark.packet_recv", probability=0.2, times=8)
                inj.arm("kvstore.flood_send", probability=0.3, times=5)
                inj.arm("kvstore.full_sync", probability=0.3, times=3)
                net = VirtualNetwork()
                for i in range(3):
                    net.add_node(
                        f"c{i}", loopback_prefix=f"10.25{i}.0.0/24"
                    )
                await net.start_all()
                net.connect("c0", "r", "c1", "l")
                net.connect("c1", "r", "c2", "l")

                def converged():
                    for i in range(3):
                        got = set(
                            net.wrappers[f"c{i}"].programmed_prefixes()
                        )
                        want = {
                            f"10.25{j}.0.0/24" for j in range(3) if j != i
                        }
                        if not want.issubset(got):
                            return False
                    return True

                try:
                    await wait_until(converged, timeout=60.0)
                    # the chaos arms actually exercised their seams
                    hits = {
                        point: inj.hits(point)
                        for point in (
                            "spark.packet_send",
                            "spark.packet_recv",
                            "kvstore.flood_send",
                            "kvstore.full_sync",
                        )
                    }
                    assert all(count > 0 for count in hits.values()), hits
                    fired = sum(
                        inj.fired(point) for point in hits
                    )
                    assert fired > 0, "no chaos fault ever fired"
                finally:
                    await net.stop_all()

        asyncio.new_event_loop().run_until_complete(body())


def test_fault_smoke(monkeypatch):
    """FAULT_SMOKE=1 tier-1 smoke: Decision(tpu, supervised)→Fib flap
    sequence with one injected solver failure and one injected fib-program
    failure — convergence completes degraded (CPU fallback active, FIB
    tables identical to an unfaulted CPU-oracle stack)."""
    monkeypatch.setenv("FAULT_SMOKE", "1")
    monkeypatch.setenv("FAULT_SMOKE_SIDE", "3")
    from openr_tpu.testing.decision_harness import run_fault_smoke

    summary = run_fault_smoke()
    assert summary["converged"] is True
    assert summary["fallback_active"] == 1
    assert summary["breaker_state"] == "open"
    assert summary["solver_faults_fired"] == 1
    assert summary["fib_faults_fired"] == 1
    assert summary["fib_program_failures"] >= 1
    assert summary["fib_sync_calls"] >= 2  # initial sync + failure resync
    assert summary["routes_programmed"] == 2
