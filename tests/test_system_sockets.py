"""Whole-stack convergence over REAL sockets.

The mock-fabric system tests (test_system.py) prove protocol logic; this
one proves deployment plumbing: two full OpenrDaemons in one process whose
Sparks discover each other through genuine UDP multicast datagrams on
loopback and whose KvStores peer over genuine TCP connections on ephemeral
ports — discovery → handshake (advertising each store's TCP port) →
KvStore full sync → adjacency/prefix flood → SPF → FIB programming, with
zero in-process shortcuts on the wire path. Mirrors what
openr/tests/OpenrSystemTest.cpp does over real ZMQ/thrift sockets.
"""

import asyncio
import os

import pytest

from openr_tpu.config import Config
from openr_tpu.kvstore import TcpTransport
from openr_tpu.openr import OpenrDaemon
from openr_tpu.platform import MockFibHandler
from openr_tpu.spark.io_provider import UdpIoProvider
from openr_tpu.testing.wrapper import wait_until
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType

GROUP = "239.88.66.55"


def run(coro, timeout=60.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def make_daemon(name: str, spark_port: int):
    cfg = Config.from_dict(
        {
            "node_name": name,
            "dryrun": False,
            "spark_config": {
                "hello_time_s": 2.0,
                "fastinit_hello_time_ms": 50.0,
                "keepalive_time_s": 0.2,
                "hold_time_s": 1.0,
                "graceful_restart_time_s": 3.0,
            },
            "decision_config": {
                "debounce_min_ms": 5.0,
                "debounce_max_ms": 20.0,
            },
        }
    )
    fib = MockFibHandler()
    io = UdpIoProvider(port=spark_port, group=GROUP)
    daemon = OpenrDaemon(
        cfg,
        io_provider=io,
        kv_transport=TcpTransport(),
        fib_service=fib,
        ctrl_port=0,
        kvstore_host="127.0.0.1",
        kvstore_port=0,  # ephemeral; advertised via Spark handshake
    )
    return daemon, io, fib


def programmed(fib) -> list:
    from openr_tpu.platform import FIB_CLIENT_OPENR

    return sorted(
        str(dest) for dest in fib.unicast_routes.get(FIB_CLIENT_OPENR, {})
    )


class TestRealSockets:
    def test_two_daemons_converge_over_udp_and_tcp(self):
        async def body():
            spark_port = 28660 + os.getpid() % 1000
            d_a, io_a, fib_a = make_daemon("node-a", spark_port)
            d_b, io_b, fib_b = make_daemon("node-b", spark_port)
            await d_a.start()
            await d_b.start()
            # distinct ephemeral KvStore ports were bound and advertised
            assert d_a.kvstore_server.port != d_b.kvstore_server.port
            assert (
                d_a.spark.config.kvstore_cmd_port == d_a.kvstore_server.port
            )

            d_a.prefix_manager.advertise_prefixes(
                [
                    PrefixEntry(
                        prefix=IpPrefix("10.1.0.0/24"),
                        type=PrefixType.LOOPBACK,
                    )
                ]
            )
            d_b.prefix_manager.advertise_prefixes(
                [
                    PrefixEntry(
                        prefix=IpPrefix("10.2.0.0/24"),
                        type=PrefixType.LOOPBACK,
                    )
                ]
            )

            # bring up loopback on both: UDP multicast discovery begins
            d_a.link_monitor.update_interface("lo", True)
            d_b.link_monitor.update_interface("lo", True)

            # adjacency via real UDP; KvStore peering via real TCP
            await wait_until(
                lambda: any(
                    node == "node-b"
                    for node, _ in d_a.link_monitor.get_adjacencies()
                ),
                timeout=20,
            )
            # the KvStore peer address is host:port, not a node id
            peers = d_a.kvstore.dbs["0"].get_peers()
            assert "node-b" in peers
            assert peers["node-b"].peer_addr == (
                f"127.0.0.1:{d_b.kvstore_server.port}"
            )

            # full route convergence in both directions
            await wait_until(
                lambda: "10.2.0.0/24" in programmed(fib_a), timeout=20
            )
            await wait_until(
                lambda: "10.1.0.0/24" in programmed(fib_b), timeout=20
            )
            # adjacency DBs flooded over TCP into both stores
            keys_a = sorted(d_a.kvstore.dump_all().key_vals)
            assert any(k.startswith("adj:node-b") for k in keys_a)

            await d_a.stop()
            await d_b.stop()
            io_a.close()
            io_b.close()

        run(body())
