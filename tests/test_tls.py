"""Mutual TLS on the control-plane and KvStore-peering transports
(openr/Main.cpp:517-543 TLS setup semantics: x509 cert/key/CA plus an
acceptable-peer common-name allow-list)."""

import asyncio
import ssl

import pytest

from openr_tpu.ctrl.client import CtrlClient, CtrlError
from openr_tpu.ctrl.server import CtrlServer
from openr_tpu.kvstore import KvStore, KvStoreTcpServer, TcpTransport
from openr_tpu.types import Value
from openr_tpu.utils.tls import (
    check_acceptable_peer,
    client_ssl_context,
    make_test_ca,
    server_ssl_context,
)


def run(coro, timeout=30.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    directory = tmp_path_factory.mktemp("pki")
    ca, pairs = make_test_ca(str(directory), ["node-a", "node-b", "rogue"])
    return {
        "ca": ca,
        "node-a": pairs[0],
        "node-b": pairs[1],
        "rogue": pairs[2],
    }


class TestCtrlTls:
    def test_mutual_tls_round_trip(self, pki):
        async def body():
            cert, key = pki["node-a"]
            server = CtrlServer(
                "node-a",
                port=0,
                ssl_context=server_ssl_context(cert, key, pki["ca"]),
            )
            port = await server.start()
            b_cert, b_key = pki["node-b"]
            client = CtrlClient(
                port=port,
                ssl_context=client_ssl_context(pki["ca"], b_cert, b_key),
            )
            async with client:
                assert await client.call("getMyNodeName") == "node-a"
            await server.stop()

        run(body())

    def test_plaintext_client_rejected(self, pki):
        async def body():
            cert, key = pki["node-a"]
            server = CtrlServer(
                "node-a",
                port=0,
                ssl_context=server_ssl_context(cert, key, pki["ca"]),
            )
            port = await server.start()
            client = CtrlClient(port=port)  # no TLS
            with pytest.raises(Exception):
                async with client:
                    await asyncio.wait_for(
                        client.call("getMyNodeName"), 3
                    )
            await server.stop()

        run(body())

    def test_client_without_cert_rejected(self, pki):
        async def body():
            cert, key = pki["node-a"]
            server = CtrlServer(
                "node-a",
                port=0,
                ssl_context=server_ssl_context(cert, key, pki["ca"]),
            )
            port = await server.start()
            # CA-verifying client that presents NO certificate: the
            # server requires one (CERT_REQUIRED)
            client = CtrlClient(
                port=port, ssl_context=client_ssl_context(pki["ca"])
            )
            with pytest.raises(
                (ssl.SSLError, ConnectionError, OSError, CtrlError)
            ):
                async with client:
                    await asyncio.wait_for(
                        client.call("getMyNodeName"), 3
                    )
            await server.stop()

        run(body())

    def test_acceptable_peers_enforced(self, pki):
        async def body():
            cert, key = pki["node-a"]
            server = CtrlServer(
                "node-a",
                port=0,
                ssl_context=server_ssl_context(cert, key, pki["ca"]),
                tls_acceptable_peers=["node-b"],
            )
            port = await server.start()
            # node-b (allowed) works
            b_cert, b_key = pki["node-b"]
            client = CtrlClient(
                port=port,
                ssl_context=client_ssl_context(pki["ca"], b_cert, b_key),
            )
            async with client:
                assert await client.call("getMyNodeName") == "node-a"
            # rogue (CA-signed but not allow-listed) is dropped
            r_cert, r_key = pki["rogue"]
            rogue = CtrlClient(
                port=port,
                ssl_context=client_ssl_context(pki["ca"], r_cert, r_key),
            )
            with pytest.raises(Exception):
                async with rogue:
                    await asyncio.wait_for(
                        rogue.call("getMyNodeName"), 3
                    )
            await server.stop()

        run(body())


class TestKvStoreTls:
    def test_full_sync_over_mutual_tls(self, pki):
        async def body():
            a_cert, a_key = pki["node-a"]
            b_cert, b_key = pki["node-b"]
            ta = TcpTransport(
                ssl_context=client_ssl_context(pki["ca"], a_cert, a_key)
            )
            tb = TcpTransport(
                ssl_context=client_ssl_context(pki["ca"], b_cert, b_key)
            )
            sa = KvStore("node-a", ["0"], ta)
            sb = KvStore("node-b", ["0"], tb)
            srv_a = KvStoreTcpServer(
                sa,
                ssl_context=server_ssl_context(a_cert, a_key, pki["ca"]),
                tls_acceptable_peers=["node-a", "node-b"],
            )
            srv_b = KvStoreTcpServer(
                sb,
                ssl_context=server_ssl_context(b_cert, b_key, pki["ca"]),
                tls_acceptable_peers=["node-a", "node-b"],
            )
            await srv_a.start()
            await srv_b.start()

            from openr_tpu.kvstore.store import PeerSpec

            sa.set_key("k1", Value(1, "node-a", b"from-a"))
            sa.add_peers({"node-b": PeerSpec(srv_b.address)})
            sb.add_peers({"node-a": PeerSpec(srv_a.address)})

            for _ in range(300):
                v = sb.get_key("k1")
                if v is not None and v.value == b"from-a":
                    break
                await asyncio.sleep(0.02)
            v = sb.get_key("k1")
            assert v is not None and v.value == b"from-a"

            sa.stop()
            sb.stop()
            await srv_a.stop()
            await srv_b.stop()

        run(body())


def test_check_acceptable_peer_without_tls_object():
    class _FakeSsl:
        def getpeercert(self):
            return {"subject": ((("commonName", "n1"),),)}

    assert check_acceptable_peer(_FakeSsl(), None)
    assert check_acceptable_peer(_FakeSsl(), ["n1"])
    assert not check_acceptable_peer(_FakeSsl(), ["n2"])
