"""TPU batched solver: op-level tests + full route-db parity vs the CPU oracle.

The parity tests are the contract from SURVEY.md §7 phase 3: identical
DecisionRouteDb output (routes, nexthops, labels) on every topology, verified
on random graphs and the fixture topologies.
"""

import random

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.ops import INF, batched_spf, compile_graph, ecmp_dag
from openr_tpu.solver import SpfSolver, TpuSpfSolver
from openr_tpu.topology import (
    build_adj_dbs,
    fabric_edges,
    grid_edges,
    ring_edges,
    wan_edges,
)
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def all_pairs_distance_check(ls):
    """Compare batched BF distances against the Dijkstra oracle for all pairs."""
    graph = compile_graph(ls)
    d = np.asarray(batched_spf(graph, np.arange(graph.n_pad, dtype=np.int32)))
    for src in graph.names:
        oracle = ls.get_spf_result(src)
        row = graph.node_index[src]
        for dst in graph.names:
            col = graph.node_index[dst]
            got = int(d[row, col])
            if dst in oracle:
                assert got == oracle[dst].metric, (src, dst)
            else:
                assert got >= INF, (src, dst)


class TestBatchedSpf:
    def test_line(self):
        ls = build_ls([("a", "b", 1), ("b", "c", 2), ("c", "d", 3)])
        all_pairs_distance_check(ls)

    def test_grid(self):
        all_pairs_distance_check(build_ls(grid_edges(4)))

    def test_weighted_ring(self):
        edges = [(f"r{i}", f"r{(i+1)%8}", (i % 3) + 1) for i in range(8)]
        all_pairs_distance_check(build_ls(edges))

    def test_disconnected(self):
        all_pairs_distance_check(build_ls([("a", "b", 1), ("x", "y", 2)]))

    def test_overloaded_transit(self):
        ls = build_ls(
            [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)],
            overloaded_nodes={"b"},
        )
        all_pairs_distance_check(ls)

    def test_overloaded_cut_vertex(self):
        # b overloaded and the only path a-c: c unreachable from a
        ls = build_ls(
            [("a", "b", 1), ("b", "c", 1)], overloaded_nodes={"b"}
        )
        graph = compile_graph(ls)
        d = np.asarray(
            batched_spf(graph, np.arange(graph.n_pad, dtype=np.int32))
        )
        ia, ib, ic = (graph.node_index[x] for x in "abc")
        assert d[ia, ib] == 1  # reachable
        assert d[ia, ic] >= INF  # no transit through b
        assert d[ib, ic] == 1  # b's own routes unaffected
        all_pairs_distance_check(ls)

    def test_random_graphs(self):
        rng = random.Random(42)
        for trial in range(10):
            n = rng.randint(4, 16)
            nodes = [f"n{i}" for i in range(n)]
            edges = []
            # random spanning tree + chords, random metrics
            for i in range(1, n):
                edges.append(
                    (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 20))
                )
            for _ in range(rng.randint(0, n)):
                a, b = rng.sample(nodes, 2)
                if not any(
                    (x == a and y == b) or (x == b and y == a)
                    for x, y, _ in edges
                ):
                    edges.append((a, b, rng.randint(1, 20)))
            overloaded = {
                nodes[i] for i in range(n) if rng.random() < 0.2
            }
            ls = build_ls(edges, overloaded_nodes=overloaded)
            all_pairs_distance_check(ls)

    def test_ecmp_dag_matches_oracle_nexthops(self):
        ls = build_ls(grid_edges(4))
        graph = compile_graph(ls)
        d = np.asarray(
            batched_spf(graph, np.arange(graph.n_pad, dtype=np.int32))
        )
        dag = np.asarray(ecmp_dag(graph, d))
        # oracle nexthop sets from each source = union over first-hop edges
        for src in graph.names:
            oracle = ls.get_spf_result(src)
            row = graph.node_index[src]
            for dst in graph.names:
                if dst == src:
                    continue
                col = graph.node_index[dst]
                got = {
                    graph.names[graph.dst[e]]
                    for e in range(graph.e)
                    if graph.src[e] == row and dag[e, col]
                }
                want = oracle[dst].next_hops if dst in oracle else set()
                assert got == want, (src, dst)

    def test_bucket_padding_reuse(self):
        # graphs in the same bucket share jit executables (no recompile):
        # just exercise two different sizes in one bucket
        for n in (5, 7):
            all_pairs_distance_check(build_ls(ring_edges(n)))

    def test_sliced_and_edge_list_kernels_agree(self):
        from openr_tpu.ops.spf import _bf_fixpoint, sell_fixpoint

        rng = random.Random(5)
        for trial in range(5):
            n = rng.randint(4, 12)
            nodes = [f"n{i}" for i in range(n)]
            edges = [
                (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 9))
                for i in range(1, n)
            ]
            overloaded = {nodes[i] for i in range(1, n) if rng.random() < 0.2}
            ls = build_ls(edges, overloaded_nodes=overloaded)
            g = compile_graph(ls)
            assert g.sell is not None  # small bounded-degree: sliced layout
            rows = np.arange(g.n_pad, dtype=np.int32)
            d_sell = np.asarray(
                sell_fixpoint(g.sell, rows, g.sell.wg, g.overloaded)
            )
            d_edge = np.asarray(
                _bf_fixpoint(rows, g.src, g.dst, g.w, g.overloaded)
            )
            np.testing.assert_array_equal(d_sell, d_edge)

    def test_star_hub_uses_fori_bucket(self):
        # hub in-degree beyond the unroll threshold exercises the
        # fori_loop bucket path; distances must still match the oracle
        edges = [("hub", f"leaf{i:03d}", 1 + i % 5) for i in range(40)]
        ls = build_ls(edges)
        g = compile_graph(ls)
        assert g.sell is not None
        assert any(a.shape[1] > 32 for a in g.sell.nbr)  # fat bucket
        all_pairs_distance_check(ls)

    def test_masked_solver_matches_link_ignore_spf(self):
        # per-row INF masks == the oracle's links_to_ignore re-solve
        from openr_tpu.ops.spf import sell_fixpoint_masked

        rng = random.Random(9)
        ls = build_ls(grid_edges(4))
        g = compile_graph(ls)
        links = sorted(g.link_edges)
        ignore_sets = [
            set(),
            {links[0]},
            {links[1], links[5]},
            set(rng.sample(links, 4)),
        ]
        me = "g0_0"
        row = g.node_index[me]
        mask_positions = [
            [p for link in ig for p in g.link_edges[link]]
            for ig in ignore_sets
        ]
        d = np.asarray(
            sell_fixpoint_masked(
                g.sell,
                np.full(len(ignore_sets), row, dtype=np.int32),
                g.overloaded,
                mask_positions,
            )
        )
        for i, ig in enumerate(ignore_sets):
            res = ls.run_spf(me, True, ig)
            for node in g.names:
                col = g.node_index[node]
                want = res[node].metric if node in res else INF
                assert d[i, col] == want, (i, node)

    def test_extreme_degree_falls_back_to_edge_list(self):
        # unroll cap exceeded (hub in-degree > _SELL_UNROLL_CAP):
        # edge-list segment-min path takes over
        edges = [("hub", f"leaf{i:04d}", 1) for i in range(1100)]
        ls = build_ls(edges)
        g = compile_graph(ls)
        assert g.sell is None
        d = np.asarray(batched_spf(graph=g, source_rows=np.arange(g.n_pad)))
        hub = g.node_index["hub"]
        leaf = g.node_index["leaf0000"]
        assert d[hub, leaf] == 1 and d[leaf, hub] == 1
        other = g.node_index["leaf0001"]
        assert d[leaf, other] == 2  # via hub


class TestIncrementalRefresh:
    """refresh_graph must patch weight/overload arrays in place for
    non-structural events (metric change, drain) — same shapes, shared
    src/dst identity — and fall back to a rebuild for structural ones."""

    def test_metric_change_patches_in_place(self):
        from openr_tpu.ops.graph import refresh_graph

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        ls = build_ls(edges)
        g1 = compile_graph(ls)
        # bump a-c metric: weight-only change
        ls.update_adjacency_database(build_adj_dbs(
            [("a", "b", 1), ("a", "c", 9)])["a"])
        g2 = refresh_graph(g1, ls)
        assert g2.src is g1.src and g2.dst is g1.dst  # no rebuild
        assert g2.version == ls.version
        # sliced-layout weights patched consistently with the edge weights
        sell = g2.sell
        assert sell is not None
        for p in range(g2.e):
            assert (
                sell.wg[sell.edge_bucket[p]][
                    sell.edge_row[p], sell.edge_slot[p]
                ]
                == g2.w[p]
            )
        all_pairs_distance_check_graph(ls, g2)

    def test_node_overload_patches_in_place(self):
        from openr_tpu.ops.graph import refresh_graph

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        ls = build_ls(edges)
        g1 = compile_graph(ls)
        db_b = build_adj_dbs(edges)["b"]
        db_b.is_overloaded = True
        ls.update_adjacency_database(db_b)
        g2 = refresh_graph(g1, ls)
        assert g2.src is g1.src
        assert g2.overloaded[g2.node_index["b"]]
        all_pairs_distance_check_graph(ls, g2)

    def test_structural_change_rebuilds(self):
        from openr_tpu.ops.graph import refresh_graph
        from openr_tpu.types import AdjacencyDatabase

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        ls = build_ls(edges)
        g1 = compile_graph(ls)
        new_a = AdjacencyDatabase(
            "a",
            [x for x in build_adj_dbs(edges)["a"].adjacencies
             if x.other_node_name != "b"],
            area="0",
        )
        ls.update_adjacency_database(new_a)
        g2 = refresh_graph(g1, ls)
        assert g2.src is not g1.src  # full rebuild
        all_pairs_distance_check_graph(ls, g2)

    def test_refresh_noop_when_version_unchanged(self):
        from openr_tpu.ops.graph import refresh_graph

        ls = build_ls([("a", "b", 1)])
        g1 = compile_graph(ls)
        assert refresh_graph(g1, ls) is g1

    def test_solver_incremental_weight_event(self):
        # a metric change must produce correct routes through the patched
        # arrays with exactly one extra device call
        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        ls = build_ls(edges)
        ps = make_prefix_state({"c": [PFXS[0]]})
        tpu = TpuSpfSolver("a")
        db1 = tpu.build_route_db("a", {"0": ls}, ps)
        nh1 = {
            nh.neighbor_node
            for nh in db1.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh1 == {"b"}
        before = tpu.device_solves
        # drop a-c to metric 1: both b and c become ECMP... no — a->b->c = 2,
        # a->c = 1, so c wins outright
        ls.update_adjacency_database(build_adj_dbs(
            [("a", "b", 1), ("a", "c", 1)])["a"])
        db2 = tpu.build_route_db("a", {"0": ls}, ps)
        nh2 = {
            nh.neighbor_node
            for nh in db2.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh2 == {"c"}
        assert tpu.device_solves == before + 1
        # arrays were patched, not rebuilt
        solve = tpu._solves[("0", "a")][1]
        assert solve.graph.version == ls.version


def apply_random_event(rng, dbs, ls, links):
    """One randomized weight-only event: link flap (down/up via adjacency
    overload), metric change, or node-overload toggle. Mutates dbs and ls;
    returns the event kind."""
    import dataclasses

    kind = rng.choice(("flap", "metric", "node_overload"))
    if kind in ("flap", "metric"):
        a, b, _ = links[rng.randrange(len(links))]
        db = dbs[a]
        new_adjs = []
        for adj in db.adjacencies:
            if adj.other_node_name == b:
                if kind == "flap":
                    adj = dataclasses.replace(
                        adj, is_overloaded=not adj.is_overloaded
                    )
                else:
                    adj = dataclasses.replace(adj, metric=rng.randint(1, 9))
            new_adjs.append(adj)
        db = dataclasses.replace(db, adjacencies=new_adjs)
        dbs[a] = db
        ls.update_adjacency_database(db)
    else:
        import dataclasses as dc

        node = sorted(dbs)[rng.randrange(len(dbs))]
        db = dc.replace(dbs[node], is_overloaded=not dbs[node].is_overloaded)
        dbs[node] = db
        ls.update_adjacency_database(db)
    return kind


def assert_solve_matches_oracle(ls, solve):
    """Every solved source row must equal the CPU Dijkstra oracle."""
    d = solve.d
    graph = solve.graph
    for name, row in solve.row_map.items():
        oracle = ls.get_spf_result(name)
        for dst in graph.names:
            col = graph.node_index[dst]
            got = int(d[row, col])
            if dst in oracle:
                assert got == oracle[dst].metric, (name, dst)
            else:
                assert got >= INF, (name, dst)


def run_warm_differential(edges, me, seed, n_events, mesh=None):
    """Randomized event sequence: after every event the warm-started
    incremental solve must be bit-identical to a from-scratch cold solve
    AND to the CPU oracle. Returns the warm _AreaSolve for counter
    assertions."""
    from openr_tpu.solver.tpu import _AreaSolve

    rng = random.Random(seed)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    warm = _AreaSolve(ls, me, mesh=mesh)
    links = list(edges)
    applied = 0
    for _ in range(n_events):
        before = ls.version
        apply_random_event(rng, dbs, ls, links)
        if ls.version == before:
            continue  # event was a topology no-op
        warm.refresh()
        cold = _AreaSolve(ls, me, mesh=mesh)  # cold solve of the same state
        np.testing.assert_array_equal(warm.d, cold.d)
        assert_solve_matches_oracle(ls, warm)
        applied += 1
    assert applied > 0
    return warm


class TestWarmStartDifferential:
    """The warm-start incremental event path (device-resident previous
    distances + on-device invalidation of increased entries) must be
    bit-identical to recompute-from-INF on arbitrary event sequences."""

    def test_grid_random_sequences(self):
        for seed in (3, 11):
            warm = run_warm_differential(grid_edges(4), "g0_0", seed, 14)
            assert warm.incremental_solves > 0

    def test_clos_random_sequence(self):
        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        )
        warm = run_warm_differential(edges, "rsw0_0", 7, 12)
        assert warm.incremental_solves > 0

    def test_increase_then_decrease_same_link(self):
        import dataclasses

        from openr_tpu.solver.tpu import _AreaSolve

        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("a", "d", 9)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        warm = _AreaSolve(ls, "a")
        cold_rounds = warm.rounds_last
        for metric in (8, 1):  # increase (invalidation pass), then decrease
            db = dbs["b"]
            db = dataclasses.replace(
                db,
                adjacencies=[
                    dataclasses.replace(adj, metric=metric)
                    if adj.other_node_name == "c"
                    else adj
                    for adj in db.adjacencies
                ],
            )
            dbs["b"] = db
            ls.update_adjacency_database(db)
            warm.refresh()
            cold = _AreaSolve(ls, "a")
            np.testing.assert_array_equal(warm.d, cold.d)
            assert_solve_matches_oracle(ls, warm)
            assert warm.rounds_last <= cold.rounds_last
        assert warm.incremental_solves == 2
        assert warm.rounds_last < cold_rounds  # warm win visible in counter

    def test_partition_flap_and_heal(self):
        import dataclasses

        from openr_tpu.solver.tpu import _AreaSolve

        # two triangles joined by one bridge: flapping it partitions
        edges = [
            ("a", "b", 1), ("b", "c", 1), ("c", "a", 1),
            ("c", "x", 2),  # bridge
            ("x", "y", 1), ("y", "z", 1), ("z", "x", 1),
        ]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        warm = _AreaSolve(ls, "a")
        for down in (True, False):
            db = dbs["c"]
            db = dataclasses.replace(
                db,
                adjacencies=[
                    dataclasses.replace(adj, is_overloaded=down)
                    if adj.other_node_name == "x"
                    else adj
                    for adj in db.adjacencies
                ],
            )
            dbs["c"] = db
            ls.update_adjacency_database(db)
            warm.refresh()
            cold = _AreaSolve(ls, "a")
            np.testing.assert_array_equal(warm.d, cold.d)
            assert_solve_matches_oracle(ls, warm)
            far = int(warm.d[0, warm.graph.node_index["z"]])
            assert (far >= INF) == down
        assert warm.incremental_solves == 2

    def test_node_overload_toggle_rides_warm_path(self):
        # ROADMAP item closed: an overload toggle is expressed as weight
        # increases on the node's out-edges and rides the existing warm
        # invalidation path — differential against cold AND the CPU oracle
        import dataclasses

        from openr_tpu.solver.tpu import _AreaSolve

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        warm = _AreaSolve(ls, "a")
        full_before = warm.full_solves
        for overloaded in (True, False):
            db = dataclasses.replace(dbs["b"], is_overloaded=overloaded)
            dbs["b"] = db
            ls.update_adjacency_database(db)
            warm.refresh()
            cold = _AreaSolve(ls, "a")
            np.testing.assert_array_equal(warm.d, cold.d)
            assert_solve_matches_oracle(ls, warm)
        # overload ON invalidates via out-edge seeds (inv rounds ran);
        # overload OFF is decrease-only and warm-starts directly
        assert warm.incremental_solves == 2
        assert warm.full_solves == full_before

    def test_node_overload_toggle_grid_differential(self):
        # the same toggle on a larger graph with ECMP structure: every
        # event sequence must stay bit-identical to cold + oracle
        import dataclasses

        from openr_tpu.solver.tpu import _AreaSolve

        edges = grid_edges(4)
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        warm = _AreaSolve(ls, "g0_0")
        # overload a transit node on the diagonal, then a corner, then heal
        for node, overloaded in (
            ("g1_1", True),
            ("g2_2", True),
            ("g1_1", False),
            ("g2_2", False),
        ):
            db = dataclasses.replace(dbs[node], is_overloaded=overloaded)
            dbs[node] = db
            ls.update_adjacency_database(db)
            warm.refresh()
            cold = _AreaSolve(ls, "g0_0")
            np.testing.assert_array_equal(warm.d, cold.d)
            assert_solve_matches_oracle(ls, warm)
        assert warm.incremental_solves == 4

    def test_oversized_event_falls_back_to_cold(self, monkeypatch):
        import dataclasses

        import openr_tpu.solver.tpu as tpu_mod

        # any non-empty patch overflows a zero-slot budget
        monkeypatch.setattr(tpu_mod, "_PATCH_SLOTS", 0)
        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("a", "d", 9)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        warm = tpu_mod._AreaSolve(ls, "a")
        full_before = warm.full_solves
        db = dbs["b"]
        db = dataclasses.replace(
            db,
            adjacencies=[
                dataclasses.replace(adj, metric=4) for adj in db.adjacencies
            ],
        )
        dbs["b"] = db
        ls.update_adjacency_database(db)
        warm.refresh()
        assert warm.incremental_solves == 0
        assert warm.full_solves == full_before + 1
        cold = tpu_mod._AreaSolve(ls, "a")
        np.testing.assert_array_equal(warm.d, cold.d)
        assert_solve_matches_oracle(ls, warm)

    def test_solver_exposes_spf_counters(self):
        import dataclasses

        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = make_prefix_state({"c": [PFXS[0]]})
        tpu = TpuSpfSolver("a")
        tpu.build_route_db("a", {"0": ls}, ps)
        assert tpu.counters["decision.spf.full_solves"] == 1
        assert tpu.counters["decision.spf.rounds_last"] >= 1
        cold_rounds = tpu.counters["decision.spf.rounds_last"]
        # weight-only event rides the warm path and the counters show it
        db = dbs["b"]
        db = dataclasses.replace(
            db,
            adjacencies=[
                dataclasses.replace(adj, metric=3)
                if adj.other_node_name == "c"
                else adj
                for adj in db.adjacencies
            ],
        )
        dbs["b"] = db
        ls.update_adjacency_database(db)
        db2 = tpu.build_route_db("a", {"0": ls}, ps)
        assert db2 is not None
        assert tpu.counters["decision.spf.incremental_solves"] == 1
        assert tpu.counters["decision.spf.full_solves"] == 1
        assert tpu.counters["decision.spf.rounds_last"] <= cold_rounds


def all_pairs_distance_check_graph(ls, graph):
    """all_pairs_distance_check against a pre-built CompiledGraph."""
    d = np.asarray(batched_spf(graph, np.arange(graph.n_pad, dtype=np.int32)))
    for src in graph.names:
        oracle = ls.get_spf_result(src)
        row = graph.node_index[src]
        for dst in graph.names:
            col = graph.node_index[dst]
            got = int(d[row, col])
            if dst in oracle:
                assert got == oracle[dst].metric, (src, dst)
            else:
                assert got >= INF, (src, dst)


class TestDeviceKsp:
    """Device-batched k-edge-disjoint shortest paths must reproduce the
    oracle's getKthPaths exactly (same paths, same order)."""

    def check_all_pairs_ksp(self, edges, me, overloaded=None, ks=(1, 2, 3)):
        ls_oracle = build_ls(edges, overloaded_nodes=overloaded)
        ls_dev = build_ls(edges, overloaded_nodes=overloaded)
        solver = TpuSpfSolver(me)
        solve = solver._area_solve(ls_dev, me)
        assert solve is not None
        dests = sorted(set(ls_oracle.node_names()) - {me})
        for k in ks:
            # prefetch path: one device batch for all dests at this k
            solver._prefetch_kth_paths(ls_dev, me, dests, k)
            for dest in dests:
                got = solver._kth_paths(ls_dev, me, dest, k)
                want = ls_oracle.get_kth_paths(me, dest, k)
                assert got == want, (me, dest, k, got, want)
        return solve

    def test_square_ring(self):
        solve = self.check_all_pairs_ksp(
            [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("d", "a", 1)], "a"
        )
        assert solve.ksp_device_batches >= 1

    def test_diamond_unequal(self):
        self.check_all_pairs_ksp(
            [("a", "b", 1), ("a", "c", 2), ("b", "d", 1), ("c", "d", 1)], "a"
        )

    def test_grid(self):
        self.check_all_pairs_ksp(grid_edges(4), "g0_0", ks=(1, 2))

    def test_overloaded_transit_node(self):
        self.check_all_pairs_ksp(
            [("a", "b", 1), ("b", "c", 1), ("a", "d", 1), ("d", "c", 1)],
            "a",
            overloaded={"b"},
        )

    def test_random_graphs(self):
        rng = random.Random(99)
        for trial in range(8):
            n = rng.randint(4, 12)
            nodes = [f"n{i}" for i in range(n)]
            edges = []
            for i in range(1, n):
                edges.append(
                    (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 5))
                )
            for _ in range(rng.randint(1, n)):
                a, b = rng.sample(nodes, 2)
                if not any({a, b} == {x, y} for x, y, _ in edges):
                    edges.append((a, b, rng.randint(1, 5)))
            overloaded = {
                nodes[i] for i in range(1, n) if rng.random() < 0.2
            }
            self.check_all_pairs_ksp(
                edges, nodes[0], overloaded=overloaded, ks=(1, 2, 3)
            )

    def test_single_dest_on_demand(self):
        # no prefetch: _kth_paths alone must still batch-solve lazily
        ls_oracle = build_ls(grid_edges(3))
        ls_dev = build_ls(grid_edges(3))
        solver = TpuSpfSolver("g0_0")
        got = solver._kth_paths(ls_dev, "g0_0", "g2_2", 2)
        want = ls_oracle.get_kth_paths("g0_0", "g2_2", 2)
        assert got == want


PFXS = ["10.1.0.0/16", "10.2.0.0/16", "10.3.0.0/16"]


def make_prefix_state(announcers, area="0", **entry_kw):
    ps = PrefixState()
    for node, pfxs in announcers.items():
        ps.update_prefix_database(
            PrefixDatabase(
                node,
                [PrefixEntry(IpPrefix(p), **entry_kw) for p in pfxs],
                area=area,
            )
        )
    return ps


def assert_route_db_equal(db_cpu, db_tpu):
    assert db_cpu is not None and db_tpu is not None
    assert set(db_cpu.unicast_entries) == set(db_tpu.unicast_entries)
    for prefix, entry in db_cpu.unicast_entries.items():
        assert db_tpu.unicast_entries[prefix] == entry, prefix
    assert set(db_cpu.mpls_entries) == set(db_tpu.mpls_entries)
    for label, entry in db_cpu.mpls_entries.items():
        assert db_tpu.mpls_entries[label] == entry, label


def run_parity(edges, announcers, me, overloaded=None, lfa=False, **entry_kw):
    ls_cpu = build_ls(edges, overloaded_nodes=overloaded)
    ls_tpu = build_ls(edges, overloaded_nodes=overloaded)
    ps = make_prefix_state(announcers, **entry_kw)
    cpu = SpfSolver(me, compute_lfa_paths=lfa)
    tpu = TpuSpfSolver(me, compute_lfa_paths=lfa)
    db_cpu = cpu.build_route_db(me, {"0": ls_cpu}, ps)
    db_tpu = tpu.build_route_db(me, {"0": ls_tpu}, ps)
    assert_route_db_equal(db_cpu, db_tpu)
    assert tpu.device_solves >= 1
    return db_tpu


class TestRouteDbParity:
    def test_line(self):
        run_parity(
            [("a", "b", 1), ("b", "c", 2)],
            {"b": [PFXS[0]], "c": [PFXS[1]]},
            "a",
        )

    def test_grid_ecmp(self):
        run_parity(
            grid_edges(4),
            {"g3_3": [PFXS[0]], "g0_3": [PFXS[1]], "g2_1": [PFXS[2]]},
            "g0_0",
        )

    def test_fabric(self):
        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=4
        )
        run_parity(
            edges,
            {"rsw1_0": [PFXS[0]], "rsw0_3": [PFXS[1]]},
            "rsw0_0",
        )

    def test_anycast(self):
        run_parity(
            [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)],
            {"b": [PFXS[0]], "d": [PFXS[0]]},
            "a",
        )

    def test_overloaded_announcer(self):
        run_parity(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": [PFXS[0]], "c": [PFXS[0]]},
            "a",
            overloaded={"b"},
        )

    def test_overloaded_transit(self):
        run_parity(
            [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)],
            {"c": [PFXS[0]]},
            "a",
            overloaded={"b"},
        )

    def test_lfa_parity(self):
        run_parity(
            [("a", "b", 1), ("a", "c", 2), ("c", "b", 1)],
            {"b": [PFXS[0]]},
            "a",
            lfa=True,
        )

    def test_ksp2_parity(self):
        run_parity(
            [("a", "b", 1), ("a", "c", 1), ("c", "b", 1)],
            {"b": [PFXS[0]]},
            "a",
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )

    def test_ksp2_anycast_grid_parity(self):
        # anycast KSP2 over a grid: multiple dests per prefix exercises the
        # one-device-call-per-k prefetch batching in _select_ksp2
        run_parity(
            grid_edges(4),
            {
                "g3_3": [PFXS[0]],
                "g0_3": [PFXS[0], PFXS[1]],
                "g2_1": [PFXS[1], PFXS[2]],
                "g1_2": [PFXS[2]],
            },
            "g0_0",
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )

    def test_wan_random(self):
        edges = wan_edges(24, degree=4, seed=7)
        run_parity(
            edges,
            {"w3": [PFXS[0]], "w17": [PFXS[1]], "w9": [PFXS[2]]},
            "w0",
        )

    def test_random_parity_sweep(self):
        rng = random.Random(1234)
        for trial in range(6):
            n = rng.randint(5, 14)
            nodes = [f"n{i}" for i in range(n)]
            edges = []
            for i in range(1, n):
                edges.append(
                    (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 9))
                )
            for _ in range(rng.randint(0, n // 2)):
                a, b = rng.sample(nodes, 2)
                if not any(
                    {a, b} == {x, y} for x, y, _ in edges
                ):
                    edges.append((a, b, rng.randint(1, 9)))
            announcers = {
                rng.choice(nodes[1:]): [PFXS[i % 3]] for i in range(3)
            }
            overloaded = {
                nodes[i] for i in range(1, n) if rng.random() < 0.15
            }
            run_parity(edges, announcers, nodes[0], overloaded=overloaded)

    def test_multi_area_parity_with_absent_node(self):
        # me participates in area A only; area B's graph lacks me entirely —
        # the TPU backend must fall back to the CPU oracle for area B
        def build(area, edges):
            ls = LinkState(area)
            for db in build_adj_dbs(edges, area=area).values():
                ls.update_adjacency_database(db)
            return ls

        als_cpu = {
            "A": build("A", [("a", "b", 1)]),
            "B": build("B", [("x", "y", 1)]),
        }
        als_tpu = {
            "A": build("A", [("a", "b", 1)]),
            "B": build("B", [("x", "y", 1)]),
        }
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase("b", [PrefixEntry(IpPrefix(PFXS[0]))], area="A")
        )
        ps.update_prefix_database(
            PrefixDatabase("y", [PrefixEntry(IpPrefix(PFXS[1]))], area="B")
        )
        db_cpu = SpfSolver("a").build_route_db("a", als_cpu, ps)
        db_tpu = TpuSpfSolver("a").build_route_db("a", als_tpu, ps)
        assert_route_db_equal(db_cpu, db_tpu)
        # reachable prefix programmed, unreachable (other area) not
        assert IpPrefix(PFXS[0]) in db_tpu.unicast_entries
        assert IpPrefix(PFXS[1]) not in db_tpu.unicast_entries

    def test_incremental_update_recompiles(self):
        # topology change bumps LinkState.version; solver must re-solve
        edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
        ls = build_ls(edges)
        ps = make_prefix_state({"c": [PFXS[0]]})
        tpu = TpuSpfSolver("a")
        db1 = tpu.build_route_db("a", {"0": ls}, ps)
        nh1 = {
            nh.neighbor_node
            for nh in db1.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh1 == {"b"}
        solves_before = tpu.device_solves
        # flap a-b: now direct a-c wins
        dbs = build_adj_dbs([("a", "c", 5)])
        from openr_tpu.types import AdjacencyDatabase

        new_a = AdjacencyDatabase(
            "a",
            [x for x in build_adj_dbs(edges)["a"].adjacencies
             if x.other_node_name != "b"],
            area="0",
        )
        ls.update_adjacency_database(new_a)
        db2 = tpu.build_route_db("a", {"0": ls}, ps)
        nh2 = {
            nh.neighbor_node
            for nh in db2.unicast_entries[IpPrefix(PFXS[0])].nexthops
        }
        assert nh2 == {"c"}
        assert tpu.device_solves == solves_before + 1
        # unchanged topology: cached solve reused
        tpu.build_route_db("a", {"0": ls}, ps)
        assert tpu.device_solves == solves_before + 1


class TestDeviceBufferProvenance:
    def test_two_refreshes_without_solve_fall_back_to_full_diff(self):
        """Safety of the changed-edges fast path: if the solver's device
        snapshot is two refreshes behind (parent_version mismatch), the
        full diff must catch BOTH events' weight changes — a silent miss
        here means stale device weights and wrong routes, not a crash."""
        import dataclasses

        from openr_tpu.solver import SpfSolver, TpuSpfSolver
        from openr_tpu.lsdb.prefix_state import PrefixState
        from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry

        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("a", "d", 9)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = PrefixState()
        for i, node in enumerate(sorted(dbs)):
            ps.update_prefix_database(
                PrefixDatabase(
                    node, [PrefixEntry(IpPrefix(f"10.{i}.0.0/24"))], area="0"
                )
            )
        tpu = TpuSpfSolver("a")
        assert tpu.build_route_db("a", {"0": ls}, ps) == SpfSolver(
            "a"
        ).build_route_db("a", {"0": ls}, ps)

        # two graph refreshes with NO solve in between: the device
        # snapshot (w_ver) is two versions behind, so the fast-path guard
        # must fail and the full diff must catch both events' changes
        from openr_tpu.ops.graph import refresh_graph

        area = tpu._solves[(ls.area, "a")][1]
        for metric in (5, 7):
            db = dbs["b"]
            db = dataclasses.replace(
                db,
                adjacencies=[
                    dataclasses.replace(adj, metric=metric)
                    for adj in db.adjacencies
                ],
            )
            dbs["b"] = db
            ls.update_adjacency_database(db)
            area.graph = refresh_graph(area.graph, ls)
        assert area.graph.parent_version != area._dev["w_ver"]

        # solving against the doubly-refreshed graph must see the final
        # weights (stale device buffers here would mean wrong distances)
        area._solve()
        got = tpu.build_route_db("a", {"0": ls}, ps)
        want = SpfSolver("a").build_route_db("a", {"0": ls}, ps)
        assert got == want
        assert area._dev["w_ver"] == area.graph.version
