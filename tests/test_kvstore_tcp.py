"""KvStore peering over real TCP sockets.

The reference peers stores across nodes with ZMQ/thrift sockets
(openr/kvstore/KvStore.h:130,453; exercised by KvStoreThriftTest.cpp).
These tests drive the TCP transport (openr_tpu.kvstore.tcp) through the
same scenarios: 3-way full sync, flooding, peer-FSM failure/recovery on
socket death — first between stores in one process on ephemeral ports,
then against a KvStore living in a separate OS process.
"""

import asyncio
import os
import subprocess
import sys

import pytest

from openr_tpu.kvstore import KvStore, KvStoreParams, PeerSpec, PeerState
from openr_tpu.kvstore.tcp import KvStoreTcpServer, TcpTransport
from openr_tpu.types import TTL_INFINITY, Value


def v(version=1, originator="node1", value=b"data", ttl=TTL_INFINITY):
    return Value(version, originator, value, ttl, 0)


def run(coro, timeout=30.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


async def make_tcp_store(name):
    """KvStore + TCP server on an ephemeral port; returns (store, server)."""
    store = KvStore(
        name, ["0"], TcpTransport(), params=KvStoreParams(node_id=name)
    )
    server = KvStoreTcpServer(store)
    await server.start()
    return store, server


async def settle(delay=0.1):
    await asyncio.sleep(delay)


class TestTcpPeering:
    def test_full_sync_both_directions(self):
        async def body():
            a, srv_a = await make_tcp_store("a")
            b, srv_b = await make_tcp_store("b")
            a.set_key("k1", v(originator="a", value=b"va"))
            b.set_key("k2", v(originator="b", value=b"vb"))
            a.add_peers({"b": PeerSpec(srv_b.address)})
            await settle()
            assert a.get_key("k2").value == b"vb"
            assert b.get_key("k1").value == b"va"  # finalize leg
            assert a.db().peer_state("b") == PeerState.INITIALIZED
            await srv_a.stop()
            await srv_b.stop()

        run(body())

    def test_flood_through_chain(self):
        async def body():
            stores, servers = {}, {}
            for name in "abc":
                stores[name], servers[name] = await make_tcp_store(name)
            # line a - b - c, peering both directions like LinkMonitor would
            stores["a"].add_peers({"b": PeerSpec(servers["b"].address)})
            stores["b"].add_peers(
                {
                    "a": PeerSpec(servers["a"].address),
                    "c": PeerSpec(servers["c"].address),
                }
            )
            stores["c"].add_peers({"b": PeerSpec(servers["b"].address)})
            await settle()
            stores["a"].set_key("k", v(originator="a", value=b"flooded"))
            await settle()
            assert stores["c"].get_key("k").value == b"flooded"
            # path-vector loop prevention: no storm, stores converged
            assert stores["b"].get_key("k").value == b"flooded"
            for srv in servers.values():
                await srv.stop()

        run(body())

    def test_conflict_resolved_by_crdt_merge(self):
        async def body():
            a, srv_a = await make_tcp_store("a")
            b, srv_b = await make_tcp_store("b")
            a.set_key("k", v(version=3, originator="a", value=b"a3"))
            b.set_key("k", v(version=5, originator="b", value=b"b5"))
            a.add_peers({"b": PeerSpec(srv_b.address)})
            await settle()
            assert a.get_key("k").value == b"b5"
            assert b.get_key("k").value == b"b5"
            await srv_a.stop()
            await srv_b.stop()

        run(body())

    def test_peer_down_backoff_and_recovery(self):
        async def body():
            a, srv_a = await make_tcp_store("a")
            b, srv_b = await make_tcp_store("b")
            addr_b = srv_b.address
            host, port = addr_b.rsplit(":", 1)
            await srv_b.stop()  # peer dead: connection refused
            a.add_peers({"b": PeerSpec(addr_b)})
            await settle()
            assert a.db().peer_state("b") == PeerState.IDLE
            # bring the peer back on the SAME port; retry task resyncs
            b.set_key("k", v(originator="b", value=b"back"))
            srv_b2 = KvStoreTcpServer(b, host=host, port=int(port))
            await srv_b2.start()
            await settle(0.5)  # covers the initial 64ms..s backoff window
            assert a.db().peer_state("b") == PeerState.INITIALIZED
            assert a.get_key("k").value == b"back"
            await srv_a.stop()
            await srv_b2.stop()

        run(body())


_CHILD_SCRIPT = """
import asyncio, sys

from openr_tpu.kvstore import KvStore, KvStoreParams
from openr_tpu.kvstore.tcp import KvStoreTcpServer, TcpTransport
from openr_tpu.types import Value


async def main():
    store = KvStore("remote", ["0"], TcpTransport(),
                    params=KvStoreParams(node_id="remote"))
    server = KvStoreTcpServer(store)
    await server.start()
    store.set_key("k_remote", Value(1, "remote", b"from-remote"))
    print(server.port, flush=True)
    # stay alive until the parent closes stdin
    await asyncio.get_event_loop().run_in_executor(None, sys.stdin.read)


asyncio.new_event_loop().run_until_complete(main())
"""


class TestCrossProcess:
    def test_sync_with_separate_process(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            filter(None, [os.getcwd(), env.get("PYTHONPATH")])
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        child = subprocess.Popen(
            [sys.executable, "-c", _CHILD_SCRIPT],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            env=env,
            text=True,
        )
        try:
            port_line = child.stdout.readline().strip()
            assert port_line.isdigit(), f"child failed: {port_line!r}"
            remote_addr = f"127.0.0.1:{port_line}"

            async def body():
                local, srv = await make_tcp_store("local")
                local.set_key("k_local", v(originator="local", value=b"mine"))
                local.add_peers({"remote": PeerSpec(remote_addr)})
                await settle(0.3)
                # pulled the remote's key over the socket
                assert local.get_key("k_remote").value == b"from-remote"
                assert (
                    local.db().peer_state("remote") == PeerState.INITIALIZED
                )
                # finalize-sync leg pushed ours into the child process
                probe = TcpTransport()
                pub = await probe.dump_key_vals(remote_addr, "0")
                assert pub.key_vals["k_local"].value == b"mine"
                probe.close()
                await srv.stop()

            run(body())
        finally:
            child.stdin.close()
            child.wait(timeout=10)
