"""Fleet observer tests (docs/Monitoring.md "Fleet observer & SLO
watchdog"): the bounded time-series store (exact eviction accounting,
gap markers, sparse-codec histogram merge), the typed counter-reset
epoch machinery (monitor/exporter.py), the standing SLO rules, offline
replay, the stalled-subscription overflow/gap contract (ISSUE satellite
3), restart attribution of mid-scrape node death (satellite 1), and the
FLEET_SMOKE tier-1 acceptance with the `breeze fleet report --json`
round-trip."""

import asyncio
import json

import pytest

from openr_tpu.fleet import (
    FleetCollector,
    FleetConfig,
    FleetObserver,
    FleetStore,
    SloConfig,
    evaluate,
    replay_soak_report,
)
from openr_tpu.fleet.rules import (
    E2E_COUNT,
    E2E_P95,
    GAUGE_PREFIX,
    RATE_PREFIX,
    STAGE_AVG_PREFIX,
)
from openr_tpu.monitor.exporter import (
    CounterEpochTracker,
    histogram_from_parsed,
    histogram_interval,
    parse_metrics_text,
    render_metrics_text,
)
from openr_tpu.testing.faults import FaultInjector, injected
from openr_tpu.utils.counters import Histogram


def run(coro, timeout=120.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


# ---------------------------------------------------------------------------
# store: rings, eviction accounting, gaps, histogram merge
# ---------------------------------------------------------------------------


class TestFleetStore:
    def test_ring_eviction_accounting_exact(self):
        store = FleetStore(capacity=4)
        for i in range(11):
            store.record("n0", "m", float(i), float(i))
        acc = store.accounting()
        assert acc["recorded"] == 11
        assert acc["retained"] == 4
        assert acc["evicted"] == 7
        assert acc["recorded"] == acc["retained"] + acc["evicted"]
        # the ring keeps the newest tail
        assert store.series("n0", "m") == [7.0, 8.0, 9.0, 10.0]
        assert store.last("n0", "m") == 10.0

    def test_gap_markers_never_silent(self):
        store = FleetStore(capacity=8)
        store.record("n0", "m", 1.0, 1.0)
        assert not store.gap_since("n0", 0.0)
        store.mark_gap("n0", 2.0, "stream_resync")
        assert store.gaps_marked == 1
        assert store.gaps("n0") == [(2.0, "stream_resync")]
        assert store.gap_since("n0", 1.5)
        assert not store.gap_since("n0", 2.5)
        # bounded, but the total stays exact
        for i in range(600):
            store.mark_gap("n0", float(i), "x")
        assert store.gaps_marked == 601
        assert len(store.gaps("n0")) == store.max_gaps

    def test_histogram_merge_via_sparse_codec(self):
        store = FleetStore()
        h1, h2 = Histogram(), Histogram()
        for v in (1.0, 2.0, 4.0):
            h1.record(v)
        for v in (8.0, 16.0):
            h2.record(v)
        store.record_histogram_sparse("n0", "fib.program_ms", h1.to_sparse())
        store.record_histogram_sparse("n1", "fib.program_ms", h2.to_sparse())
        merged = store.merged_histogram("fib.program_ms")
        assert merged.count == 5
        assert merged.sum == pytest.approx(31.0)
        assert merged.max == 16.0
        # per-node view survives next to the merge
        assert store.node_histogram("n0", "fib.program_ms").count == 3

    def test_tail_shape(self):
        store = FleetStore(capacity=4)
        store.record("n0", "m", 1.0, 5.0)
        store.mark_gap("n0", 2.0, "restart")
        h = Histogram()
        h.record(3.0)
        store.record_histogram("n0", "x_ms", h)
        tail = store.tail("n0")
        assert tail["series"]["m"] == [[1.0, 5.0]]
        assert tail["gaps"] == [[2.0, "restart"]]
        assert tail["histograms"]["x_ms"]["count"] == 1


# ---------------------------------------------------------------------------
# typed counter-reset epochs + histogram interval diffs (satellite 2)
# ---------------------------------------------------------------------------


class TestCounterEpochs:
    def test_monotone_deltas_within_epoch(self):
        tr = CounterEpochTracker()
        first = tr.observe("n0", {"a": 5.0, "b": 1.0})
        assert first["first"] and not first["reset"]
        obs = tr.observe("n0", {"a": 8.0, "b": 1.0, "c": 2.0})
        assert not obs["reset"] and obs["epoch"] == 0
        assert obs["deltas"] == {"a": 3.0, "b": 0.0, "c": 2.0}

    def test_reset_opens_typed_epoch_and_rebases(self):
        tr = CounterEpochTracker()
        tr.observe("n0", {"a": 100.0, "b": 7.0})
        obs = tr.observe("n0", {"a": 3.0, "b": 7.0})
        assert obs["reset"] is True
        assert obs["epoch"] == 1
        assert obs["decreased"] == ["a"]
        # restart-from-zero rebase: the new absolutes ARE the deltas
        assert obs["deltas"] == {"a": 3.0, "b": 7.0}
        # next scrape differences within the new epoch
        obs2 = tr.observe("n0", {"a": 5.0, "b": 9.0})
        assert not obs2["reset"] and obs2["epoch"] == 1
        assert obs2["deltas"] == {"a": 2.0, "b": 2.0}

    def test_forget_consumes_no_epoch(self):
        tr = CounterEpochTracker()
        tr.observe("n0", {"a": 100.0})
        tr.forget("n0")
        obs = tr.observe("n0", {"a": 1.0})
        assert not obs["reset"] and obs["epoch"] == 0

    def test_epochs_are_per_node(self):
        tr = CounterEpochTracker()
        tr.observe("n0", {"a": 5.0})
        tr.observe("n1", {"a": 5.0})
        assert tr.observe("n0", {"a": 1.0})["epoch"] == 1
        assert tr.observe("n1", {"a": 9.0})["epoch"] == 0


def _parsed_hist(hist: Histogram, name: str = "convergence.e2e_ms"):
    text = render_metrics_text({}, {name: hist}, node_name="n0")
    parsed = parse_metrics_text(text)
    from openr_tpu.monitor.exporter import prom_name

    return parsed["histograms"][prom_name(name)]


class TestHistogramInterval:
    def test_interval_from_cumulative_diff(self):
        h = Histogram()
        for v in (10.0, 12.0):
            h.record(v)
        prev = _parsed_hist(h)
        for v in (400.0, 410.0, 420.0, 430.0):
            h.record(v)
        cur = _parsed_hist(h)
        interval = histogram_interval(prev, cur)
        assert interval["count"] == 4
        assert interval["avg"] == pytest.approx(415.0, rel=0.01)
        # the interval p95 reflects only the NEW samples (~430ms bucket),
        # not the old 10ms ones
        assert 350.0 < interval["p95"] < 520.0

    def test_reset_rebases_on_zero(self):
        h = Histogram()
        for v in (50.0, 60.0, 70.0):
            h.record(v)
        prev = _parsed_hist(h)
        fresh = Histogram()
        fresh.record(5.0)
        interval = histogram_interval(prev, _parsed_hist(fresh))
        assert interval["count"] == 1  # not negative, not 1-3
        assert interval["avg"] == pytest.approx(5.0)

    def test_idle_interval(self):
        h = Histogram()
        h.record(5.0)
        cur = _parsed_hist(h)
        assert histogram_interval(cur, cur)["count"] == 0

    def test_histogram_from_parsed_round_trip(self):
        h = Histogram()
        for v in (0.5, 3.0, 3.1, 40.0, 500.0):
            h.record(v)
        got = histogram_from_parsed(_parsed_hist(h))
        assert got.count == h.count
        assert got.sum == pytest.approx(h.sum)
        assert got.buckets == h.buckets
        # rehydrated histograms merge like native ones
        merged = Histogram().merge(got).merge(got)
        assert merged.count == 2 * h.count


# ---------------------------------------------------------------------------
# standing SLO rules
# ---------------------------------------------------------------------------


def _seed_stage_baseline(store, node="n0"):
    h = Histogram()
    for _ in range(20):
        h.record(2.0)
    store.record_histogram(node, "fib.program_ms", h)


class TestRules:
    def test_clean_store_no_findings(self):
        store = FleetStore()
        store.record("n0", E2E_P95, 1.0, 20.0)
        store.record("n0", E2E_COUNT, 1.0, 4.0)
        store.record("n0", GAUGE_PREFIX + "decision.spf.fallback_active",
                     1.0, 0.0)
        assert evaluate(store, SloConfig()) == []

    def test_convergence_budget_breach_names_worst_node_and_stage(self):
        store = FleetStore()
        for node, p95 in (("n0", 1500.0), ("n1", 2500.0), ("n2", 30.0)):
            store.record(node, E2E_P95, 1.0, p95)
            store.record(node, E2E_COUNT, 1.0, 3.0)
        _seed_stage_baseline(store, "n1")
        store.record("n1", STAGE_AVG_PREFIX + "fib.program_ms", 1.0, 2400.0)
        store.record("n1", STAGE_AVG_PREFIX + "decision.route_build_ms",
                     1.0, 1.0)
        findings = evaluate(
            store, SloConfig(convergence_p95_budget_ms=1000.0,
                             trend_min_windows=0)
        )
        assert len(findings) == 1
        f = findings[0]
        assert f.kind == "convergence_p95"
        assert f.node == "n1"
        assert f.value == 2500.0
        assert sorted(f.evidence["offenders"]) == ["n0", "n1"]
        stages = [s["stage"] for s in f.attribution]
        assert stages[0] == "fib.program_ms"
        assert "decision.route_build_ms" not in stages

    def test_convergence_budget_needs_events(self):
        store = FleetStore()
        store.record("n0", E2E_P95, 1.0, 9999.0)
        store.record("n0", E2E_COUNT, 1.0, 0.0)
        cfg = SloConfig(convergence_p95_budget_ms=100.0,
                        convergence_min_events=1, trend_min_windows=0)
        assert evaluate(store, cfg) == []

    def test_trend_step_detection(self):
        store = FleetStore()
        series = [10.0] * 6 + [200.0] * 4
        for i, v in enumerate(series):
            store.record("n0", E2E_P95, float(i), v)
        findings = evaluate(
            store,
            SloConfig(convergence_p95_budget_ms=0.0, trend_min_windows=6),
        )
        assert [f.kind for f in findings] == ["convergence_trend"]
        step = findings[0].evidence["step"]
        assert step["index"] == 6
        assert step["before_ms"] == pytest.approx(10.0)

    def test_solver_health_fallback_and_trips(self):
        store = FleetStore()
        store.record("n0", GAUGE_PREFIX + "decision.spf.fallback_active",
                     1.0, 1.0)
        store.record("n1", RATE_PREFIX + "decision.spf.breaker_trips",
                     1.0, 2.0)
        kinds = sorted(
            (f.kind, f.node)
            for f in evaluate(
                store, SloConfig(convergence_p95_budget_ms=0.0,
                                 trend_min_windows=0)
            )
        )
        assert kinds == [("solver_health", "n0"), ("solver_health", "n1")]

    def test_stream_backpressure_and_admission(self):
        store = FleetStore()
        store.record("n0", RATE_PREFIX + "ctrl.stream.resyncs", 1.0, 3.0)
        store.record("n1", RATE_PREFIX + "ctrl.admission.timeouts", 1.0, 1.0)
        kinds = sorted(
            (f.kind, f.node)
            for f in evaluate(
                store, SloConfig(convergence_p95_budget_ms=0.0,
                                 trend_min_windows=0)
            )
        )
        assert kinds == [
            ("admission_rejections", "n1"),
            ("stream_backpressure", "n0"),
        ]

    def test_restart_health_stuck_stale_routes(self):
        store = FleetStore()
        for i in range(8):
            store.record("n0", GAUGE_PREFIX + "fib.num_stale_routes",
                         float(i), 4.0)
        store.record("n1", RATE_PREFIX + "fib.stale_deadline_flushes",
                     1.0, 1.0)
        findings = evaluate(
            store, SloConfig(convergence_p95_budget_ms=0.0,
                             trend_min_windows=0, stale_route_ticks=8)
        )
        assert sorted((f.kind, f.node) for f in findings) == [
            ("restart_health", "n0"),
            ("restart_health", "n1"),
        ]

    def test_flood_health_quarantine_and_rejects_breach(self):
        store = FleetStore()
        store.record("n0", RATE_PREFIX + "kvstore.quarantine.trips",
                     1.0, 1.0)
        store.record("n1", RATE_PREFIX + "kvstore.wire.rejected_total",
                     1.0, 3.0)
        findings = evaluate(
            store, SloConfig(convergence_p95_budget_ms=0.0,
                             trend_min_windows=0)
        )
        assert sorted((f.kind, f.node) for f in findings) == [
            ("flood_health", "n0"),
            ("flood_health", "n1"),
        ]
        by_node = {f.node: f for f in findings}
        assert "quarantine trip" in by_node["n0"].detail
        assert by_node["n1"].evidence["wire_rejects"] == 3.0

    def test_flood_health_duplicate_ratio_gated_by_floor(self):
        cfg = SloConfig(convergence_p95_budget_ms=0.0,
                        trend_min_windows=0,
                        flood_duplicate_budget=0.5,
                        flood_min_received=8)
        # under the receive floor: ratio never judged
        store = FleetStore()
        store.record("n0", RATE_PREFIX + "kvstore.flood.received", 1.0, 4.0)
        store.record("n0", RATE_PREFIX + "kvstore.flood.duplicates",
                     1.0, 4.0)
        assert evaluate(store, cfg) == []
        # over the floor and over budget: breach with the ratio named
        store.record("n0", RATE_PREFIX + "kvstore.flood.received", 2.0, 10.0)
        store.record("n0", RATE_PREFIX + "kvstore.flood.duplicates",
                     2.0, 8.0)
        findings = evaluate(store, cfg)
        assert [f.kind for f in findings] == ["flood_health"]
        assert findings[0].evidence["duplicate_ratio"] == 0.8
        # ratio check disabled by default (<0 budget)
        assert evaluate(
            store, SloConfig(convergence_p95_budget_ms=0.0,
                             trend_min_windows=0)
        ) == []


# ---------------------------------------------------------------------------
# collector: scrape folding, epochs -> gaps
# ---------------------------------------------------------------------------


def _scrape_text(counters, hists):
    return render_metrics_text(counters, hists, node_name="n0")


class TestCollector:
    def test_fold_interval_series_and_epoch_gap(self):
        store = FleetStore()
        collector = FleetCollector(store)
        h = Histogram()
        h.record(10.0)
        collector.fold(
            "n0",
            1.0,
            _scrape_text(
                {"ctrl.stream.resyncs": 0, "decision.spf.fallback_active": 0},
                {"convergence.e2e_ms": h, "fib.program_ms": h},
            ),
        )
        h.record(300.0)
        h.record(320.0)
        collector.fold(
            "n0",
            2.0,
            _scrape_text(
                {"ctrl.stream.resyncs": 2, "decision.spf.fallback_active": 0},
                {"convergence.e2e_ms": h, "fib.program_ms": h},
            ),
        )
        assert store.series("n0", E2E_COUNT) == [2.0]
        assert store.series("n0", RATE_PREFIX + "ctrl.stream.resyncs") == [
            2.0
        ]
        (p95,) = store.series("n0", E2E_P95)
        assert 250.0 < p95 < 400.0
        assert store.series("n0", STAGE_AVG_PREFIX + "fib.program_ms")
        assert store.merged_histogram("fib.program_ms").count == 3

        # counter reset (restarted node): typed epoch -> gap marker
        fresh = Histogram()
        fresh.record(5.0)
        obs = collector.fold(
            "n0",
            3.0,
            _scrape_text(
                {"ctrl.stream.resyncs": 0, "decision.spf.fallback_active": 0},
                {"convergence.e2e_ms": fresh, "fib.program_ms": fresh},
            ),
        )
        assert obs["reset"] is True
        assert store.gap_since("n0", 2.5)
        assert any(r == "counter_epoch" for _, r in store.gaps("n0"))


# ---------------------------------------------------------------------------
# offline replay
# ---------------------------------------------------------------------------


class TestReplay:
    def _soak_report(self, series, faulted=()):
        return {
            "windows": [
                {
                    "start": float(i),
                    "events": 3,
                    "faulted": i in faulted,
                    "e2e_p50_ms": v / 2,
                    "e2e_p95_ms": v,
                    "e2e_max_ms": v * 2,
                }
                for i, v in enumerate(series)
            ],
            "verdict": {"pass": True},
        }

    def test_replay_clean_soak_passes(self):
        report = replay_soak_report(
            self._soak_report([10.0] * 10),
            slo=SloConfig(convergence_p95_budget_ms=100.0),
        )
        assert report["verdict"]["pass"] is True
        assert report["replayed"]["windows"] == 10

    def test_replay_detects_step(self):
        report = replay_soak_report(
            self._soak_report([10.0] * 6 + [300.0] * 4),
            slo=SloConfig(convergence_p95_budget_ms=100.0),
        )
        assert report["verdict"]["pass"] is False
        kinds = {f["kind"] for f in report["findings"]}
        assert "convergence_p95" in kinds


# ---------------------------------------------------------------------------
# satellite 1: mid-scrape node death attribution
# ---------------------------------------------------------------------------


class TestScrapeDeathAttribution:
    def test_dead_node_counts_error_without_restart_window(self):
        observer = FleetObserver.for_hosts(["127.0.0.1:9"])

        async def body():
            ok = await observer._scrape_node("127.0.0.1:9", {})
            assert ok is False

        run(body())
        assert observer.counters.get("fleet.scrape_errors") == 1
        assert not observer.counters.get("fleet.restart_attributed")
        assert observer.store.gaps("127.0.0.1:9")[-1][1] == "scrape_error"

    def test_dead_node_attributed_inside_restart_window(self):
        observer = FleetObserver.for_hosts(["127.0.0.1:9"])
        observer.note_restart("127.0.0.1:9", window_s=60.0)

        async def body():
            await observer._scrape_node("127.0.0.1:9", {})

        run(body())
        assert not observer.counters.get("fleet.scrape_errors")
        assert observer.counters.get("fleet.restart_attributed") == 1
        assert observer.store.gaps("127.0.0.1:9")[-1][1] == "restart"

    def test_soak_scrape_log_attribution(self):
        from openr_tpu.testing.soak import _ScrapeLog

        class _DeadDaemon:
            class monitor:
                @staticmethod
                def get_counters():
                    raise ConnectionRefusedError("node restarting")

        log = _ScrapeLog()
        log.scrape("n1", _DeadDaemon())
        assert log.errors == 1 and log.restart_attributed == 0
        log.note_restart("n1")
        log.scrape("n1", _DeadDaemon())
        assert log.errors == 1 and log.restart_attributed == 1
        summary = log.summary()
        assert summary["restart_attributed"] == 1


# ---------------------------------------------------------------------------
# satellite 3: stalled fleet subscription -> marked resync, gap-marked
# ---------------------------------------------------------------------------


class TestStalledSubscriptionGap:
    def test_overflow_resync_gap_marked_no_silent_holes(self):
        from openr_tpu.ctrl import CtrlServer
        from openr_tpu.kvstore import InProcessTransport, KvStore
        from openr_tpu.streaming import StreamConfig, StreamManager

        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            store.db("0").set_key_vals(
                {"adj:n1": _value("n1")}
            )
            manager = StreamManager(
                kvstore_updates=store.updates_queue,
                config=StreamConfig(
                    subscriber_max_pending=1, coalesce_budget=2
                ),
            )
            manager.start()
            server = CtrlServer(
                "n1", port=0, kvstore=store, stream_manager=manager
            )
            port = await server.start()
            observer = FleetObserver.for_hosts(
                [f"127.0.0.1:{port}"],
                config=FleetConfig(scrape_interval_s=0.1),
            )
            node = f"127.0.0.1:{port}"
            with injected(FaultInjector()) as inj:
                # server-side stall of exactly the observer's stream
                inj.arm(
                    "ctrl.stream.deliver",
                    times=None,
                    action=lambda sub: setattr(sub, "throttle_s", 0.05),
                    when=lambda sub: getattr(sub, "label", "")
                    == "fleet-observer",
                )
                await observer.start()
                # wait for the subscription snapshot
                deadline = asyncio.get_running_loop().time() + 20
                while not observer.counters.get("fleet.stream_frames"):
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.02)
                # burst far past the coalesce budget while delivery crawls
                for i in range(30):
                    store.db("0").set_key_vals(
                        {f"adj:k{i}": _value("n1", version=i + 1)}
                    )
                    await asyncio.sleep(0.01)
                deadline = asyncio.get_running_loop().time() + 30
                while not observer.counters.get("fleet.stream_resyncs"):
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.02)
                inj.disarm("ctrl.stream.deliver")
            await observer.stop()
            await server.stop()
            manager.stop()
            store.stop()
            return observer

        observer = run(body())
        # the stalled stream recovered via a MARKED resync...
        assert observer.counters["fleet.stream_resyncs"] >= 1
        node = observer.store.nodes()[0] if observer.store.nodes() else None
        # ...and the store is provably gap-marked: no silent holes
        gaps = [
            reason
            for n in {g for g in observer._targets_fn()}
            for _, reason in observer.store.gaps(n)
        ]
        assert "stream_resync" in gaps, gaps
        # server side confirms the overflow actually happened
        # (coalesce -> budget exceeded -> marked resync)
        assert observer.counters["fleet.stream_frames"] >= 2


def _value(originator, version=1, value=b"x"):
    from openr_tpu.types import Value

    return Value(
        version=version, originator_id=originator, value=value, ttl=600000
    )


# ---------------------------------------------------------------------------
# FLEET_SMOKE (tier-1 acceptance) + breeze round-trip
# ---------------------------------------------------------------------------


class TestFleetSmoke:
    def test_fleet_smoke(self, tmp_path, capsys):
        from openr_tpu.cli.breeze import main as breeze_main
        from openr_tpu.fleet.smoke import run_fleet_smoke

        summary = run_fleet_smoke()
        # the acceptance assertions live inside run_fleet_smoke; pin the
        # headline evidence here too
        assert summary["faults_fired"] == 1
        assert len(summary["findings"]) == 1
        finding = summary["findings"][0]
        assert finding["kind"] == "convergence_p95"
        assert finding["node"] == summary["victim"]
        assert any(
            s["stage"] == "fib.program_ms" for s in finding["attribution"]
        )
        assert summary["forensics"][0]["id"] == finding["forensics_id"]

        # `breeze fleet report --json` round-trips the report (offline:
        # no daemon is dialed)
        path = tmp_path / "fleet.json"
        path.write_text(
            json.dumps(summary["report"], sort_keys=True, default=str)
        )
        rc = breeze_main(["fleet", "report", str(path), "--json"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "fleet verdict: BREACH" in out
        # the --json block is the exact report, round-tripped
        blob = out[out.index("{"):]
        assert json.loads(blob) == json.loads(path.read_text())


# ---------------------------------------------------------------------------
# python -m openr_tpu.fleet --replay (CLI)
# ---------------------------------------------------------------------------


def test_fleet_cli_replay(tmp_path, capsys):
    from openr_tpu.fleet.__main__ import main as fleet_main

    soak = {
        "windows": [
            {"start": float(i), "events": 2, "faulted": False,
             "e2e_p50_ms": 5.0, "e2e_p95_ms": 10.0, "e2e_max_ms": 20.0}
            for i in range(8)
        ],
        "verdict": {"pass": True},
    }
    src = tmp_path / "soak.json"
    src.write_text(json.dumps(soak))
    out = tmp_path / "fleet.json"
    rc = fleet_main(
        ["--replay", str(src), "--out", str(out), "--budget-ms", "100"]
    )
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["fleet"] == "PASS"
    report = json.loads(out.read_text())
    assert report["verdict"]["pass"] is True
    assert report["replayed"]["windows"] == 8
