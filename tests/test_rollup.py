"""Windowed-rollup tests: the eviction-proof invariant (windowed totals
account for every span even when the LogSample ring only retains a
tail), window-ring bounds, sparse-histogram round-trips, cross-node
snapshot merging, and the report/aggregate surfaces that carry the
cumulative-vs-windowed split."""

import asyncio

import pytest

from openr_tpu.monitor import LogSample, Monitor
from openr_tpu.monitor.report import (
    ConvergenceRollup,
    aggregate_convergence_reports,
    merge_rollup_snapshots,
    node_convergence_report,
)
from openr_tpu.monitor.spans import Span
from openr_tpu.utils.counters import Histogram


def _span_values(total_ms=5.0, **stages):
    values = {"event": "CONVERGENCE_TRACE", "span": "flap"}
    values.update({f"{k}_ms": v for k, v in stages.items()})
    values["total_ms"] = total_ms
    return values


class TestConvergenceRollup:
    def test_windows_and_cumulative_split(self):
        clock = {"t": 100.0}
        rollup = ConvergenceRollup(
            window_s=10.0, max_windows=8, clock=lambda: clock["t"]
        )
        rollup.record_span(_span_values(3.0, fib_program=1.0))
        clock["t"] = 112.0
        rollup.record_span(_span_values(7.0, fib_program=2.0))
        snap = rollup.snapshot()
        assert snap["events_total"] == 2
        assert [w["start"] for w in snap["windows"]] == [100.0, 110.0]
        assert all(w["events"] == 1 for w in snap["windows"])
        cum = Histogram.from_sparse(snap["cumulative"]["total"])
        assert cum.count == 2 and cum.max == 7.0

    def test_window_ring_bounded_with_eviction_accounting(self):
        clock = {"t": 0.0}
        rollup = ConvergenceRollup(
            window_s=1.0, max_windows=3, clock=lambda: clock["t"]
        )
        for i in range(10):
            clock["t"] = float(i)
            rollup.record_span(_span_values(1.0))
        snap = rollup.snapshot()
        assert len(snap["windows"]) == 3
        assert snap["window_evictions"] == 7
        assert snap["evicted_events"] == 7
        # the invariant: windowed + evicted == total, nothing lost
        assert (
            sum(w["events"] for w in snap["windows"])
            + snap["evicted_events"]
            == snap["events_total"]
            == 10
        )
        # cumulative layer kept every sample
        assert snap["cumulative"]["total"]["count"] == 10

    def test_out_of_order_stamp_folds_into_its_window(self):
        clock = {"t": 0.0}
        rollup = ConvergenceRollup(
            window_s=10.0, max_windows=8, clock=lambda: clock["t"]
        )
        rollup.record_span(_span_values(1.0), ts=5.0)
        rollup.record_span(_span_values(1.0), ts=25.0)
        rollup.record_span(_span_values(1.0), ts=7.0)  # late drain
        snap = rollup.snapshot()
        assert [w["events"] for w in snap["windows"]] == [2, 1]
        assert snap["evicted_events"] == 0

    def test_stamp_older_than_ring_counts_as_evicted(self):
        rollup = ConvergenceRollup(window_s=1.0, max_windows=2)
        for ts in (100.0, 101.0):
            rollup.record_span(_span_values(1.0), ts=ts)
        rollup.record_span(_span_values(1.0), ts=50.0)  # pre-ring
        snap = rollup.snapshot()
        assert snap["events_total"] == 3
        assert snap["evicted_events"] == 1
        assert sum(w["events"] for w in snap["windows"]) == 2
        assert snap["cumulative"]["total"]["count"] == 3

    def test_spanless_sample_ignored(self):
        rollup = ConvergenceRollup()
        rollup.record_span({"event": "CONVERGENCE_TRACE"})
        assert rollup.events_total == 0


class TestSparseHistogram:
    def test_round_trip_preserves_stats_and_percentiles(self):
        h = Histogram()
        for v in (0.0005, 1.5, 2.5, 40.0, 4000.0):
            h.record(v)
        back = Histogram.from_sparse(h.to_sparse())
        assert back.count == h.count
        assert back.sum == pytest.approx(h.sum)
        assert back.min == h.min and back.max == h.max
        for p in (50, 95, 99):
            assert back.percentile(p) == pytest.approx(h.percentile(p))

    def test_empty_round_trip(self):
        back = Histogram.from_sparse(Histogram().to_sparse())
        assert back.count == 0 and back.min is None


class TestMergeSnapshots:
    def test_same_window_merges_across_nodes(self):
        snaps = []
        for node_ms in (2.0, 8.0):
            rollup = ConvergenceRollup(window_s=10.0)
            rollup.record_span(_span_values(node_ms), ts=105.0)
            snaps.append(rollup.snapshot())
        merged = merge_rollup_snapshots(snaps)
        assert merged["events_total"] == 2
        assert len(merged["windows"]) == 1
        window = merged["windows"][0]
        assert window["start"] == 100.0 and window["events"] == 2
        total = window["stages"]["total"]
        assert total.count == 2 and total.max == 8.0
        assert merged["cumulative"]["total"].count == 2

    def test_empty_and_none_snapshots_tolerated(self):
        merged = merge_rollup_snapshots([None, {}, {"windows": []}])
        assert merged["events_total"] == 0 and merged["windows"] == []


class TestMonitorRecordTimeFold:
    def test_ring_evicts_but_rollup_counts_everything(self):
        """The headline invariant at the Monitor level: push 25 spans
        through a 4-deep ring — the ring holds the tail, the rollup
        holds history."""
        mon = Monitor("n1", max_event_log=4, rollup_window_s=60.0)
        for i in range(25):
            span = Span("flap")
            span.mark("fib.program")
            mon.add_event_log(span.to_log_sample())
            # interleave flood noise, the realistic eviction pressure
            mon.add_event_log(
                LogSample().add_string("event", "FLOOD_TRACE")
            )
        assert len(mon.get_event_logs()) == 4
        assert mon.rollup.events_total == 25
        assert mon.counters["monitor.event_log_evictions"] == 46
        report = node_convergence_report("n1", mon)
        assert len(report["spans"]) <= 4
        assert report["rollup"]["events_total"] == 25

    def test_aggregate_report_carries_rollup_section(self):
        monitors = []
        for node in ("a", "b"):
            mon = Monitor(node, max_event_log=2, rollup_window_s=60.0)
            for _ in range(6):
                span = Span("flap")
                span.mark("decision.route_build")
                span.mark("fib.program")
                mon.add_event_log(span.to_log_sample())
            monitors.append(mon)
        agg = aggregate_convergence_reports(
            node_convergence_report(m.node_name, m) for m in monitors
        )
        rollup = agg["rollup"]
        assert rollup["events_total"] == 12
        assert rollup["evicted_events"] == 0
        assert rollup["cumulative"]["total"]["count"] == 12
        assert rollup["windows"] and all(
            "e2e_ms" in w for w in rollup["windows"]
        )
        # the ring-derived section only saw the retained tail
        assert agg["spans_total"] == 4

    def test_reports_without_rollup_still_aggregate(self):
        """breeze perf report may fold reports from older daemons whose
        JSON carries no rollup key."""
        agg = aggregate_convergence_reports(
            [{"node": "old", "spans": [], "e2e_ms": [], "floods": []}]
        )
        assert agg["rollup"]["events_total"] == 0


class TestEmulatorEvictionProof:
    def test_flap_events_beyond_ring_all_counted(self):
        """The satellite contract: more flap events than max_event_log on
        a small VirtualNetwork — the windowed report counts every event
        Fib ever closed while the LogSample rings hold only the tail."""
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        n, flaps, ring = 3, 4, 2

        async def body():
            net = VirtualNetwork()
            for i in range(n):
                net.add_node(
                    f"n{i}",
                    loopback_prefix=f"10.{i}.0.0/24",
                    config_overrides={
                        "monitor_config": {
                            "max_event_log": ring,
                            "rollup_window_s": 0.5,
                        }
                    },
                )
            await net.start_all()
            for i in range(n - 1):
                net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

            def converged():
                for i in range(n):
                    got = set(
                        net.wrappers[f"n{i}"].programmed_prefixes()
                    )
                    want = {
                        f"10.{j}.0.0/24" for j in range(n) if j != i
                    }
                    if not want.issubset(got):
                        return False
                return True

            def partitioned():
                return "10.2.0.0/24" not in net.wrappers[
                    "n0"
                ].programmed_prefixes()

            try:
                await wait_until(converged, timeout=60.0)
                for _ in range(flaps):
                    net.fail_link("n1", "if1r", "n2", "if2l")
                    await wait_until(partitioned, timeout=60.0)
                    net.restore_link("n1", "if1r", "n2", "if2l")
                    await wait_until(converged, timeout=60.0)

                def fib_spans():
                    return sum(
                        w.daemon.fib.counters.get(
                            "fib.convergence_spans", 0
                        )
                        for w in net.wrappers.values()
                    )

                def rollup_events():
                    return sum(
                        w.daemon.monitor.rollup.events_total
                        for w in net.wrappers.values()
                    )

                await wait_until(
                    lambda: rollup_events() >= fib_spans()
                    and fib_spans() > 0,
                    timeout=20.0,
                )
                agg = net.convergence_report()
                closed = fib_spans()
            finally:
                await net.stop_all()

            rollup = agg["rollup"]
            # every span Fib closed is accounted, and there were more of
            # them than any ring could hold
            assert rollup["events_total"] == closed
            assert closed > ring
            assert (
                sum(w["events"] for w in rollup["windows"])
                + rollup["evicted_events"]
                == rollup["events_total"]
            )
            # the rings really did evict: the point-in-time section is
            # strictly smaller than history
            assert agg["spans_total"] <= n * ring
            assert agg["spans_total"] < rollup["events_total"]
            assert rollup["cumulative"]["total"]["count"] == closed

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(body(), 120.0))
        finally:
            loop.close()
