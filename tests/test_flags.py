"""Legacy flag bridge (config/flags.py — the GflagConfig equivalent,
openr/config/GflagConfig.h + common/Flags.cpp) and build info."""

import json

import pytest

from openr_tpu.config.flags import build_parser, config_from_flags, parse_flags
from openr_tpu.utils.build_info import get_build_info


def cfg_of(*argv):
    return config_from_flags(build_parser().parse_args(list(argv)))


def test_defaults_match_reference_timers():
    c = cfg_of("--node_name", "n1").config
    assert c.spark_config.hello_time_s == 20.0
    assert c.spark_config.fastinit_hello_time_ms == 500.0
    assert c.spark_config.keepalive_time_s == 2.0
    assert c.spark_config.hold_time_s == 10.0
    assert c.spark_config.graceful_restart_time_s == 30.0
    assert c.kvstore_config.key_ttl_ms == 300_000
    assert c.kvstore_config.sync_interval_s == 60
    assert c.decision_config.debounce_min_ms == 10.0
    assert c.decision_config.debounce_max_ms == 250.0
    assert c.openr_ctrl_port == 2018


def test_flags_map_onto_config_fields():
    c = cfg_of(
        "--node_name", "r1",
        "--areas", "pod1,pod2",
        "--openr_ctrl_port", "3018",
        "--spark_hold_time_s", "30",
        "--kvstore_key_ttl_ms", "60000",
        "--decision_solver_backend", "tpu",
        "--enable_lfa",
        "--iface_regex_include", "eth.*,po.*",
        "--redistribute_ifaces", "lo",
        "--enable_prefix_alloc",
        "--seed_prefix", "face:b00c::/56",
        "--alloc_prefix_len", "64",
        "--dryrun",
        "--enable_flood_optimization",
        "--is_flood_root",
        "--noenable_v4",
        "--memory_limit_mb", "1200",
    ).config
    assert c.node_name == "r1"
    assert [a.area_id for a in c.areas] == ["pod1", "pod2"]
    assert c.openr_ctrl_port == 3018
    assert c.spark_config.hold_time_s == 30.0
    assert c.kvstore_config.key_ttl_ms == 60_000
    assert c.decision_config.solver_backend == "tpu"
    assert c.decision_config.compute_lfa_paths
    assert c.link_monitor_config.include_interface_regexes == ["eth.*", "po.*"]
    assert c.link_monitor_config.redistribute_interface_regexes == ["lo"]
    assert c.enable_prefix_allocation
    assert c.prefix_allocation_config.seed_prefix == "face:b00c::/56"
    assert c.prefix_allocation_config.allocate_prefix_len == 64
    assert c.dryrun
    assert c.kvstore_config.enable_flood_optimization
    assert c.kvstore_config.is_flood_root
    assert not c.enable_v4
    assert c.watchdog_config.max_memory_mb == 1200


def test_config_file_overrides_flags(tmp_path):
    path = tmp_path / "openr.json"
    path.write_text(json.dumps({"node_name": "from_file", "dryrun": True}))
    config, args = parse_flags(
        ["--config", str(path), "--node_name", "from_flags"]
    )
    assert config.node_name == "from_file"
    assert config.is_dryrun()


def test_missing_node_name_rejected():
    with pytest.raises(ValueError):
        cfg_of()


def test_build_info_shape():
    info = get_build_info()
    assert info["build_package_name"] == "openr-tpu"
    assert info["build_package_version"]
    assert all(isinstance(v, str) for v in info.values())
