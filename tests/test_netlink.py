"""Native netlink library + NetlinkFibHandler integration tests.

These program real kernel state (proto-99 routes on the loopback device in
the test container) — the rebuild's analog of the reference's
netlink_fib_handler tests which need a live rtnetlink. Skipped wholesale if
the native library can't load or the kernel denies netlink writes.
"""

import asyncio

import pytest

from openr_tpu.nl import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native netlink library unavailable"
)

TEST_PROTO = 97  # avoid colliding with anything else in the container


def _can_program_routes() -> bool:
    from openr_tpu.nl import NetlinkError, NetlinkSocket, NlNextHop

    try:
        with NetlinkSocket() as s:
            lo = next(l for l in s.get_links() if l.name == "lo")
            s.add_unicast_route(
                "10.254.254.0/24", [NlNextHop(ifindex=lo.ifindex)],
                proto=TEST_PROTO,
            )
            s.del_unicast_route("10.254.254.0/24", proto=TEST_PROTO)
        return True
    except (NetlinkError, StopIteration):
        return False


CAN_WRITE = _can_program_routes()
needs_write = pytest.mark.skipif(
    not CAN_WRITE, reason="kernel denies netlink route writes"
)


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


class TestNetlinkSocket:
    def test_get_links_includes_loopback(self):
        from openr_tpu.nl import NetlinkSocket

        with NetlinkSocket() as s:
            links = s.get_links()
        names = {l.name for l in links}
        assert "lo" in names
        lo = next(l for l in links if l.name == "lo")
        assert lo.is_up
        assert lo.ifindex >= 1

    def test_get_addrs_includes_localhost(self):
        from openr_tpu.nl import NetlinkSocket

        with NetlinkSocket() as s:
            addrs = s.get_addrs()
        assert any(a.addr == "127.0.0.1" for a in addrs)

    @needs_write
    def test_route_roundtrip_v4(self):
        from openr_tpu.nl import NetlinkSocket, NlNextHop

        with NetlinkSocket() as s:
            lo = next(l for l in s.get_links() if l.name == "lo")
            s.add_unicast_route(
                "10.253.0.0/24", [NlNextHop(ifindex=lo.ifindex)],
                proto=TEST_PROTO,
            )
            try:
                routes = s.get_routes(proto=TEST_PROTO)
                assert [r.dest for r in routes] == ["10.253.0.0/24"]
                assert routes[0].nexthops[0].ifindex == lo.ifindex
            finally:
                s.del_unicast_route("10.253.0.0/24", proto=TEST_PROTO)
            assert s.get_routes(proto=TEST_PROTO) == []

    @needs_write
    def test_route_roundtrip_v6(self):
        from openr_tpu.nl import NetlinkSocket, NlNextHop

        with NetlinkSocket() as s:
            lo = next(l for l in s.get_links() if l.name == "lo")
            s.add_unicast_route(
                "fd00:dead::/64", [NlNextHop(ifindex=lo.ifindex)],
                proto=TEST_PROTO,
            )
            try:
                routes = s.get_routes(proto=TEST_PROTO)
                assert [r.dest for r in routes] == ["fd00:dead::/64"]
            finally:
                s.del_unicast_route("fd00:dead::/64", proto=TEST_PROTO)

    @needs_write
    def test_route_replace_changes_nexthops(self):
        from openr_tpu.nl import NetlinkSocket, NlNextHop

        with NetlinkSocket() as s:
            lo = next(l for l in s.get_links() if l.name == "lo")
            s.add_unicast_route(
                "10.253.1.0/24",
                [NlNextHop(via="127.0.0.2", ifindex=lo.ifindex)],
                proto=TEST_PROTO,
            )
            s.add_unicast_route(
                "10.253.1.0/24",
                [NlNextHop(via="127.0.0.3", ifindex=lo.ifindex)],
                proto=TEST_PROTO,
            )
            try:
                routes = s.get_routes(proto=TEST_PROTO)
                assert len(routes) == 1
                assert routes[0].nexthops[0].via == "127.0.0.3"
            finally:
                s.del_unicast_route("10.253.1.0/24", proto=TEST_PROTO)

    def test_bad_prefix_raises(self):
        from openr_tpu.nl import NetlinkError, NetlinkSocket, NlNextHop

        with NetlinkSocket() as s:
            with pytest.raises(NetlinkError):
                s.add_unicast_route(
                    "not-a-prefix/33", [NlNextHop(ifindex=1)],
                    proto=TEST_PROTO,
                )

    def test_event_subscription_fd(self):
        from openr_tpu.nl import NetlinkSocket

        with NetlinkSocket() as s:
            fd = s.subscribe()
            assert fd > 0
            assert s.next_event() is None  # nothing pending


@needs_write
class TestNetlinkFibHandler:
    def _cleanup(self, handler):
        async def body():
            await handler.sync_fib(0, [])
            handler.close()

        run(body())

    def test_add_delete_and_sync(self):
        from openr_tpu.platform.netlink_fib import NetlinkFibHandler
        from openr_tpu.types import IpPrefix, NextHop, UnicastRoute

        async def body():
            handler = NetlinkFibHandler(proto=TEST_PROTO)
            route = UnicastRoute(
                IpPrefix("10.252.0.0/24"), (NextHop("", iface="lo"),)
            )
            await handler.add_unicast_routes(0, [route])
            table = await handler.get_route_table_by_client(0)
            assert [str(r.dest) for r in table] == ["10.252.0.0/24"]
            assert table[0].nexthops[0].iface == "lo"

            # sync to a different set: old route removed, new added
            route2 = UnicastRoute(
                IpPrefix("10.252.1.0/24"), (NextHop("", iface="lo"),)
            )
            await handler.sync_fib(0, [route2])
            table = await handler.get_route_table_by_client(0)
            assert [str(r.dest) for r in table] == ["10.252.1.0/24"]

            await handler.delete_unicast_routes(
                0, [IpPrefix("10.252.1.0/24")]
            )
            assert await handler.get_route_table_by_client(0) == []
            handler.close()

        run(body())

    def test_delete_missing_route_is_idempotent(self):
        from openr_tpu.platform.netlink_fib import NetlinkFibHandler
        from openr_tpu.types import IpPrefix

        async def body():
            handler = NetlinkFibHandler(proto=TEST_PROTO)
            await handler.delete_unicast_routes(
                0, [IpPrefix("10.251.0.0/24")]
            )  # must not raise
            handler.close()

        run(body())

    def test_fib_module_end_to_end_against_kernel(self):
        """Decision delta → Fib → native netlink → kernel FIB."""
        from openr_tpu.fib import Fib, FibConfig
        from openr_tpu.messaging import RWQueue
        from openr_tpu.platform.netlink_fib import NetlinkFibHandler
        from openr_tpu.solver import DecisionRouteUpdate
        from openr_tpu.solver.routes import RibUnicastEntry
        from openr_tpu.types import IpPrefix, NextHop

        async def body():
            handler = NetlinkFibHandler(proto=TEST_PROTO)
            route_q = RWQueue()
            fib = Fib(
                FibConfig(my_node_name="n1"), handler, route_q
            )
            fib.start()
            route_q.push(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        RibUnicastEntry(
                            prefix=IpPrefix("10.250.0.0/24"),
                            nexthops={NextHop("", iface="lo")},
                        )
                    ]
                )
            )
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                table = await handler.get_route_table_by_client(0)
                if [str(r.dest) for r in table] == ["10.250.0.0/24"]:
                    break
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)
            fib.stop()
            await handler.sync_fib(0, [])
            handler.close()

        run(body())
