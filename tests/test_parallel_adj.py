"""Parallel-adjacency and churn-reliability coverage.

Mirrors the reference's parallel-adj ring fixture
(openr/decision/tests/DecisionTest.cpp:2932-3556) and reliability-under-
churn (:5556): multiple links between one node pair must form distinct
Link identities keyed by (node, iface) pairs (LinkState.h:107-110), ECMP
across equal-cost parallel links, deterministic selection after a metric
change; and a randomized update/withdraw storm must leave both solver
backends agreeing with an oracle built from the final state alone.
"""

import dataclasses
import random

import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.lsdb.prefix_state import PrefixState
from openr_tpu.solver import SpfSolver, TpuSpfSolver
from openr_tpu.topology import build_adj_dbs, make_adj_pair
from openr_tpu.types import (
    AdjacencyDatabase,
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
)


def parallel_pair(a, b, metrics):
    """AdjacencyDatabases for nodes a, b joined by len(metrics) parallel
    links (distinct interface names per link)."""
    adjs_a, adjs_b = [], []
    for i, m in enumerate(metrics):
        adj_a, adj_b = make_adj_pair(a, b, m)
        adj_a = dataclasses.replace(
            adj_a, if_name=f"po{i}-{a}", other_if_name=f"po{i}-{b}"
        )
        adj_b = dataclasses.replace(
            adj_b, if_name=f"po{i}-{b}", other_if_name=f"po{i}-{a}"
        )
        adjs_a.append(adj_a)
        adjs_b.append(adj_b)
    return (
        AdjacencyDatabase(a, adjs_a, area="0", node_label=100),
        AdjacencyDatabase(b, adjs_b, area="0", node_label=101),
    )


class TestParallelAdjacencies:
    def test_parallel_links_have_distinct_identities(self):
        ls = LinkState("0")
        db_a, db_b = parallel_pair("a", "b", [1, 1, 1])
        ls.update_adjacency_database(db_a)
        ls.update_adjacency_database(db_b)
        assert ls.num_links() == 3
        res = ls.run_spf("a")
        assert res["b"].metric == 1

    def test_metric_change_prefers_one_parallel_link(self):
        ls = LinkState("0")
        db_a, db_b = parallel_pair("a", "b", [10, 10])
        ls.update_adjacency_database(db_a)
        ls.update_adjacency_database(db_b)
        assert ls.run_spf("a")["b"].metric == 10
        # drop one link's metric: shortest path uses it exclusively
        db_a2, db_b2 = parallel_pair("a", "b", [10, 3])
        ls.update_adjacency_database(db_a2)
        ls.update_adjacency_database(db_b2)
        assert ls.run_spf("a")["b"].metric == 3
        # k-shortest paths see the two parallel links as disjoint
        paths = ls.get_kth_paths("a", "b", 1)
        more = ls.get_kth_paths("a", "b", 2)
        used = {link for p in paths for link in p}
        used2 = {link for p in more for link in p}
        assert used and used2 and not (used & used2)

    def test_route_db_parity_with_parallel_ring(self):
        """Triangle with doubled links: TPU backend == CPU oracle."""
        ls = LinkState("0")
        dbs = {}
        for x, y in (("a", "b"), ("b", "c"), ("a", "c")):
            db_x, db_y = parallel_pair(x, y, [1, 1])
            for db in (db_x, db_y):
                prev = dbs.get(db.this_node_name)
                if prev is None:
                    dbs[db.this_node_name] = db
                else:
                    dbs[db.this_node_name] = dataclasses.replace(
                        prev,
                        adjacencies=prev.adjacencies + db.adjacencies,
                    )
        for db in dbs.values():
            ls.update_adjacency_database(db)
        assert ls.num_links() == 6
        ps = PrefixState()
        for i, node in enumerate(sorted(dbs)):
            ps.update_prefix_database(
                PrefixDatabase(
                    node,
                    [PrefixEntry(IpPrefix(f"10.{i}.0.0/24"))],
                    area="0",
                )
            )
        cpu = SpfSolver("a").build_route_db("a", {"0": ls}, ps)
        tpu = TpuSpfSolver("a").build_route_db("a", {"0": ls}, ps)
        assert cpu == tpu
        # both parallel a-b links carry ECMP traffic toward b's loopback
        entry = cpu.unicast_entries[IpPrefix("10.1.0.0/24")]
        assert len(entry.nexthops) >= 2


class TestChurnReliability:
    @pytest.mark.parametrize("seed", [1, 7])
    def test_backends_agree_after_update_storm(self, seed):
        """Randomized adjacency churn: metric changes, node withdrawals,
        re-advertisements. After the storm, both backends must equal an
        oracle built from only the final state (no history leakage)."""
        rng = random.Random(seed)
        n = 12
        base = [
            (f"n{i}", f"n{j}", 1)
            for i in range(n)
            for j in range(i + 1, n)
            if rng.random() < 0.4 or j == i + 1
        ]
        ls = LinkState("0")
        current = build_adj_dbs(base)
        for db in current.values():
            ls.update_adjacency_database(db)

        cpu, tpu = SpfSolver("n0"), TpuSpfSolver("n0")
        ps = PrefixState()
        for i, node in enumerate(sorted(current)):
            ps.update_prefix_database(
                PrefixDatabase(
                    node,
                    [PrefixEntry(IpPrefix(f"10.{i}.0.0/24"))],
                    area="0",
                )
            )

        for step in range(30):
            op = rng.random()
            victim = rng.choice(sorted(current))
            if op < 0.3 and victim != "n0":
                # withdraw the node entirely
                ls.delete_adjacency_database(victim)
            elif op < 0.6:
                # re-advertise with perturbed metrics
                db = current[victim]
                db = dataclasses.replace(
                    db,
                    adjacencies=[
                        dataclasses.replace(
                            adj, metric=rng.randint(1, 9)
                        )
                        for adj in db.adjacencies
                    ],
                )
                current[victim] = db
                ls.update_adjacency_database(db)
            else:
                # restore the stored copy (covers re-add after withdraw)
                ls.update_adjacency_database(current[victim])
            # periodically force both backends through the changed state
            if step % 7 == 0:
                assert cpu.build_route_db("n0", {"0": ls}, ps) == (
                    tpu.build_route_db("n0", {"0": ls}, ps)
                )

        final_cpu = cpu.build_route_db("n0", {"0": ls}, ps)
        final_tpu = tpu.build_route_db("n0", {"0": ls}, ps)
        fresh = SpfSolver("n0").build_route_db("n0", {"0": ls}, ps)
        assert final_cpu == fresh  # incremental state == from-scratch
        assert final_tpu == fresh
