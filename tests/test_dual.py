"""DUAL tests, mirroring openr/dual/tests/DualTest.cpp: state machine
transitions (:123), ring/full-mesh/grid topologies with SPT validation,
link failures and cost changes, multi-root, non-graceful peer restart."""

import random
from typing import Dict, List, Optional, Set, Tuple

import pytest

from openr_tpu.dual import (
    Dual,
    DualMessages,
    DualNode,
    DualState,
    INF_DISTANCE,
)
from openr_tpu.dual.dual import DualEvent, DualStateMachine


class TestStateMachine:
    """Transition matrix (DualTest.cpp:123)."""

    def test_passive_stays_on_fc(self):
        sm = DualStateMachine()
        sm.process_event(DualEvent.OTHERS, fc=True)
        assert sm.state == DualState.PASSIVE

    def test_passive_to_active1(self):
        sm = DualStateMachine()
        sm.process_event(DualEvent.OTHERS, fc=False)
        assert sm.state == DualState.ACTIVE1

    def test_passive_to_active3_on_successor_query(self):
        sm = DualStateMachine()
        sm.process_event(DualEvent.QUERY_FROM_SUCCESSOR, fc=False)
        assert sm.state == DualState.ACTIVE3

    def test_active1_transitions(self):
        sm = DualStateMachine()
        sm.state = DualState.ACTIVE1
        sm.process_event(DualEvent.INCREASE_D)
        assert sm.state == DualState.ACTIVE0
        sm.state = DualState.ACTIVE1
        sm.process_event(DualEvent.LAST_REPLY)
        assert sm.state == DualState.PASSIVE
        sm.state = DualState.ACTIVE1
        sm.process_event(DualEvent.QUERY_FROM_SUCCESSOR)
        assert sm.state == DualState.ACTIVE2

    def test_active0_last_reply(self):
        sm = DualStateMachine()
        sm.state = DualState.ACTIVE0
        sm.process_event(DualEvent.LAST_REPLY, fc=True)
        assert sm.state == DualState.PASSIVE
        sm.state = DualState.ACTIVE0
        sm.process_event(DualEvent.LAST_REPLY, fc=False)
        assert sm.state == DualState.ACTIVE2

    def test_active2_and_3(self):
        sm = DualStateMachine()
        sm.state = DualState.ACTIVE2
        sm.process_event(DualEvent.LAST_REPLY, fc=False)
        assert sm.state == DualState.ACTIVE3
        sm.process_event(DualEvent.LAST_REPLY)
        assert sm.state == DualState.PASSIVE
        sm.state = DualState.ACTIVE3
        sm.process_event(DualEvent.INCREASE_D)
        assert sm.state == DualState.ACTIVE2


class _BusNode(DualNode):
    """DualNode over a synchronous in-memory bus (DualTest TestNode equiv)."""

    def __init__(self, bus: "Bus", node_id: str, is_root: bool) -> None:
        super().__init__(node_id, is_root)
        self.bus = bus
        self.nexthop_changes: List[Tuple[str, Optional[str], Optional[str]]] = []

    def send_dual_messages(self, neighbor: str, msgs: DualMessages) -> bool:
        if not self.neighbor_is_up(neighbor):
            return False
        self.bus.enqueue(neighbor, msgs)
        return True

    def process_nexthop_change(self, root_id, old_nh, new_nh) -> None:
        self.nexthop_changes.append((root_id, old_nh, new_nh))
        # maintain parent's child-set (KvStore does this via flood-topo
        # set/unset commands in the real wiring)
        dual = self.duals[root_id]
        if old_nh is not None and old_nh in self.bus.nodes:
            self.bus.nodes[old_nh].duals.get(root_id) and self.bus.nodes[
                old_nh
            ].duals[root_id].remove_child(self.node_id)
        if new_nh is not None and new_nh != self.node_id:
            self.bus.defer_child_add(root_id, new_nh, self.node_id)


class Bus:
    """FIFO message fabric: delivers queued DualMessages until quiescent."""

    def __init__(self) -> None:
        self.nodes: Dict[str, _BusNode] = {}
        self.queue: List[Tuple[str, DualMessages]] = []
        self.links: Set[frozenset] = set()
        self._child_adds: List[Tuple[str, str, str]] = []

    def add_node(self, name: str, is_root: bool = False) -> _BusNode:
        node = _BusNode(self, name, is_root)
        self.nodes[name] = node
        return node

    def enqueue(self, dst: str, msgs: DualMessages) -> None:
        self.queue.append((dst, msgs))

    def defer_child_add(self, root_id, parent, child) -> None:
        self._child_adds.append((root_id, parent, child))

    def connect(self, a: str, b: str, cost: int = 1) -> None:
        self.links.add(frozenset((a, b)))
        self.nodes[a].peer_up(b, cost)
        self.nodes[b].peer_up(a, cost)
        self.settle()

    def disconnect(self, a: str, b: str) -> None:
        self.links.discard(frozenset((a, b)))
        self.nodes[a].peer_down(b)
        self.nodes[b].peer_down(a)
        self.settle()

    def change_cost(self, a: str, b: str, cost: int) -> None:
        self.nodes[a].peer_cost_change(b, cost)
        self.nodes[b].peer_cost_change(a, cost)
        self.settle()

    def settle(self, max_steps: int = 100_000) -> None:
        steps = 0
        while self.queue:
            steps += 1
            assert steps < max_steps, "dual did not converge"
            dst, msgs = self.queue.pop(0)
            if frozenset((dst, msgs.src_id)) not in self.links:
                continue  # dropped on a dead link
            self.nodes[dst].process_dual_messages(msgs)
            self._apply_child_adds()
        self._apply_child_adds()

    def _apply_child_adds(self) -> None:
        while self._child_adds:
            root_id, parent, child = self._child_adds.pop(0)
            node = self.nodes.get(parent)
            if node is not None and root_id in node.duals:
                node.duals[root_id].add_child(child)

    # -- validation (DualTest.cpp checkSpt semantics) -------------------

    def check_spt(self, root_id: str, expect_distances: Dict[str, int]):
        for name, node in self.nodes.items():
            dual = node.duals.get(root_id)
            expected = expect_distances.get(name)
            if expected is None:
                assert dual is None or not dual.has_valid_route()
                continue
            assert dual is not None, f"{name} has no dual for {root_id}"
            assert dual.sm.state == DualState.PASSIVE, (
                f"{name} not passive: {dual.sm.state}"
            )
            assert dual.distance == expected, (
                f"{name}: d={dual.distance} expected {expected}"
            )
            if name != root_id:
                # parent is one hop closer through a live link
                parent = dual.nexthop
                assert parent is not None, name
                assert frozenset((name, parent)) in self.links
                parent_d = self.nodes[parent].duals[root_id].distance
                assert parent_d < dual.distance
                # loop-free: following parents reaches the root
                seen, cur = set(), name
                while cur != root_id:
                    assert cur not in seen, f"loop at {cur}"
                    seen.add(cur)
                    cur = self.nodes[cur].duals[root_id].nexthop


class TestRing:
    def test_three_ring_spt(self):
        bus = Bus()
        for name in ("r", "a", "b"):
            bus.add_node(name, is_root=(name == "r"))
        bus.connect("r", "a")
        bus.connect("r", "b")
        bus.connect("a", "b")
        bus.check_spt("r", {"r": 0, "a": 1, "b": 1})
        # spt peers of root include both children
        assert bus.nodes["a"].get_spt_peers("r") == {"r"}

    def test_link_failure_reroutes(self):
        bus = Bus()
        for name in ("r", "a", "b"):
            bus.add_node(name, is_root=(name == "r"))
        bus.connect("r", "a")
        bus.connect("r", "b")
        bus.connect("a", "b")
        bus.disconnect("r", "a")
        # a now reaches r via b
        bus.check_spt("r", {"r": 0, "b": 1, "a": 2})
        assert bus.nodes["a"].duals["r"].nexthop == "b"

    def test_cost_change_moves_traffic(self):
        bus = Bus()
        for name in ("r", "a", "b"):
            bus.add_node(name, is_root=(name == "r"))
        bus.connect("r", "a", cost=10)
        bus.connect("r", "b", cost=1)
        bus.connect("a", "b", cost=1)
        bus.check_spt("r", {"r": 0, "b": 1, "a": 2})
        # direct r-a link becomes cheap: a switches to direct
        bus.change_cost("r", "a", 1)
        bus.check_spt("r", {"r": 0, "b": 1, "a": 1})
        assert bus.nodes["a"].duals["r"].nexthop == "r"

    def test_larger_ring(self):
        n = 8
        bus = Bus()
        names = [f"n{i}" for i in range(n)]
        for name in names:
            bus.add_node(name, is_root=(name == "n0"))
        for i in range(n):
            bus.connect(names[i], names[(i + 1) % n])
        expected = {
            names[i]: min(i, n - i) for i in range(n)
        }
        bus.check_spt("n0", expected)


class TestFullMeshAndGrid:
    def test_full_mesh(self):
        bus = Bus()
        names = [f"m{i}" for i in range(5)]
        for name in names:
            bus.add_node(name, is_root=(name == "m0"))
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                bus.connect(a, b)
        bus.check_spt("m0", {names[0]: 0, **{n: 1 for n in names[1:]}})

    def test_grid(self):
        side = 3
        bus = Bus()
        for i in range(side):
            for j in range(side):
                bus.add_node(f"g{i}_{j}", is_root=(i == 0 and j == 0))
        for i in range(side):
            for j in range(side):
                if j + 1 < side:
                    bus.connect(f"g{i}_{j}", f"g{i}_{j+1}")
                if i + 1 < side:
                    bus.connect(f"g{i}_{j}", f"g{i+1}_{j}")
        expected = {
            f"g{i}_{j}": i + j for i in range(side) for j in range(side)
        }
        bus.check_spt("g0_0", expected)

    def test_random_failures_still_converge(self):
        rng = random.Random(7)
        side = 3
        bus = Bus()
        for i in range(side):
            for j in range(side):
                bus.add_node(f"g{i}_{j}", is_root=(i == 0 and j == 0))
        edges = []
        for i in range(side):
            for j in range(side):
                if j + 1 < side:
                    edges.append((f"g{i}_{j}", f"g{i}_{j+1}"))
                if i + 1 < side:
                    edges.append((f"g{i}_{j}", f"g{i+1}_{j}"))
        for a, b in edges:
            bus.connect(a, b)
        # fail a few non-partitioning links
        for a, b in rng.sample(edges, 3):
            remaining = [e for e in bus.links]
            bus.disconnect(a, b)
            if not _connected(bus):
                bus.connect(a, b)
        # recompute expected distances by BFS over live links
        expected = _bfs_distances(bus, "g0_0")
        bus.check_spt("g0_0", expected)


class TestMultiRoot:
    def test_smallest_valid_root_wins(self):
        bus = Bus()
        for name in ("a-root", "b-root", "x", "y"):
            bus.add_node(name, is_root=name.endswith("root"))
        bus.connect("a-root", "x")
        bus.connect("x", "y")
        bus.connect("y", "b-root")
        for node in bus.nodes.values():
            assert node.get_spt_root_id() == "a-root"
        # a-root dies entirely: everyone falls back to b-root
        bus.disconnect("a-root", "x")
        assert bus.nodes["x"].get_spt_root_id() == "b-root"
        assert bus.nodes["y"].get_spt_root_id() == "b-root"


def _connected(bus: Bus) -> bool:
    if not bus.nodes:
        return True
    adj: Dict[str, Set[str]] = {n: set() for n in bus.nodes}
    for link in bus.links:
        a, b = tuple(link)
        adj[a].add(b)
        adj[b].add(a)
    seen: Set[str] = set()
    stack = [next(iter(bus.nodes))]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(adj[cur] - seen)
    return len(seen) == len(bus.nodes)


def _bfs_distances(bus: Bus, root: str) -> Dict[str, int]:
    adj: Dict[str, Set[str]] = {n: set() for n in bus.nodes}
    for link in bus.links:
        a, b = tuple(link)
        adj[a].add(b)
        adj[b].add(a)
    dist = {root: 0}
    frontier = [root]
    while frontier:
        nxt = []
        for cur in frontier:
            for other in adj[cur]:
                if other not in dist:
                    dist[other] = dist[cur] + 1
                    nxt.append(other)
        frontier = nxt
    return dist
