"""Solver fault domain: supervised solves, circuit breaker + CPU fallback,
probe-driven recovery with hysteresis, and the warm-state shadow audit —
every degraded path driven by the deterministic fault injector
(openr_tpu/testing/faults.py), no real device errors required."""

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.monitor import Watchdog
from openr_tpu.solver import SolverSupervisor, SpfSolver, SupervisorConfig, TpuSpfSolver
from openr_tpu.solver.supervisor import (
    CLOSED,
    FAULT_COMPILE,
    FAULT_DEADLINE,
    FAULT_DEVICE_LOSS,
    FAULT_RUNTIME,
    HALF_OPEN,
    OPEN,
    SolveDeadlineExceeded,
    classify_solver_error,
)
from openr_tpu.testing.faults import FaultInjected, FaultInjector, injected
from openr_tpu.topology import build_adj_dbs, grid_edges
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry


class FakeClock:
    def __init__(self) -> None:
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def make_prefix_state(announcers, area="0"):
    ps = PrefixState()
    for node, pfxs in announcers.items():
        ps.update_prefix_database(
            PrefixDatabase(
                node, [PrefixEntry(IpPrefix(p)) for p in pfxs], area=area
            )
        )
    return ps


def assert_route_db_equal(db_a, db_b):
    assert db_a is not None and db_b is not None
    assert set(db_a.unicast_entries) == set(db_b.unicast_entries)
    for prefix, entry in db_a.unicast_entries.items():
        assert entry.nexthops == db_b.unicast_entries[prefix].nexthops, prefix
    assert set(db_a.mpls_entries) == set(db_b.mpls_entries)
    for label, entry in db_a.mpls_entries.items():
        assert entry.nexthops == db_b.mpls_entries[label].nexthops, label


def make_supervisor(me="g0_0", clock=None, watchdog=None, samples=None,
                    **cfg_kw):
    cfg = SupervisorConfig(**cfg_kw)
    return SolverSupervisor(
        TpuSpfSolver(me),
        SpfSolver(me),
        cfg,
        watchdog=watchdog,
        log_sample_fn=(samples.append if samples is not None else None),
        clock=clock or FakeClock(),
    )


EDGES = grid_edges(3)
ANNOUNCERS = {"g2_2": ["10.1.0.0/16"], "g0_2": ["10.2.0.0/16"]}


def solve_inputs():
    return "g0_0", {"0": build_ls(EDGES)}, make_prefix_state(ANNOUNCERS)


def oracle_db():
    me, states, ps = solve_inputs()
    return SpfSolver(me).build_route_db(me, states, ps)


class TestClassification:
    def test_deadline(self):
        assert classify_solver_error(SolveDeadlineExceeded("x")) == (
            FAULT_DEADLINE
        )

    def test_device_loss_by_message(self):
        assert classify_solver_error(
            RuntimeError("DEVICE_LOST: chip 3 went away")
        ) == FAULT_DEVICE_LOSS

    def test_compile_by_message_and_type(self):
        assert classify_solver_error(
            RuntimeError("XLA compile failed: out of registers")
        ) == FAULT_COMPILE
        assert classify_solver_error(TypeError("bad avals")) == FAULT_COMPILE

    def test_chained_cause_is_searched(self):
        try:
            try:
                raise RuntimeError("device is lost")
            except RuntimeError as inner:
                raise ValueError("wrapper") from inner
        except ValueError as exc:
            assert classify_solver_error(exc) == FAULT_DEVICE_LOSS

    def test_unknown_defaults_to_runtime(self):
        assert classify_solver_error(RuntimeError("boom")) == FAULT_RUNTIME
        assert classify_solver_error(FaultInjected("p")) == FAULT_RUNTIME


class TestSupervisedSolve:
    def test_clean_path_serves_primary(self):
        sup = make_supervisor()
        db = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db, oracle_db())
        assert sup.state == CLOSED
        assert sup.counters["decision.spf.fallback_active"] == 0
        assert "decision.spf.fallback_solves" not in sup.counters

    def test_retry_within_call_heals_transient_fault(self):
        sup = make_supervisor(failure_threshold=5, max_attempts=2)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=1)
            db = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db, oracle_db())
        assert sup.state == CLOSED
        assert sup.consecutive_failures == 0  # success reset the streak
        assert sup.counters["decision.spf.solver_retries"] == 1
        assert sup.counters["decision.spf.solver_failures"] == 1
        assert sup.counters["decision.spf.solver_failures.runtime"] == 1

    def test_exhausted_retries_serve_fallback_without_trip(self):
        sup = make_supervisor(failure_threshold=10, max_attempts=2)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)
            db = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db, oracle_db())
        assert sup.state == CLOSED  # below threshold: breaker still closed
        assert sup.counters["decision.spf.fallback_solves"] == 1

    def test_deadline_overrun_counts_but_serves_result(self):
        clock = FakeClock()
        watchdog = Watchdog()
        sup = make_supervisor(
            clock=clock,
            watchdog=watchdog,
            solve_deadline_s=0.0,  # every real solve overruns a 0s budget
            failure_threshold=10,
        )
        # make elapsed strictly positive under the fake clock
        def ticking():
            clock.advance(1.0)
            return clock.t

        sup._clock = ticking
        sup._probe_backoff._clock = ticking
        db = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db, oracle_db())  # slow-but-correct is served
        assert sup.counters["decision.spf.solver_failures.deadline"] == 1
        assert watchdog.slow_sections.get("decision") == 1
        assert sup.state == CLOSED


class TestCircuitBreaker:
    def test_persistent_failure_trips_to_cpu_fallback_and_probe_recovers(
        self,
    ):
        """Acceptance: injected persistent TPU failure → oracle-identical
        routes via CPU fallback, fallback_active reads 1; a successful
        probe streak restores the TPU path (reads 0)."""
        clock = FakeClock()
        samples = []
        sup = make_supervisor(
            clock=clock,
            samples=samples,
            failure_threshold=2,
            max_attempts=1,
            probe_interval_s=5.0,
            probe_successes_to_close=2,
        )
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)  # persistent device fault
            db1 = sup.build_route_db(*solve_inputs())  # failure 1
            assert sup.state == CLOSED
            db2 = sup.build_route_db(*solve_inputs())  # failure 2 → trip
            assert sup.state == OPEN
            db3 = sup.build_route_db(*solve_inputs())  # served while open
        for db in (db1, db2, db3):
            assert_route_db_equal(db, oracle_db())
        assert sup.counters["decision.spf.fallback_active"] == 1
        assert sup.counters["decision.spf.breaker_trips"] == 1
        assert sup.counters["decision.spf.solver_failures"] == 2
        assert sup.health()["degraded"] is True
        assert any(
            s.get("event") == "SOLVER_BREAKER_TRIPPED" for s in samples
        )
        # the warm state was invalidated on trip
        assert sup.primary.counters[
            "decision.spf.warm_state_invalidations"
        ] >= 1

        # device healed (no injector): probes with hysteresis restore it
        clock.advance(5.0)
        assert sup.maybe_probe()
        assert sup.state == HALF_OPEN  # 1 of 2 successes: still degraded
        assert sup.health()["degraded"] is True
        clock.advance(5.0)
        assert sup.maybe_probe()
        assert sup.state == CLOSED
        assert sup.counters["decision.spf.fallback_active"] == 0
        assert sup.health()["degraded"] is False
        assert sup.counters["decision.spf.probe_successes"] == 2
        assert any(
            s.get("event") == "SOLVER_BREAKER_CLOSED" for s in samples
        )
        # and the primary serves again, identically
        db4 = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db4, oracle_db())
        # db1 (retry exhausted), db2 (trip), db3 (open) — and no more
        # after the breaker closed
        assert sup.counters["decision.spf.fallback_solves"] == 3

    def test_probe_failure_resets_streak_and_backs_off(self):
        clock = FakeClock()
        sup = make_supervisor(
            clock=clock,
            failure_threshold=1,
            max_attempts=1,
            probe_interval_s=5.0,
            probe_successes_to_close=2,
        )
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)
            sup.build_route_db(*solve_inputs())
            assert sup.state == OPEN
            clock.advance(5.0)
            assert sup.maybe_probe()  # probe fails too
            assert sup.state == OPEN
            assert sup.probe_streak == 0
            assert sup.counters["decision.spf.probe_failures"] == 1
            # backoff gates the next probe: not due immediately
            clock.advance(1.0)
            assert not sup.probe_due()
        # flapping device: one success then a failure never closes
        clock.advance(60.0)
        assert sup.maybe_probe()
        assert sup.state == HALF_OPEN
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)
            clock.advance(5.0)
            assert sup.maybe_probe()
            assert sup.state == OPEN
            assert sup.probe_streak == 0

    def test_opportunistic_probe_from_solve_path(self):
        # loop-less embeddings recover without the background task: the
        # solve path itself runs due probes
        clock = FakeClock()
        sup = make_supervisor(
            clock=clock,
            failure_threshold=1,
            max_attempts=1,
            probe_interval_s=5.0,
            probe_successes_to_close=1,
        )
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=1)
            sup.build_route_db(*solve_inputs())
        assert sup.state == OPEN
        clock.advance(5.0)
        db = sup.build_route_db(*solve_inputs())  # probe runs, closes, but
        assert sup.state == CLOSED  # this event was already queued to
        assert_route_db_equal(db, oracle_db())  # whichever path served it

    def test_static_routes_flow_through_both_backends(self):
        from openr_tpu.types import NextHop

        sup = make_supervisor(failure_threshold=1, max_attempts=1)
        nh = NextHop(address="fe80::1", iface="lo")
        sup.push_static_routes_delta({100: {nh}}, set())
        delta = sup.process_static_route_updates()
        assert delta is not None and delta.mpls_routes_to_update
        # fallback ingested the same static state in lockstep
        assert sup.fallback.static_mpls_routes == (
            sup.primary.static_mpls_routes
        )


def _device_lost(point):
    return RuntimeError(f"device is lost at {point}")


class TestPartialMeshDegradation:
    """The degradation ladder (docs/Robustness.md): device-loss streaks
    shrink the solver mesh over surviving chips; the CPU oracle is the
    LAST rung, reached only when no viable mesh remains."""

    def make_meshed_supervisor(self, mesh, samples=None, **cfg_kw):
        # threshold 1: every failed build reaches a ladder/trip decision
        cfg_kw.setdefault("failure_threshold", 1)
        cfg_kw.setdefault("max_attempts", 1)
        return SolverSupervisor(
            TpuSpfSolver("g0_0", mesh=mesh),
            SpfSolver("g0_0"),
            SupervisorConfig(**cfg_kw),
            log_sample_fn=(samples.append if samples is not None else None),
            clock=FakeClock(),
        )

    def test_device_loss_degrades_mesh_instead_of_tripping(self):
        samples = []
        sup = self.make_meshed_supervisor((2, 2), samples=samples)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=1, exc=_device_lost)
            db = sup.build_route_db(*solve_inputs())  # fails -> takes a rung
        assert_route_db_equal(db, oracle_db())  # this event served degraded
        assert sup.state == CLOSED  # breaker never opened
        assert sup.consecutive_failures == 0  # streak reset by the rung
        assert sup.counters["decision.spf.mesh_degradations"] == 1
        assert sup.counters["decision.spf.mesh_devices"] == 2
        assert dict(sup.primary.mesh.shape) == {"batch": 1, "graph": 2}
        assert "decision.spf.breaker_trips" not in sup.counters
        assert any(
            s.get("event") == "SOLVER_MESH_DEGRADED" for s in samples
        )
        # the primary serves the next event on the smaller mesh
        db2 = sup.build_route_db(*solve_inputs())
        assert_route_db_equal(db2, oracle_db())
        assert sup.counters.get("decision.spf.fallback_solves", 0) == 1

    def test_ladder_walks_to_cpu_when_no_mesh_remains(self):
        """Persistent device loss: (1, 2) -> (1, 1) -> no rung below a
        single device -> the breaker finally trips to the oracle."""
        sup = self.make_meshed_supervisor((1, 2))
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None, exc=_device_lost)
            db = sup.build_route_db(*solve_inputs())  # rung: (1, 1)
            assert sup.state == CLOSED
            assert dict(sup.primary.mesh.shape) == {"batch": 1, "graph": 1}
            db = sup.build_route_db(*solve_inputs())  # no rung left: trip
            assert sup.state == OPEN
            db = sup.build_route_db(*solve_inputs())  # served while open
        assert_route_db_equal(db, oracle_db())
        assert sup.counters["decision.spf.mesh_degradations"] == 1
        assert sup.counters["decision.spf.breaker_trips"] == 1
        assert sup.health()["mesh_degradations"] == 1
        assert sup.health()["solver_mesh"] == {"batch": 1, "graph": 1}

    def test_non_device_loss_faults_skip_the_ladder(self):
        """A compile/runtime streak trips straight to the oracle — a
        smaller mesh cannot heal a lowering bug."""
        sup = self.make_meshed_supervisor((2, 2))
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)  # runtime kind
            sup.build_route_db(*solve_inputs())
        assert sup.state == OPEN
        assert "decision.spf.mesh_degradations" not in sup.counters
        assert dict(sup.primary.mesh.shape) == {"batch": 2, "graph": 2}

    def test_knob_disables_the_ladder(self):
        sup = self.make_meshed_supervisor((2, 2), mesh_degrade=False)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None, exc=_device_lost)
            sup.build_route_db(*solve_inputs())
        assert sup.state == OPEN
        assert "decision.spf.mesh_degradations" not in sup.counters

    def test_meshless_primary_trips_as_before(self):
        sup = make_supervisor(failure_threshold=1, max_attempts=1)
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None, exc=_device_lost)
            sup.build_route_db(*solve_inputs())
        assert sup.state == OPEN
        assert sup.health()["solver_mesh"] is None


class TestWarmStateAudit:
    def _corrupt(self, solve):
        """Perturb one warm D entry (device + host mirror) — the injected
        warm-state corruption of the acceptance criteria."""
        import jax.numpy as jnp

        d = np.array(solve.d)
        d[0, d.shape[1] // 2] += 3
        solve._d_host = d
        solve._d_dev = jnp.asarray(d)

    def test_corruption_caught_within_n_events_and_healed(self):
        """Acceptance: a perturbed D entry is caught by the shadow audit
        within N events, increments decision.spf.audit_mismatches, and the
        forced cold re-solve restores oracle-identical routes."""
        samples = []
        sup = make_supervisor(samples=samples, audit_interval=2)
        me, states, ps = solve_inputs()
        ls = states["0"]

        db = sup.build_route_db(me, states, ps)  # event 1: no audit yet
        assert sup.counters.get("decision.spf.audit_runs", 0) == 0

        with injected() as inj:
            inj.arm("solver.tpu.warm_d", action=self._corrupt, times=1)
            # event 2: the warm solve lands corrupted, the every-2nd-event
            # audit catches it in the same rebuild and self-heals
            import dataclasses

            dbs = build_adj_dbs(EDGES)
            db_b = dbs["g1_1"]
            db_b = dataclasses.replace(
                db_b,
                adjacencies=[
                    dataclasses.replace(adj, metric=4)
                    for adj in db_b.adjacencies
                ],
            )
            ls.update_adjacency_database(db_b)
            db2 = sup.build_route_db(me, states, ps)

        assert sup.counters["decision.spf.audit_runs"] == 1
        assert sup.counters["decision.spf.audit_mismatches"] >= 1
        assert sup.counters["decision.spf.audit_forced_cold_solves"] == 1
        assert any(
            s.get("event") == "WARM_STATE_AUDIT_MISMATCH" for s in samples
        )
        # the re-served routes are oracle-identical despite the corruption
        oracle = SpfSolver(me).build_route_db(me, states, ps)
        assert_route_db_equal(db2, oracle)
        # and the next solve's warm state is clean again
        db3 = sup.build_route_db(me, states, ps)
        assert_route_db_equal(db3, oracle)
        assert sup.counters["decision.spf.audit_mismatches"] >= 1

    def test_clean_audit_reports_nothing(self):
        sup = make_supervisor(audit_interval=1)
        for _ in range(3):
            sup.build_route_db(*solve_inputs())
        assert sup.counters["decision.spf.audit_runs"] == 3
        assert "decision.spf.audit_mismatches" not in sup.counters

    def test_audit_direct_on_solver(self):
        # the TpuSpfSolver-level audit API: detects a direct perturbation
        tpu = TpuSpfSolver("g0_0")
        me, states, ps = solve_inputs()
        tpu.build_route_db(me, states, ps)
        assert tpu.audit_warm_state() == []
        (_, solve), = tpu._solves.values()
        self._corrupt(solve)
        (record,) = tpu.audit_warm_state()
        assert record["entries"] == 1
        assert record["max_abs_delta"] == 3
        tpu.invalidate_warm_state()
        assert tpu._solves == {}
        assert tpu.counters["decision.spf.warm_state_invalidations"] == 1


class TestDecisionIntegration:
    def test_decision_tpu_backend_is_supervised_by_default(self):
        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue

        decision = Decision(
            DecisionConfig(my_node_name="a", solver_backend="tpu"),
            RQueue(RWQueue()),
            ReplicateQueue(),
        )
        assert isinstance(decision.solver, SolverSupervisor)
        health = decision.get_solver_health()
        assert health["degraded"] is False
        assert health["breaker_state"] == CLOSED

    def test_decision_cpu_backend_reports_unsupervised(self):
        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue

        decision = Decision(
            DecisionConfig(my_node_name="a", solver_backend="cpu"),
            RQueue(RWQueue()),
            ReplicateQueue(),
        )
        health = decision.get_solver_health()
        assert health["degraded"] is False
        assert health["breaker_state"] == "unsupervised"

    def test_supervisor_counters_reach_decision_counters(self):
        import asyncio

        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue
        from openr_tpu.testing.decision_harness import lsdb_publication

        async def body():
            kv_q = RWQueue()
            decision = Decision(
                DecisionConfig(
                    my_node_name="g0_0",
                    solver_backend="tpu",
                    solver_failure_threshold=1,
                    solver_max_attempts=1,
                    debounce_min=0.005,
                    debounce_max=0.02,
                ),
                RQueue(kv_q),
                ReplicateQueue(),
            )
            decision.start()
            try:
                with injected() as inj:
                    inj.arm("solver.tpu.solve", times=1)
                    kv_q.push(
                        lsdb_publication(
                            build_adj_dbs(EDGES).values(), ANNOUNCERS
                        )
                    )
                    deadline = asyncio.get_event_loop().time() + 10.0
                    while not decision.have_computed_routes:
                        assert (
                            asyncio.get_event_loop().time() < deadline
                        ), "no routes"
                        await asyncio.sleep(0.005)
            finally:
                task = decision._task
                decision.stop()
                if task is not None:
                    await asyncio.gather(task, return_exceptions=True)
            # the degraded flag is visible through Decision's counter sync
            assert decision.counters["decision.spf.fallback_active"] == 1
            assert decision.counters["decision.spf.solver_failures"] == 1
            assert decision.get_solver_health()["degraded"] is True

        asyncio.new_event_loop().run_until_complete(body())
