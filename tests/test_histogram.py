"""Histogram/Timer primitive tests (utils/counters.py): log-bucket math at
boundary values, empty-histogram behavior, cross-module merge, and the
HistogramsMixin/Timer recording path."""

import math

from openr_tpu.utils.counters import (
    _LO,
    _NBUCKETS,
    _SUB,
    Histogram,
    HistogramsMixin,
)


class TestBucketMath:
    def test_zero_and_tiny_values_land_in_bucket_zero(self):
        assert Histogram.bucket_index(0.0) == 0
        assert Histogram.bucket_index(_LO / 2) == 0
        assert Histogram.bucket_index(_LO * 0.999) == 0

    def test_lower_edge_is_inclusive(self):
        # bucket i's lower edge belongs to bucket i ([lo, hi) semantics)
        for i in (1, 2, 5, _SUB, 3 * _SUB + 1):
            lo, hi = Histogram.bucket_bounds(i)
            assert Histogram.bucket_index(lo) == i, i
            # clearly below the upper edge stays in bucket i
            assert Histogram.bucket_index(hi * (1 - 1e-6)) == i, i
            # the upper edge itself opens the next bucket
            assert Histogram.bucket_index(hi) == i + 1, i

    def test_index_monotonic_over_geometric_sweep(self):
        prev = -1
        v = _LO / 4
        while v < 1e9:
            idx = Histogram.bucket_index(v)
            assert 0 <= idx < _NBUCKETS
            assert idx >= prev, v
            prev = idx
            v *= 1.31

    def test_huge_values_clamp_to_last_bucket(self):
        assert Histogram.bucket_index(1e300) == _NBUCKETS - 1
        h = Histogram()
        h.record(1e300)
        assert h.count == 1 and h.max == 1e300

    def test_bounds_tile_the_axis(self):
        for i in range(1, _NBUCKETS - 1):
            lo, hi = Histogram.bucket_bounds(i)
            lo2, _ = Histogram.bucket_bounds(i + 1)
            assert math.isclose(hi, lo2)
            assert math.isclose(hi / lo, 2 ** (1 / _SUB))


class TestHistogram:
    def test_empty(self):
        h = Histogram()
        assert h.count == 0
        assert h.sum == 0.0
        assert h.percentile(50) == 0.0
        d = h.to_dict()
        assert d["count"] == 0 and d["p99"] == 0.0 and d["max"] == 0.0

    def test_single_sample_percentiles_are_exact(self):
        h = Histogram()
        h.record(5.0)
        for p in (0, 50, 95, 99, 100):
            assert h.percentile(p) == 5.0
        assert h.min == h.max == 5.0
        assert h.avg == 5.0

    def test_negative_and_nan_clamp_to_zero(self):
        h = Histogram()
        h.record(-3.0)
        h.record(float("nan"))
        assert h.count == 2
        assert h.sum == 0.0 and h.max == 0.0

    def test_percentiles_bounded_by_bucket_error(self):
        # log buckets guarantee <= 2**(1/_SUB)-1 relative error
        h = Histogram()
        values = [0.1 * 1.13 ** i for i in range(150)]
        for v in values:
            h.record(v)
        values.sort()
        for p in (50, 95, 99):
            true = values[min(len(values) - 1, int(p / 100 * len(values)))]
            got = h.percentile(p)
            assert got <= true * 2 ** (1 / _SUB) * 1.01
            assert got >= true / (2 ** (1 / _SUB) * 1.01)
        assert h.percentile(100) == max(values)

    def test_merge_equals_recording_into_one(self):
        a, b, both = Histogram(), Histogram(), Histogram()
        for i, v in enumerate(x * 0.37 for x in range(1, 50)):
            (a if i % 2 else b).record(v)
            both.record(v)
        merged = a.copy().merge(b)
        assert merged.buckets == both.buckets
        assert merged.count == both.count
        assert math.isclose(merged.sum, both.sum)
        assert merged.min == both.min and merged.max == both.max
        assert merged.percentile(95) == both.percentile(95)
        # merge never mutates its argument, copy never aliases
        assert a.count + b.count == merged.count
        a.record(1.0)
        assert merged.count == both.count

    def test_merge_with_empty(self):
        a = Histogram()
        a.record(2.0)
        assert a.copy().merge(Histogram()).to_dict() == a.to_dict()
        assert Histogram().merge(a).to_dict() == a.to_dict()


class TestHistogramsMixin:
    class _Mod(HistogramsMixin):
        pass

    def test_observe_creates_and_records(self):
        m = self._Mod()
        m._observe("decision.debounce_ms", 1.5)
        m._observe("decision.debounce_ms", 2.5)
        h = m.histograms["decision.debounce_ms"]
        assert h.count == 2 and h.sum == 4.0

    def test_timer_records_elapsed_ms(self):
        m = self._Mod()
        with m._timer("fib.program_ms"):
            sum(range(1000))
        h = m.histograms["fib.program_ms"]
        assert h.count == 1
        assert 0.0 <= h.max < 10_000.0


class TestResetOnRead:
    def test_reset_clears_all_state(self):
        from openr_tpu.utils.counters import Histogram

        h = Histogram()
        for v in (0.5, 2.0, 300.0):
            h.record(v)
        h.reset()
        assert h.count == 0
        assert h.sum == 0.0
        assert h.min is None and h.max is None
        assert all(b == 0 for b in h.buckets)
        # and it keeps recording normally afterwards
        h.record(7.0)
        assert h.count == 1 and h.min == 7.0

    def test_monitor_reset_on_read_windows(self):
        from openr_tpu.monitor import Monitor
        from openr_tpu.utils.counters import Histogram

        monitor = Monitor("n")

        class Mod:
            histograms = {}

        hist = Histogram()
        hist.record(1.0)
        hist.record(2.0)
        Mod.histograms = {"decision.debounce_ms": hist}
        monitor.register_module("decision", Mod())

        window1 = monitor.get_histograms(reset=True)
        assert window1["decision.debounce_ms"]["count"] == 2
        hist.record(9.0)
        window2 = monitor.get_histograms(reset=True)
        # only the post-reset sample: consecutive exports are disjoint
        assert window2["decision.debounce_ms"]["count"] == 1
        assert window2["decision.debounce_ms"]["min"] == 9.0
        # plain reads never reset
        assert monitor.get_histograms()["decision.debounce_ms"]["count"] == 0

    def test_shared_histogram_object_merged_and_reset_once(self):
        """Decision re-exports the solver's histograms by reference; the
        merge must neither double-count nor double-clear them."""
        from openr_tpu.monitor import merge_module_histograms
        from openr_tpu.utils.counters import Histogram

        shared = Histogram()
        shared.record(3.0)

        class A:
            histograms = {"decision.spf.solve_ms": shared}

        class B:
            histograms = {"decision.spf.solve_ms": shared}

        merged = merge_module_histograms([A(), B()], reset=True)
        assert merged["decision.spf.solve_ms"].count == 1  # not 2
        assert shared.count == 0
