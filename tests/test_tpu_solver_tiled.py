"""Destination-tiled P('batch', 'graph') solver differentials.

The 2-D layout (docs/Decision.md "Distance layout and halo exchange")
replaces the per-chip [S, n_pad] distance replica with a
[S/batch, n_pad/graph] tile and halo-exchanges per-partition frontier
minima between relaxation rounds. Every solve it produces must be
bit-identical to BOTH the replicated single-device path and the CPU
Dijkstra oracle — cold, warm (increase and decrease), overload toggles,
and partition flaps, on grid/Clos/WAN topologies over the virtual
8-device CPU mesh (conftest.py).

Resharding contract: warm state is never re-tiled across mesh shapes —
a mesh change (the partial-mesh degradation ladder) drops every cached
solve and the next event cold-starts, pinned here so it can never be
silently wrong.
"""

import random

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.ops.graph import INF
from openr_tpu.parallel import (
    plan_degraded_mesh,
    resolve_mesh,
    shrink_candidates,
    surviving_devices,
    tile_graph,
)
from openr_tpu.solver import SpfSolver, TpuSpfSolver
from openr_tpu.solver.tpu import _AreaSolve
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges, wan_edges

from test_tpu_solver import (
    apply_random_event,
    assert_solve_matches_oracle,
)
from test_tpu_solver_mesh import (
    assert_route_db_equal,
    build_ls,
    make_prefix_state,
    run_parity,
)

# graph axis > 1 on every shape: these meshes exercise the tiled layout
TILED_MESHES = [(2, 4), (2, 2), (1, 2)]

PFXS = ["10.1.0.0/16", "10.2.0.0/16"]


def run_tiled_differential(edges, me, seed, n_events, mesh_shape):
    """Randomized event sequence: after every event the warm tiled solve
    must be bit-identical to a fresh cold tiled solve, to a fresh
    replicated (mesh=None) solve, AND to the CPU oracle. Returns the warm
    _AreaSolve for counter assertions."""
    mesh = resolve_mesh(mesh_shape)
    rng = random.Random(seed)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    warm = _AreaSolve(ls, me, mesh=mesh)
    assert warm._dev is not None and warm._dev.get("kind") == "tile2d"
    links = list(edges)
    applied = 0
    for _ in range(n_events):
        before = ls.version
        apply_random_event(rng, dbs, ls, links)
        if ls.version == before:
            continue
        warm.refresh()
        cold_tiled = _AreaSolve(ls, me, mesh=mesh)
        cold_repl = _AreaSolve(ls, me, mesh=None)
        np.testing.assert_array_equal(warm.d, cold_tiled.d)
        np.testing.assert_array_equal(warm.d, cold_repl.d)
        assert_solve_matches_oracle(ls, warm)
        applied += 1
    assert applied > 0
    return warm


class TestTiledDifferential:
    """Sharded-vs-replicated-vs-oracle on randomized event sequences
    (metric increase/decrease, link flap, node-overload toggle)."""

    @pytest.mark.parametrize("mesh", TILED_MESHES)
    def test_grid_random_sequences(self, mesh):
        warm = run_tiled_differential(grid_edges(4), "g0_0", 23, 10, mesh)
        assert warm.incremental_solves > 0
        # the tile really is sharded over every mesh device
        assert len(warm._d_dev.sharding.device_set) == mesh[0] * mesh[1]

    def test_clos_random_sequence(self):
        edges = fabric_edges(
            pods=2, planes=2, ssw_per_plane=2, fsw_per_pod=2, rsw_per_pod=3
        )
        warm = run_tiled_differential(edges, "rsw0_0", 5, 8, (2, 4))
        assert warm.incremental_solves > 0

    def test_wan_random_sequence(self):
        warm = run_tiled_differential(wan_edges(24, seed=2), "w0", 9, 8, (2, 2))
        assert warm.incremental_solves > 0

    def test_overload_toggle_rides_warm_path(self):
        """A node-overload toggle must warm-start on the tiled layout
        (newly-overloaded out-edges seed the halo-aware invalidation) and
        still match both comparators."""
        import dataclasses

        mesh = resolve_mesh((2, 2))
        edges = grid_edges(4)
        dbs = build_adj_dbs(edges)
        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        warm = _AreaSolve(ls, "g0_0", mesh=mesh)
        for node in ("g1_1", "g2_2", "g1_1"):  # on, on, off again
            dbs[node] = dataclasses.replace(
                dbs[node], is_overloaded=not dbs[node].is_overloaded
            )
            ls.update_adjacency_database(dbs[node])
            warm.refresh()
            np.testing.assert_array_equal(
                warm.d, _AreaSolve(ls, "g0_0", mesh=None).d
            )
            assert_solve_matches_oracle(ls, warm)
        assert warm.incremental_solves == 3  # every toggle stayed warm

    def test_partition_flap(self):
        """Cut the single bridge between two grid islands (partition), then
        heal it: unreachable columns must read INF on the tiled layout and
        recover, bit-identical to the replicated path throughout."""
        import dataclasses

        mesh = resolve_mesh((2, 4))
        edges = [
            (f"a{i}_{j}", n, 1)
            for i in range(3)
            for j in range(3)
            for n in ([f"a{i+1}_{j}"] if i < 2 else [])
            + ([f"a{i}_{j+1}"] if j < 2 else [])
        ]
        edges += [
            (f"b{i}_{j}", n, 1)
            for i in range(3)
            for j in range(3)
            for n in ([f"b{i+1}_{j}"] if i < 2 else [])
            + ([f"b{i}_{j+1}"] if j < 2 else [])
        ]
        edges.append(("a2_2", "b0_0", 3))  # the bridge
        dbs = build_adj_dbs(edges)
        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        warm = _AreaSolve(ls, "a0_0", mesh=mesh)
        assert int(warm.d[0, warm.graph.node_index["b2_2"]]) < INF

        def set_bridge(down: bool):
            db = dbs["a2_2"]
            db = dataclasses.replace(
                db,
                adjacencies=[
                    dataclasses.replace(adj, is_overloaded=down)
                    if adj.other_node_name == "b0_0"
                    else adj
                    for adj in db.adjacencies
                ],
            )
            dbs["a2_2"] = db
            ls.update_adjacency_database(db)

        set_bridge(True)
        warm.refresh()
        np.testing.assert_array_equal(
            warm.d, _AreaSolve(ls, "a0_0", mesh=None).d
        )
        assert int(warm.d[0, warm.graph.node_index["b2_2"]]) >= INF
        assert_solve_matches_oracle(ls, warm)
        set_bridge(False)
        warm.refresh()
        np.testing.assert_array_equal(
            warm.d, _AreaSolve(ls, "a0_0", mesh=None).d
        )
        assert int(warm.d[0, warm.graph.node_index["b2_2"]]) < INF
        assert_solve_matches_oracle(ls, warm)


class TestTiledRouteDbParity:
    """Full route-pipeline parity through TpuSpfSolver on tiled meshes —
    the same contract as tests/test_tpu_solver_mesh.py, with the graph
    axis doing the destination sharding."""

    def test_grid_routes(self):
        run_parity(
            grid_edges(5),
            {"g4_4": [PFXS[0]], "g0_4": [PFXS[1]]},
            "g0_0",
            (2, 4),
        )

    def test_random_graphs(self):
        rng = random.Random(31)
        for _ in range(4):
            n = rng.randint(6, 13)
            nodes = [f"n{i}" for i in range(n)]
            edges = []
            for i in range(1, n):
                edges.append(
                    (nodes[rng.randrange(i)], nodes[i], rng.randint(1, 5))
                )
            for _ in range(rng.randint(1, n)):
                a, b = rng.sample(nodes, 2)
                if not any({a, b} == {x, y} for x, y, _ in edges):
                    edges.append((a, b, rng.randint(1, 5)))
            overloaded = {
                nodes[i] for i in range(1, n) if rng.random() < 0.15
            }
            run_parity(
                edges,
                {nodes[i]: [PFXS[i % 2]] for i in range(1, n) if i % 2},
                nodes[0],
                (2, 4),
                overloaded=overloaded,
            )


class TestHaloAccounting:
    def test_halo_counters_flow(self):
        """Tiled solves must account their ring traffic: exchanges gauge
        and cumulative bytes, surfaced as decision.spf.halo_* through the
        solver counter sync."""
        import dataclasses

        edges = grid_edges(4)
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = make_prefix_state({"g3_3": [PFXS[0]]})
        tpu = TpuSpfSolver("g0_0", mesh=(2, 2))
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert tpu.counters["decision.spf.halo_exchanges_last"] > 0
        cold_bytes = tpu.counters["decision.spf.halo_bytes"]
        assert cold_bytes > 0
        # a warm flap event pays the seed exchange + its (fewer) rounds
        db = dataclasses.replace(
            dbs["g1_0"],
            adjacencies=[
                dataclasses.replace(adj, metric=7)
                if adj.other_node_name == "g1_1"
                else adj
                for adj in dbs["g1_0"].adjacencies
            ],
        )
        ls.update_adjacency_database(db)
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert tpu.counters["decision.spf.incremental_solves"] == 1
        assert tpu.counters["decision.spf.halo_bytes"] > cold_bytes

    def test_tile_memory_is_fraction_of_replica(self):
        """The point of the layout: the per-device distance tile holds
        n_pad/graph columns, not the full destination axis."""
        import jax

        from openr_tpu.ops import compile_graph

        ls = build_ls(grid_edges(6))
        g = compile_graph(ls)
        mesh = resolve_mesh((2, 4))
        solve = _AreaSolve(ls, "g0_0", mesh=mesh)
        shards = {
            s.device: s.data.shape for s in solve._d_dev.addressable_shards
        }
        assert len(shards) == 8
        s_pad, n_pad = solve._d_dev.shape
        for shape in shards.values():
            assert shape == (s_pad // 2, n_pad // 4)


class TestResharding:
    def test_degrade_mesh_cold_starts_never_silently_wrong(self):
        """Mesh degradation mid-flight: warm state is dropped (tile
        ownership is a function of the factorization), the next event
        cold-starts on the smaller mesh, and routes still match a fresh
        CPU oracle — re-tiled-or-cold, never silently wrong."""
        import dataclasses

        edges = grid_edges(4)
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        ps = make_prefix_state({"g3_3": [PFXS[0]]})
        tpu = TpuSpfSolver("g0_0", mesh=(2, 4))
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert len(tpu._solves) == 1
        assert tpu.degrade_mesh() is True
        assert tpu.counters["decision.spf.mesh_degradations"] == 1
        assert tpu.counters["decision.spf.mesh_devices"] == 4
        # the ladder prefers keeping the graph axis (the memory win)
        assert (tpu.mesh.shape["batch"], tpu.mesh.shape["graph"]) == (1, 4)
        assert not tpu._solves  # warm state dropped, not re-tiled
        full_before = tpu.counters.get("decision.spf.full_solves", 0)
        db = dataclasses.replace(
            dbs["g1_0"],
            adjacencies=[
                dataclasses.replace(adj, metric=5)
                if adj.other_node_name == "g1_1"
                else adj
                for adj in dbs["g1_0"].adjacencies
            ],
        )
        ls.update_adjacency_database(db)
        db_tpu = tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert tpu.counters["decision.spf.full_solves"] > full_before
        assert tpu.counters.get("decision.spf.incremental_solves", 0) == 0
        ls_cpu = LinkState("0")
        for name in sorted(dbs):
            src = db if name == "g1_0" else dbs[name]
            ls_cpu.update_adjacency_database(src)
        assert_route_db_equal(
            SpfSolver("g0_0").build_route_db("g0_0", {"0": ls_cpu}, ps),
            db_tpu,
        )

    def test_ladder_shapes(self):
        assert shrink_candidates((4, 2)) == [(2, 2), (1, 2), (1, 1)]
        assert shrink_candidates((2, 4)) == [(1, 4), (1, 2), (1, 1)]
        assert shrink_candidates((1, 1)) == []

    def test_plan_degraded_mesh_bottoms_out(self):
        mesh = resolve_mesh((1, 2))
        smaller = plan_degraded_mesh(mesh)
        assert smaller is not None
        assert dict(smaller.shape) == {"batch": 1, "graph": 1}
        assert plan_degraded_mesh(smaller) is None  # no rung below 1 device

    def test_surviving_devices_all_alive_on_cpu_mesh(self):
        import jax

        devices = jax.devices()[:4]
        assert surviving_devices(devices) == list(devices)


class TestTiledDeltaPath:
    def test_qualifying_flap_yields_device_delta(self):
        """A warm weight event not incident to me must produce a device
        delta on the tiled layout (col_changed sharded P('graph'), host
        reads one popcount) exactly like the replicated layouts."""
        import dataclasses

        # a line: the flapped link is a bottleneck, so distances beyond it
        # must actually move (grids absorb single-edge changes into ECMP)
        edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1), ("d", "e", 1)]
        dbs = build_adj_dbs(edges)
        ls = build_ls(edges)
        tpu = TpuSpfSolver("a", mesh=(2, 2))
        solve = tpu._area_solve(ls, "a")
        assert solve.take_route_delta() is None  # cold solve poisons
        db = dataclasses.replace(
            dbs["c"],
            adjacencies=[
                dataclasses.replace(adj, metric=9)
                if adj.other_node_name == "d"
                else adj
                for adj in dbs["c"].adjacencies
            ],
        )
        ls.update_adjacency_database(db)
        solve = tpu._area_solve(ls, "a")
        cols = solve.take_route_delta()
        assert cols is not None
        names = {solve.graph.names[c] for c in cols}
        assert names == {"d", "e"}  # exactly the columns past the flap
        assert solve.delta_extracts == 1
        assert solve.delta_bytes > 0
        # the patched host mirror equals a cold fetch
        np.testing.assert_array_equal(
            solve.d, _AreaSolve(ls, "a", mesh=None).d
        )
