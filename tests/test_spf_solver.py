"""SpfSolver route-computation tests, mirroring the core scenarios of
openr/decision/tests/DecisionTest.cpp (ShortestPathTest :364, AdjacencyUpdate
:491, BGP metric vectors :673, ConnectivityTest/overload :1089, IP2MPLS :3558).
"""

import pytest

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.solver import (
    DecisionRouteDb,
    SpfSolver,
    get_route_delta,
)
from openr_tpu.solver.cpu import BestPathCalResult
from openr_tpu.topology import build_adj_dbs
from openr_tpu.types import (
    CompareType,
    IpPrefix,
    MetricEntity,
    MetricVector,
    MplsActionCode,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixType,
)


def make_network(edges, prefixes, area="0", overloaded_nodes=None, **entry_kw):
    """Build (area_link_states, prefix_state) from edge list + node->prefix map."""
    ls = LinkState(area)
    for db in build_adj_dbs(
        edges, area=area, overloaded_nodes=overloaded_nodes
    ).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for node, pfxs in prefixes.items():
        entries = [
            PrefixEntry(IpPrefix(p), **entry_kw) if isinstance(p, str) else p
            for p in pfxs
        ]
        ps.update_prefix_database(
            PrefixDatabase(node, entries, area=area)
        )
    return {area: ls}, ps


PFX_A, PFX_B, PFX_C, PFX_D = (
    "10.1.0.0/16",
    "10.2.0.0/16",
    "10.3.0.0/16",
    "10.4.0.0/16",
)


class TestShortestPath:
    def test_line(self):
        als, ps = make_network(
            [("a", "b", 10), ("b", "c", 20)],
            {"a": [PFX_A], "b": [PFX_B], "c": [PFX_C]},
        )
        solver = SpfSolver("a")
        db = solver.build_route_db("a", als, ps)
        assert db is not None
        # no route to own prefix
        assert IpPrefix(PFX_A) not in db.unicast_entries
        rb = db.unicast_entries[IpPrefix(PFX_B)]
        assert {nh.neighbor_node for nh in rb.nexthops} == {"b"}
        assert {nh.metric for nh in rb.nexthops} == {10}
        rc = db.unicast_entries[IpPrefix(PFX_C)]
        assert {nh.neighbor_node for nh in rc.nexthops} == {"b"}
        assert {nh.metric for nh in rc.nexthops} == {30}

    def test_nonexistent_node(self):
        als, ps = make_network([("a", "b", 1)], {"a": [PFX_A]})
        assert SpfSolver("zz").build_route_db("zz", als, ps) is None

    def test_unreachable_prefix_skipped(self):
        als, ps = make_network(
            [("a", "b", 1)], {"a": [PFX_A], "b": [PFX_B], "z": [PFX_C]}
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_C) not in db.unicast_entries

    def test_v4_disabled(self):
        als, ps = make_network([("a", "b", 1)], {"b": [PFX_B]})
        db = SpfSolver("a", enable_v4=False).build_route_db("a", als, ps)
        assert IpPrefix(PFX_B) not in db.unicast_entries
        # v6 still works
        als, ps = make_network([("a", "b", 1)], {"b": ["fc00:2::/64"]})
        db = SpfSolver("a", enable_v4=False).build_route_db("a", als, ps)
        assert IpPrefix("fc00:2::/64") in db.unicast_entries


class TestEcmp:
    def test_square_ecmp(self):
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)],
            {"d": [PFX_D]},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b", "c"}
        assert all(nh.metric == 2 for nh in rd.nexthops)

    def test_anycast_best_node_lowest_name(self):
        # b and c both announce the prefix, equidistant from a
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": [PFX_D], "c": [PFX_D]},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b", "c"}
        assert rd.best_prefix_entry == PrefixEntry(IpPrefix(PFX_D))
        # lowest node name wins best
        # (best_area recorded from winning announcer)
        assert rd.best_area == "0"

    def test_anycast_closer_node_wins(self):
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 5)],
            {"b": [PFX_D], "c": [PFX_D]},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b"}

    def test_drained_announcer_filtered(self):
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": [PFX_D], "c": [PFX_D]},
            overloaded_nodes={"b"},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"c"}

    def test_all_drained_keeps_routes(self):
        als, ps = make_network(
            [("a", "b", 1)],
            {"b": [PFX_D]},
            overloaded_nodes={"b"},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_D) in db.unicast_entries


class TestLfa:
    def test_lfa_adds_alternate(self):
        # a--b cost 1, a--c cost 2, c--b cost 1: c is an LFA for a->b
        # (dist(c,b)=1 < dist(a,b)+dist(c,a): 1 < 1+2)
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 2), ("c", "b", 1)],
            {"b": [PFX_B]},
        )
        db_nolfa = SpfSolver("a").build_route_db("a", als, ps)
        assert {
            nh.neighbor_node
            for nh in db_nolfa.unicast_entries[IpPrefix(PFX_B)].nexthops
        } == {"b"}
        db_lfa = SpfSolver("a", compute_lfa_paths=True).build_route_db(
            "a", als, ps
        )
        nhs = db_lfa.unicast_entries[IpPrefix(PFX_B)].nexthops
        assert {nh.neighbor_node for nh in nhs} == {"b", "c"}
        # LFA nexthop metric reflects dist over that link: 2 + 1 = 3
        lfa_nh = next(nh for nh in nhs if nh.neighbor_node == "c")
        assert lfa_nh.metric == 3

    def test_no_lfa_through_loop(self):
        # plain triangle where alternate would loop back: b--c metric large
        als, ps = make_network(
            [("a", "b", 1), ("a", "c", 1), ("c", "b", 5)],
            {"b": [PFX_B]},
        )
        db = SpfSolver("a", compute_lfa_paths=True).build_route_db(
            "a", als, ps
        )
        # dist(c,b)=2 (via a) ... LFA condition: dist(c,b) < dist(a,b)+dist(c,a)
        # 2 < 1+1 false -> c not an LFA
        nhs = db.unicast_entries[IpPrefix(PFX_B)].nexthops
        assert {nh.neighbor_node for nh in nhs} == {"b"}


class TestMplsLabelRoutes:
    def test_node_label_routes(self):
        als, ps = make_network(
            [("a", "b", 1), ("b", "c", 1)],
            {},
        )
        # node labels: a=100, b=101, c=102 (sorted order from build_adj_dbs)
        db = SpfSolver("a").build_route_db("a", als, ps)
        # own label: POP_AND_LOOKUP
        own = db.mpls_entries[100]
        assert len(own.nexthops) == 1
        nh = next(iter(own.nexthops))
        assert nh.mpls_action.action == MplsActionCode.POP_AND_LOOKUP
        # direct neighbor label: PHP
        rb = db.mpls_entries[101]
        nh = next(iter(rb.nexthops))
        assert nh.mpls_action.action == MplsActionCode.PHP
        assert nh.neighbor_node == "b"
        # remote node label: SWAP through b
        rc = db.mpls_entries[102]
        nh = next(iter(rc.nexthops))
        assert nh.mpls_action.action == MplsActionCode.SWAP
        assert nh.mpls_action.swap_label == 102
        assert nh.neighbor_node == "b"

    def test_invalid_node_label_skipped(self):
        ls = LinkState("0")
        dbs = build_adj_dbs([("a", "b", 1)], node_labels=False)
        dbs["a"].node_label = 5  # invalid: < 16
        dbs["b"].node_label = 1 << 21  # invalid: > 2^20-1
        for db_ in dbs.values():
            ls.update_adjacency_database(db_)
        db = SpfSolver("a").build_route_db("a", {"0": ls}, PrefixState())
        assert db.mpls_entries == {}

    def test_duplicate_node_label(self):
        ls = LinkState("0")
        dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1)], node_labels=False)
        dbs["a"].node_label = 100
        dbs["b"].node_label = 200
        dbs["c"].node_label = 200  # conflicts with b
        for db_ in dbs.values():
            ls.update_adjacency_database(db_)
        db = SpfSolver("a").build_route_db("a", {"0": ls}, PrefixState())
        # conflict resolution (Decision.cpp:439-448): the entry whose node
        # name sorts lower survives regardless of processing order -> b keeps
        # 200, and b is our neighbor so the action is PHP
        nh = next(iter(db.mpls_entries[200].nexthops))
        assert nh.mpls_action.action == MplsActionCode.PHP
        assert nh.neighbor_node == "b"

    def test_adj_label_routes(self):
        ls = LinkState("0")
        dbs = build_adj_dbs([("a", "b", 7)], node_labels=False)
        from openr_tpu.types import replace

        dbs["a"].adjacencies = [
            replace(adj, adj_label=50000) for adj in dbs["a"].adjacencies
        ]
        for db_ in dbs.values():
            ls.update_adjacency_database(db_)
        db = SpfSolver("a").build_route_db("a", {"0": ls}, PrefixState())
        entry = db.mpls_entries[50000]
        nh = next(iter(entry.nexthops))
        assert nh.mpls_action.action == MplsActionCode.PHP
        assert nh.metric == 7


class TestKsp2:
    def make_sr_network(self, edges, prefixes, algo, **kw):
        entries = {
            node: [
                PrefixEntry(
                    IpPrefix(p),
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=algo,
                    **kw,
                )
                for p in pfxs
            ]
            for node, pfxs in prefixes.items()
        }
        return make_network(edges, entries)

    def test_sr_mpls_sp_ecmp_uses_first_paths(self):
        als, ps = self.make_sr_network(
            [("a", "b", 1), ("b", "c", 1)],
            {"c": [PFX_C]},
            PrefixForwardingAlgorithm.SP_ECMP,
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rc = db.unicast_entries[IpPrefix(PFX_C)]
        assert len(rc.nexthops) == 1
        nh = next(iter(rc.nexthops))
        assert nh.use_non_shortest_route
        assert nh.metric == 2
        # label stack: PUSH c's label (b's popped for PHP)
        assert nh.mpls_action.action == MplsActionCode.PUSH
        assert nh.mpls_action.push_labels == (102,)

    def test_ksp2_adds_second_path(self):
        # square: a->b->d and a->c->d; ksp2 gives both as "first" ECMP paths
        # triangle version gives a second longer path
        als, ps = self.make_sr_network(
            [("a", "b", 1), ("a", "c", 1), ("c", "b", 1)],
            {"b": [PFX_B]},
            PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rb = db.unicast_entries[IpPrefix(PFX_B)]
        # direct path (metric 1) + detour via c (metric 2)
        metrics = sorted(nh.metric for nh in rb.nexthops)
        assert metrics == [1, 2]
        detour = next(nh for nh in rb.nexthops if nh.metric == 2)
        assert detour.neighbor_node == "c"
        # detour stack: PUSH b's label (c's popped... walk: a->c->b;
        # labels [c,b] reversed => [b's label at bottom]; pop first-hop c
        assert detour.mpls_action.action == MplsActionCode.PUSH

    def test_min_nexthop_drops_route(self):
        als, ps = self.make_sr_network(
            [("a", "b", 1)],
            {"b": [PFX_B]},
            PrefixForwardingAlgorithm.KSP2_ED_ECMP,
            min_nexthop=2,
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_B) not in db.unicast_entries

    def test_prepend_label(self):
        als, ps = self.make_sr_network(
            [("a", "b", 1), ("b", "c", 1)],
            {"c": [PFX_C]},
            PrefixForwardingAlgorithm.SP_ECMP,
            prepend_label=60000,
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        nh = next(iter(db.unicast_entries[IpPrefix(PFX_C)].nexthops))
        # prepend label at bottom of the stack
        assert nh.mpls_action.push_labels == (60000, 102)


def mv(*entities) -> MetricVector:
    return MetricVector(version=1, metrics=tuple(entities))


def me(id, priority, metric, tiebreak=False):
    return MetricVector  # placeholder


class TestBgp:
    def make_bgp_network(self, edges, announcers):
        """announcers: node -> MetricVector"""
        als, _ = make_network(edges, {})
        ps = PrefixState()
        for node, vector in announcers.items():
            ps.update_prefix_database(
                PrefixDatabase(
                    node,
                    [
                        PrefixEntry(
                            IpPrefix(PFX_D), type=PrefixType.BGP, mv=vector
                        ),
                        PrefixEntry(
                            IpPrefix(f"192.168.0.{ord(node[-1])}/32"),
                            type=PrefixType.LOOPBACK,
                        ),
                    ],
                    area="0",
                )
            )
        return als, ps

    def test_winner_takes_route(self):
        e = lambda val: MetricEntity(
            id=10, priority=10, op=CompareType.WIN_IF_PRESENT, metric=(val,)
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": mv(e(100)), "c": mv(e(50))},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b"}
        assert rd.best_nexthop is not None
        assert rd.best_nexthop.address == "192.168.0.98"  # b's loopback

    def test_tie_no_route(self):
        e = lambda val: MetricEntity(
            id=10, priority=10, op=CompareType.WIN_IF_PRESENT, metric=(val,)
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": mv(e(100)), "c": mv(e(100))},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_D) not in db.unicast_entries

    def test_tiebreaker_ecmp(self):
        # tie-breaker entities produce TIE_WINNER/TIE_LOOSER: both programmed
        e = lambda val: MetricEntity(
            id=10,
            priority=10,
            op=CompareType.WIN_IF_PRESENT,
            is_best_path_tiebreaker=True,
            metric=(val,),
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1), ("a", "c", 1)],
            {"b": mv(e(100)), "c": mv(e(50))},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b", "c"}
        # best node is the tie-winner b
        assert rd.best_nexthop.address == "192.168.0.98"

    def test_igp_tiebreak(self):
        # equal vectors + bgp_use_igp_metric: closer announcer wins
        e = lambda: MetricEntity(
            id=10,
            priority=10,
            op=CompareType.WIN_IF_PRESENT,
            is_best_path_tiebreaker=True,
            metric=(7,),
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1), ("a", "c", 5)],
            {"b": mv(e()), "c": mv(e())},
        )
        db = SpfSolver("a", bgp_use_igp_metric=True).build_route_db(
            "a", als, ps
        )
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b"}

    def test_self_originated_no_route(self):
        e = lambda val: MetricEntity(
            id=10, priority=10, op=CompareType.WIN_IF_PRESENT, metric=(val,)
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1)],
            {"a": mv(e(100)), "b": mv(e(50))},
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_D) not in db.unicast_entries

    def test_bgp_dry_run(self):
        e = lambda val: MetricEntity(
            id=10, priority=10, op=CompareType.WIN_IF_PRESENT, metric=(val,)
        )
        als, ps = self.make_bgp_network(
            [("a", "b", 1)], {"b": mv(e(100))}
        )
        db = SpfSolver("a", bgp_dry_run=True).build_route_db("a", als, ps)
        assert db.unicast_entries[IpPrefix(PFX_D)].do_not_install

    def test_mixed_bgp_nonbgp_skipped(self):
        als, _ = make_network([("a", "b", 1), ("a", "c", 1)], {})
        ps = PrefixState()
        e = MetricEntity(
            id=10, priority=10, op=CompareType.WIN_IF_PRESENT, metric=(1,)
        )
        ps.update_prefix_database(
            PrefixDatabase(
                "b",
                [PrefixEntry(IpPrefix(PFX_D), type=PrefixType.BGP, mv=mv(e))],
                area="0",
            )
        )
        ps.update_prefix_database(
            PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX_D))], area="0")
        )
        db = SpfSolver("a").build_route_db("a", als, ps)
        assert IpPrefix(PFX_D) not in db.unicast_entries
        assert SpfSolver("a").counters.get("decision.skipped_unicast_route") is None


class TestRouteDelta:
    def test_delta(self):
        als, ps = make_network(
            [("a", "b", 1), ("b", "c", 1)],
            {"b": [PFX_B], "c": [PFX_C]},
        )
        solver = SpfSolver("a")
        db1 = solver.build_route_db("a", als, ps)
        # c withdraws its prefix; b's route unchanged
        ps.update_prefix_database(PrefixDatabase("c", [], area="0"))
        db2 = solver.build_route_db("a", als, ps)
        delta = get_route_delta(db2, db1)
        assert delta.unicast_routes_to_delete == [IpPrefix(PFX_C)]
        assert delta.unicast_routes_to_update == []
        assert delta.mpls_routes_to_update == []
        # metric change on the path to b
        ls = als["0"]
        dbs = build_adj_dbs([("a", "b", 9), ("b", "c", 1)])
        ls.update_adjacency_database(dbs["a"])
        db3 = solver.build_route_db("a", als, ps)
        delta2 = get_route_delta(db3, db2)
        assert [e.prefix for e in delta2.unicast_routes_to_update] == [
            IpPrefix(PFX_B)
        ]

    def test_empty_delta(self):
        als, ps = make_network([("a", "b", 1)], {"b": [PFX_B]})
        solver = SpfSolver("a")
        db1 = solver.build_route_db("a", als, ps)
        db2 = solver.build_route_db("a", als, ps)
        assert get_route_delta(db2, db1).empty()


class TestStaticRoutes:
    def test_static_mpls_updates(self):
        from openr_tpu.types import NextHop

        solver = SpfSolver("a")
        assert not solver.static_routes_updated()
        nh = NextHop(address="fc00::1")
        solver.push_static_routes_delta({40000: {nh}}, set())
        assert solver.static_routes_updated()
        upd = solver.process_static_route_updates()
        assert [e.label for e in upd.mpls_routes_to_update] == [40000]
        assert not solver.static_routes_updated()
        # delete wins over earlier add
        solver.push_static_routes_delta({40001: {nh}}, set())
        solver.push_static_routes_delta({}, {40001})
        upd = solver.process_static_route_updates()
        assert upd.mpls_routes_to_update == []
        assert upd.mpls_routes_to_delete == [40001]


class TestMultiArea:
    def test_ecmp_across_areas(self):
        # area A: a--b announces prefix; area B: a--c announces same prefix
        ls_a = LinkState("A")
        for db in build_adj_dbs([("a", "b", 1)], area="A").values():
            ls_a.update_adjacency_database(db)
        ls_b = LinkState("B")
        for db in build_adj_dbs([("a", "c", 1)], area="B").values():
            ls_b.update_adjacency_database(db)
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX_D))], area="A")
        )
        ps.update_prefix_database(
            PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX_D))], area="B")
        )
        db = SpfSolver("a").build_route_db(
            "a", {"A": ls_a, "B": ls_b}, ps
        )
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b", "c"}
        assert {nh.area for nh in rd.nexthops} == {"A", "B"}

    def test_closer_area_wins(self):
        ls_a = LinkState("A")
        for db in build_adj_dbs([("a", "b", 1)], area="A").values():
            ls_a.update_adjacency_database(db)
        ls_b = LinkState("B")
        for db in build_adj_dbs([("a", "c", 9)], area="B").values():
            ls_b.update_adjacency_database(db)
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX_D))], area="A")
        )
        ps.update_prefix_database(
            PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX_D))], area="B")
        )
        db = SpfSolver("a").build_route_db(
            "a", {"A": ls_a, "B": ls_b}, ps
        )
        rd = db.unicast_entries[IpPrefix(PFX_D)]
        assert {nh.neighbor_node for nh in rd.nexthops} == {"b"}


class TestMultiAreaBackends:
    """TPU/CPU parity and degenerate cases for multi-area selection
    (complements TestMultiArea's announcer/ECMP/area-label coverage)."""

    def _two_area_network(self, m0, m1):
        ls0 = LinkState("0")
        for db in build_adj_dbs([("a", "b", m0)], area="0").values():
            ls0.update_adjacency_database(db)
        ls1 = LinkState("1")
        for db in build_adj_dbs([("a", "c", m1)], area="1").values():
            ls1.update_adjacency_database(db)
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX_A))], area="0")
        )
        ps.update_prefix_database(
            PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX_A))], area="1")
        )
        return {"0": ls0, "1": ls1}, ps

    def test_tpu_backend_multi_area_parity(self):
        from openr_tpu.solver import TpuSpfSolver

        for m0, m1 in ((10, 2), (3, 3), (1, 9)):
            als, ps = self._two_area_network(m0, m1)
            cpu = SpfSolver("a").build_route_db("a", als, ps)
            tpu = TpuSpfSolver("a").build_route_db("a", als, ps)
            assert cpu == tpu, (m0, m1)

    def test_node_absent_from_one_area(self):
        # node "a" not in area 1's graph at all: area-0 routes still built
        ls0 = LinkState("0")
        for db in build_adj_dbs([("a", "b", 1)], area="0").values():
            ls0.update_adjacency_database(db)
        ls1 = LinkState("1")
        for db in build_adj_dbs([("x", "y", 1)], area="1").values():
            ls1.update_adjacency_database(db)
        ps = PrefixState()
        ps.update_prefix_database(
            PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX_A))], area="0")
        )
        ps.update_prefix_database(
            PrefixDatabase("y", [PrefixEntry(IpPrefix(PFX_B))], area="1")
        )
        db = SpfSolver("a").build_route_db("a", {"0": ls0, "1": ls1}, ps)
        assert IpPrefix(PFX_A) in db.unicast_entries
        # unreachable area's prefix yields no route (no announcer reachable)
        assert IpPrefix(PFX_B) not in db.unicast_entries
