"""Decision module tests, mirroring DecisionTestFixture scenarios from
openr/decision/tests/DecisionTest.cpp:4234+ (publication processing, debounce
batching, route delta emission, expiry, cold start, RibPolicy)."""

import asyncio

import pytest

from openr_tpu.decision import Decision, DecisionConfig
from openr_tpu.messaging import ReplicateQueue, RWQueue, RQueue
from openr_tpu.solver.rib_policy import (
    RibPolicy,
    RibPolicyStatement,
    SetWeightAction,
)
from openr_tpu.topology import build_adj_dbs
from openr_tpu.types import (
    AdjacencyDatabase,
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    Publication,
    Value,
    adj_key,
    prefix_key,
)
from openr_tpu.utils import serializer


def run(coro, timeout=10.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def make_publication(adj_dbs=(), prefix_dbs=(), expired=(), area="0", version=1):
    pub = Publication(area=area)
    for db in adj_dbs:
        pub.key_vals[adj_key(db.this_node_name)] = Value(
            version, db.this_node_name, serializer.dumps(db)
        )
    for db in prefix_dbs:
        pub.key_vals[prefix_key(db.this_node_name)] = Value(
            version, db.this_node_name, serializer.dumps(db)
        )
    pub.expired_keys.extend(expired)
    return pub


def make_decision(backend="cpu", **cfg_kw):
    kv_q = RWQueue()
    route_q = ReplicateQueue()
    decision = Decision(
        DecisionConfig(
            my_node_name="a",
            solver_backend=backend,
            debounce_min=0.005,
            debounce_max=0.02,
            **cfg_kw,
        ),
        RQueue(kv_q),
        route_q,
    )
    return decision, kv_q, route_q


PFX = "10.9.0.0/16"


class TestDecision:
    def test_publication_to_route_delta(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[
                        PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX))])
                    ],
                )
            )
            delta = await reader.get()
            assert [e.prefix for e in delta.unicast_routes_to_update] == [
                IpPrefix(PFX)
            ]
            nh = next(iter(delta.unicast_routes_to_update[0].nexthops))
            assert nh.neighbor_node == "b"
            assert delta.mpls_routes_to_update  # node label routes
            decision.stop()

        run(body())

    def test_debounce_batches_publications(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1), ("c", "d", 1)])
            # push each node's adjacency separately: one rebuild
            for db in dbs.values():
                kv_q.push(make_publication(adj_dbs=[db]))
            kv_q.push(
                make_publication(
                    prefix_dbs=[PrefixDatabase("d", [PrefixEntry(IpPrefix(PFX))])]
                )
            )
            delta = await reader.get()
            assert decision.counters["decision.route_build_runs"] == 1
            assert decision.counters["decision.adj_db_update"] == 4  # 4 nodes
            decision.stop()

        run(body())

    def test_link_flap_reroutes(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 5)]
            dbs = build_adj_dbs(edges)
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            d1 = await reader.get()
            nh1 = next(iter(d1.unicast_routes_to_update[0].nexthops))
            assert nh1.neighbor_node == "b"
            # b withdraws its link to c
            b_down = AdjacencyDatabase(
                "b",
                [x for x in dbs["b"].adjacencies if x.other_node_name != "c"],
                node_label=dbs["b"].node_label,
            )
            kv_q.push(make_publication(adj_dbs=[b_down], version=2))
            d2 = await reader.get()
            route = next(
                e
                for e in d2.unicast_routes_to_update
                if e.prefix == IpPrefix(PFX)
            )
            assert {nh.neighbor_node for nh in route.nexthops} == {"c"}
            decision.stop()

        run(body())

    def test_adj_expiry_removes_routes(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await reader.get()
            # c's adjacency db expires from the store
            kv_q.push(make_publication(expired=[adj_key("c")]))
            d2 = await reader.get()
            assert IpPrefix(PFX) in d2.unicast_routes_to_delete
            decision.stop()

        run(body())

    def test_prefix_expiry(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await reader.get()
            kv_q.push(make_publication(expired=[prefix_key("b")]))
            d2 = await reader.get()
            assert d2.unicast_routes_to_delete == [IpPrefix(PFX)]
            decision.stop()

        run(body())

    def test_cold_start_holds_computation(self):
        async def body():
            decision, kv_q, route_q = make_decision(eor_time_s=0.15)
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await asyncio.sleep(0.05)
            assert not decision.have_computed_routes  # still held
            delta = await reader.get()  # emitted after eor expires
            assert decision.have_computed_routes
            assert delta.unicast_routes_to_update
            decision.stop()

        run(body())

    def test_rib_policy_weights(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await reader.get()
            policy = RibPolicy(
                [
                    RibPolicyStatement(
                        "s1",
                        {IpPrefix(PFX)},
                        SetWeightAction(
                            default_weight=1, area_to_weight={"0": 7}
                        ),
                    )
                ],
                ttl_secs=60,
            )
            decision.set_rib_policy(policy)
            delta = await reader.get()
            entry = delta.unicast_routes_to_update[0]
            assert {nh.weight for nh in entry.nexthops} == {7}
            decision.stop()

        run(body())

    def test_rib_policy_zero_weight_drops_nexthop(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await reader.get()
            decision.set_rib_policy(
                RibPolicy(
                    [
                        RibPolicyStatement(
                            "s1",
                            {IpPrefix(PFX)},
                            SetWeightAction(default_weight=0),
                        )
                    ],
                    ttl_secs=60,
                )
            )
            delta = await reader.get()
            assert delta.unicast_routes_to_update[0].nexthops == set()
            decision.stop()

        run(body())

    def test_get_decision_route_db_other_node(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("a", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            await reader.get()
            # from c's perspective, route to a's prefix via b
            c_db = decision.get_decision_route_db("c")
            nh = next(iter(c_db.unicast_entries[IpPrefix(PFX)].nexthops))
            assert nh.neighbor_node == "b"
            decision.stop()

        run(body())

    def test_tpu_backend_end_to_end(self):
        async def body():
            decision, kv_q, route_q = make_decision(backend="tpu")
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs(
                [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
            )
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("d", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            delta = await reader.get()
            route = delta.unicast_routes_to_update[0]
            assert {nh.neighbor_node for nh in route.nexthops} == {"b", "c"}
            assert decision.solver.device_solves >= 1
            decision.stop()

        run(body())

    def test_per_prefix_keys_accumulate(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            p1, p2 = IpPrefix("10.1.0.0/16"), IpPrefix("10.2.0.0/16")
            pub = make_publication(adj_dbs=dbs.values())
            # two per-prefix keys from the same node must accumulate
            for p in (p1, p2):
                pub.key_vals[prefix_key("b", p, "0")] = Value(
                    1, "b", serializer.dumps(
                        PrefixDatabase("b", [PrefixEntry(p)])
                    )
                )
            kv_q.push(pub)
            delta = await reader.get()
            assert {e.prefix for e in delta.unicast_routes_to_update} == {
                p1, p2
            }
            # expiry of ONE per-prefix key withdraws only that prefix
            kv_q.push(
                make_publication(expired=[prefix_key("b", p1, "0")])
            )
            d2 = await reader.get()
            assert d2.unicast_routes_to_delete == [p1]
            assert decision.get_decision_route_db().unicast_entries.keys() == {
                p2
            }
            decision.stop()

        run(body())

    def test_node_label_only_change_rebuilds(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(make_publication(adj_dbs=dbs.values()))
            d1 = await reader.get()
            assert {e.label for e in d1.mpls_routes_to_update} == {100, 101}
            # b changes only its node label
            b2 = AdjacencyDatabase(
                "b", dbs["b"].adjacencies, node_label=555
            )
            kv_q.push(make_publication(adj_dbs=[b2], version=2))
            d2 = await reader.get()
            assert {e.label for e in d2.mpls_routes_to_update} == {555}
            assert d2.mpls_routes_to_delete == [101]
            decision.stop()

        run(body())

    def test_malformed_value_does_not_kill_consumer(self):
        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()
            bad = Publication(area="0")
            bad.key_vals[adj_key("evil")] = Value(1, "evil", b"not-json")
            kv_q.push(bad)
            await asyncio.sleep(0.05)
            assert decision.counters.get("decision.errors") == 1
            # consumer still alive: a good publication still computes routes
            dbs = build_adj_dbs([("a", "b", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[PrefixDatabase("b", [PrefixEntry(IpPrefix(PFX))])],
                )
            )
            delta = await reader.get()
            assert delta.unicast_routes_to_update
            decision.stop()

        run(body())

    def test_serializer_roundtrip_deterministic(self):
        dbs = build_adj_dbs([("a", "b", 1)])
        blob1 = serializer.dumps(dbs["a"])
        blob2 = serializer.dumps(serializer.loads(blob1))
        assert blob1 == blob2
        pdb = PrefixDatabase("a", [PrefixEntry(IpPrefix(PFX))])
        assert serializer.loads(serializer.dumps(pdb)) == pdb


class TestOrderedFib:
    def test_link_up_held_by_hop_distance_then_released(self):
        """Ordered-FIB programming (Decision.cpp:1669-1679): a link coming
        up is held for my hop-distance to the advertising node, so nodes
        closer to the change program first; decrement ticks release it and
        trigger a rebuild."""

        async def body():
            decision, kv_q, route_q = make_decision(enable_ordered_fib=True)
            reader = route_q.get_reader()
            decision.start()

            # line a - b - c - d, with d's loopback advertised
            edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
            dbs = build_adj_dbs(edges)
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[
                        PrefixDatabase("d", [PrefixEntry(IpPrefix(PFX))])
                    ],
                )
            )
            delta = await reader.get()
            routes = {e.prefix for e in delta.unicast_routes_to_update}
            assert IpPrefix(PFX) in routes

            # b raises its b->c metric (a "down"-direction change
            # advertised by b): nodes closer to b than the farthest node
            # hold back so remote nodes program first —
            # hold_down(a) = max_hops_to(b) - hops(a,b) = 2 - 1 = 1 tick
            dbs2 = build_adj_dbs([("a", "b", 1), ("b", "c", 5), ("c", "d", 1)])
            kv_q.push(make_publication(adj_dbs=[dbs2["b"]], version=2))
            # the held change must not produce an immediate route update
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.get(), 0.15)

            # one hold tick releases the change and triggers the rebuild
            decision.decrement_ordered_fib_holds()
            delta2 = await asyncio.wait_for(reader.get(), 5)
            updated = {
                e.prefix: e for e in delta2.unicast_routes_to_update
            }
            assert IpPrefix(PFX) in updated
            # the released change is reflected: path a-b-c-d now costs
            # 1 + 5 + 1 = 7 through the raised b->c metric
            nh = next(iter(updated[IpPrefix(PFX)].nexthops))
            assert nh.metric == 7, nh
            decision.stop()

        run(body())


class TestRebuildErrorResilience:
    def test_solver_exception_does_not_kill_the_module(self):
        """rebuild_routes runs from a timer callback; a solver failure must
        be logged + counted, and the NEXT publication must still converge
        (the daemon retries rather than silently stopping)."""

        async def body():
            decision, kv_q, route_q = make_decision()
            reader = route_q.get_reader()
            decision.start()

            boom = {"armed": True}
            real_build = decision.solver.build_route_db

            def flaky(*args, **kwargs):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected solver failure")
                return real_build(*args, **kwargs)

            decision.solver.build_route_db = flaky
            dbs = build_adj_dbs([("a", "b", 1), ("b", "c", 1)])
            kv_q.push(
                make_publication(
                    adj_dbs=dbs.values(),
                    prefix_dbs=[
                        PrefixDatabase("c", [PrefixEntry(IpPrefix(PFX))])
                    ],
                )
            )
            # first rebuild fails, the debounce re-arms, and the retry
            # converges without any new publication
            delta = await asyncio.wait_for(reader.get(), 5)
            assert decision.counters.get("decision.route_build_errors") == 1
            assert IpPrefix(PFX) in {
                e.prefix for e in delta.unicast_routes_to_update
            }
            decision.stop()

        run(body())
