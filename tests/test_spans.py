"""Convergence span tests: Span primitive semantics, monotonic-clock
immunity to wall-clock jumps, and the full KvStore→Decision→Fib trace pass
(ISSUE 2 acceptance: non-zero decision.spf.solve_ms and convergence.e2e_ms
after a link-flap sequence, warm vs cold solves distinguishable,
invalidation rounds populated on an increase event)."""

import asyncio
import time

from openr_tpu.monitor import SPAN_EVENT, Span
from openr_tpu.testing.decision_harness import (
    lsdb_publication,
    run_convergence_trace,
)
from openr_tpu.topology import build_adj_dbs, grid_edges
from openr_tpu.types import Value, adj_key
from openr_tpu.utils import serializer


def run(coro, timeout=120.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


class TestSpan:
    def test_marks_accumulate_stage_durations(self):
        span = Span("convergence")
        first = span.mark("decision.recv")
        second = span.mark("decision.debounce")
        assert first >= 0.0 and second >= 0.0
        durations = span.stage_durations_ms()
        assert list(durations) == ["decision.recv", "decision.debounce"]
        assert span.elapsed_ms() >= first + second

    def test_seeded_t0_predates_first_mark(self):
        t0 = time.monotonic() - 0.050
        span = Span("convergence", t0=t0)
        ms = span.mark("decision.recv")
        assert ms >= 50.0

    def test_wall_clock_jump_does_not_skew(self, monkeypatch):
        """Satellite: spans run on time.monotonic — a wall-clock step
        (NTP, manual date set) between marks must not leak into stage
        durations or e2e."""
        span = Span("convergence")
        monkeypatch.setattr(time, "time", lambda: 4e9)  # jump ~100 years
        ms = span.mark("decision.recv")
        assert ms < 10_000.0
        assert span.elapsed_ms() < 10_000.0

    def test_to_log_sample(self):
        span = Span("convergence")
        span.mark("decision.recv")
        span.mark("fib.program")
        sample = span.to_log_sample()
        assert sample.get("event") == SPAN_EVENT
        assert sample.get("span") == "convergence"
        assert sample.get("decision.recv_ms") >= 0.0
        assert sample.get("fib.program_ms") >= 0.0
        assert sample.get("total_ms") >= 0.0

    def test_explicit_ts_replays_past_marks(self):
        t0 = time.monotonic() - 0.100
        span = Span("convergence", t0=t0)
        first = span.mark("spark.neighbor_event", ts=t0)
        mid = span.mark("linkmonitor.adj_advertised", ts=t0 + 0.040)
        last = span.mark("kvstore.publish")
        assert first == 0.0
        assert 39.0 <= mid <= 41.0
        assert last >= 55.0  # ~60ms of real elapsed time remain

    def test_out_of_order_ts_clamps_to_previous_mark(self):
        span = Span("convergence")
        span.mark("a")
        behind = span.mark("b", ts=span.marks[0][1] - 1.0)
        assert behind == 0.0
        durations = span.stage_durations_ms()
        assert durations["b"] == 0.0
        assert span.marks[1][1] == span.marks[0][1]


class TestSpanSeeding:
    """Decision's span construction from pre-publish stages: exact
    monotonic span_stages on the origin node, wall-clock reconstruction
    (origin PerfEvents + flood hop trace) on remote nodes."""

    def _stages(self, span):
        return [stage for stage, _ in span.marks]

    def test_local_span_stages_prefix_the_span(self):
        from openr_tpu.decision.decision import _build_span
        from openr_tpu.types import Publication

        now = time.monotonic()
        pub = Publication(
            ts_monotonic=now,
            span_stages=[
                ("spark.neighbor_event", now - 0.050),
                ("linkmonitor.adj_advertised", now - 0.020),
            ],
        )
        span = _build_span(None, pub)
        assert self._stages(span) == [
            "spark.neighbor_event",
            "linkmonitor.adj_advertised",
            "kvstore.publish",
        ]
        durations = span.stage_durations_ms()
        assert durations["spark.neighbor_event"] == 0.0  # == t0
        assert 29.0 <= durations["linkmonitor.adj_advertised"] <= 31.0
        assert 19.0 <= durations["kvstore.publish"] <= 21.0

    def test_remote_span_reconstructed_from_wall_clock_traces(self):
        from openr_tpu.decision.decision import _build_span
        from openr_tpu.kvstore.store import (
            FLOOD_ORIGINATED_EVENT,
            FLOOD_RECEIVED_EVENT,
        )
        from openr_tpu.types import PerfEvent, PerfEvents, Publication

        now_wall = time.time() * 1e3
        value_perf = PerfEvents(
            [
                PerfEvent("n1", "NEIGHBOR_EVENT_RECVD", now_wall - 50.0),
                PerfEvent("n1", "ADJ_DB_ADVERTISED", now_wall - 40.0),
            ]
        )
        flood = PerfEvents(
            [
                PerfEvent("n1", FLOOD_ORIGINATED_EVENT, now_wall - 30.0),
                PerfEvent("n2", FLOOD_RECEIVED_EVENT, now_wall - 20.0),
                PerfEvent("n3", FLOOD_RECEIVED_EVENT, now_wall - 10.0),
            ]
        )
        pub = Publication(
            ts_monotonic=time.monotonic(), perf_events=flood
        )
        span = _build_span(value_perf, pub)
        assert self._stages(span) == [
            "spark.neighbor_event",
            "linkmonitor.adj_advertised",
            "kvstore.flood.origin",
            "kvstore.flood.hop1",
            "kvstore.flood.hop2",
            "kvstore.publish",
        ]
        durations = span.stage_durations_ms()
        # the 10ms wall-clock gaps survive the monotonic reconstruction
        for stage in (
            "linkmonitor.adj_advertised",
            "kvstore.flood.origin",
            "kvstore.flood.hop1",
            "kvstore.flood.hop2",
        ):
            assert 8.0 <= durations[stage] <= 12.0, (stage, durations)
        assert span.elapsed_ms() >= 45.0

    def test_no_stages_falls_back_to_publish_stamp(self):
        from openr_tpu.decision.decision import _build_span
        from openr_tpu.types import Publication

        now = time.monotonic()
        span = _build_span(None, Publication(ts_monotonic=now))
        assert self._stages(span) == ["kvstore.publish"]
        assert span.t0 == now


def _flap_publication(edges, metric, nodes=("g0_0", "g0_1"), version=2):
    """Publication re-announcing `nodes` adj dbs with the (g0_0, g0_1)
    link's metric set to `metric`."""
    flapped = [
        (a, b, metric) if {a, b} == {"g0_0", "g0_1"} else (a, b, m)
        for a, b, m in edges
    ]
    dbs = build_adj_dbs(flapped)
    pub = lsdb_publication([])
    for node in nodes:
        pub.key_vals[adj_key(node)] = Value(
            version, node, serializer.dumps(dbs[node])
        )
    return pub


class TestConvergenceTracePass:
    """Cold ingest + metric increase/decrease/increase flaps through the
    full Decision(tpu)→Fib pipeline, observability asserted end to end."""

    def _run(self):
        edges = grid_edges(4)
        base = lsdb_publication(
            build_adj_dbs(edges).values(), {"g3_3": ["10.0.0.0/24"]}
        )
        # increase → decrease → increase; the last event is an increase so
        # the invalidation_rounds_last gauge reflects a mark fixpoint run
        # (a decrease correctly writes 0 — its inc_idx is empty)
        flaps = [
            _flap_publication(edges, 5, version=2),
            _flap_publication(edges, 1, version=3),
            _flap_publication(edges, 7, version=4),
        ]
        return run(run_convergence_trace("g0_0", [base, *flaps]))

    def test_link_flap_sequence_histograms_and_counters(self):
        monitor, decision, fib = self._run()
        hists = monitor.get_histograms()

        # acceptance: non-zero solve + e2e latency distributions
        solve = hists["decision.spf.solve_ms"]
        assert solve["count"] >= 4
        assert solve["p50"] > 0.0 and solve["p99"] > 0.0
        e2e = hists["convergence.e2e_ms"]
        assert e2e["count"] == 4
        assert e2e["p50"] > 0.0 and e2e["p99"] > 0.0

        # warm vs cold solves distinguishable: one cold ingest, three warm
        # weight-patch flaps
        assert hists["decision.spf.solve_cold_ms"]["count"] >= 1
        assert hists["decision.spf.solve_warm_ms"]["count"] >= 3

        # per-stage histograms populated once per debounced rebuild
        assert hists["decision.debounce_ms"]["count"] == 4
        assert hists["decision.route_build_ms"]["count"] == 4
        assert hists["fib.program_ms"]["count"] == 4

        counters = monitor.get_counters()
        assert counters["decision.spf.incremental_solves"] == 3
        # the increase event ran the boolean invalidation-mark fixpoint
        assert counters["decision.spf.invalidation_rounds_last"] >= 1
        assert counters["decision.spf.rounds_last"] >= 1
        # profiling: traffic crossed the host-device link both ways and
        # the executable cache compiled at least the cold + warm solvers
        assert counters["decision.spf.host_to_device_bytes"] > 0
        assert counters["decision.spf.device_to_host_bytes"] > 0
        assert counters["decision.spf.compile_cache_misses"] >= 1
        assert counters["fib.convergence_spans"] == 4

    def test_span_log_samples_reach_monitor(self):
        monitor, decision, fib = self._run()
        traces = [
            s
            for s in monitor.get_event_logs()
            if s.get("event") == SPAN_EVENT
        ]
        assert len(traces) == 4
        for sample in traces:
            # the full stage chain is present and non-negative
            for stage in (
                "decision.recv_ms",
                "decision.debounce_ms",
                "decision.route_build_ms",
                "fib.recv_ms",
                "fib.program_ms",
                "total_ms",
            ):
                assert sample.get(stage) is not None, stage
                assert sample.get(stage) >= 0.0, stage
            # debounce waited at least roughly the configured minimum
            assert sample.get("total_ms") >= sample.get(
                "decision.debounce_ms"
            )
            # node_name auto-filled by the monitor drain
            assert sample.get("node_name") == "g0_0"


class TestCpuBackendSolveHistogram:
    """The CPU oracle backend reports decision.spf.solve_ms too, so the
    observability surface does not depend on the device backend."""

    def test_cpu_solver_times_spf(self):
        edges = grid_edges(3)
        base = lsdb_publication(
            build_adj_dbs(edges).values(), {"g2_2": ["10.1.0.0/24"]}
        )
        monitor, decision, fib = run(
            run_convergence_trace("g0_0", [base], backend="cpu")
        )
        hists = monitor.get_histograms()
        assert hists["decision.spf.solve_ms"]["count"] >= 1
        assert hists["convergence.e2e_ms"]["count"] == 1
