"""Convergence span tests: Span primitive semantics, monotonic-clock
immunity to wall-clock jumps, and the full KvStore→Decision→Fib trace pass
(ISSUE 2 acceptance: non-zero decision.spf.solve_ms and convergence.e2e_ms
after a link-flap sequence, warm vs cold solves distinguishable,
invalidation rounds populated on an increase event)."""

import asyncio
import time

from openr_tpu.monitor import SPAN_EVENT, Span
from openr_tpu.testing.decision_harness import (
    lsdb_publication,
    run_convergence_trace,
)
from openr_tpu.topology import build_adj_dbs, grid_edges
from openr_tpu.types import Value, adj_key
from openr_tpu.utils import serializer


def run(coro, timeout=120.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


class TestSpan:
    def test_marks_accumulate_stage_durations(self):
        span = Span("convergence")
        first = span.mark("decision.recv")
        second = span.mark("decision.debounce")
        assert first >= 0.0 and second >= 0.0
        durations = span.stage_durations_ms()
        assert list(durations) == ["decision.recv", "decision.debounce"]
        assert span.elapsed_ms() >= first + second

    def test_seeded_t0_predates_first_mark(self):
        t0 = time.monotonic() - 0.050
        span = Span("convergence", t0=t0)
        ms = span.mark("decision.recv")
        assert ms >= 50.0

    def test_wall_clock_jump_does_not_skew(self, monkeypatch):
        """Satellite: spans run on time.monotonic — a wall-clock step
        (NTP, manual date set) between marks must not leak into stage
        durations or e2e."""
        span = Span("convergence")
        monkeypatch.setattr(time, "time", lambda: 4e9)  # jump ~100 years
        ms = span.mark("decision.recv")
        assert ms < 10_000.0
        assert span.elapsed_ms() < 10_000.0

    def test_to_log_sample(self):
        span = Span("convergence")
        span.mark("decision.recv")
        span.mark("fib.program")
        sample = span.to_log_sample()
        assert sample.get("event") == SPAN_EVENT
        assert sample.get("span") == "convergence"
        assert sample.get("decision.recv_ms") >= 0.0
        assert sample.get("fib.program_ms") >= 0.0
        assert sample.get("total_ms") >= 0.0


def _flap_publication(edges, metric, nodes=("g0_0", "g0_1"), version=2):
    """Publication re-announcing `nodes` adj dbs with the (g0_0, g0_1)
    link's metric set to `metric`."""
    flapped = [
        (a, b, metric) if {a, b} == {"g0_0", "g0_1"} else (a, b, m)
        for a, b, m in edges
    ]
    dbs = build_adj_dbs(flapped)
    pub = lsdb_publication([])
    for node in nodes:
        pub.key_vals[adj_key(node)] = Value(
            version, node, serializer.dumps(dbs[node])
        )
    return pub


class TestConvergenceTracePass:
    """Cold ingest + metric increase/decrease/increase flaps through the
    full Decision(tpu)→Fib pipeline, observability asserted end to end."""

    def _run(self):
        edges = grid_edges(4)
        base = lsdb_publication(
            build_adj_dbs(edges).values(), {"g3_3": ["10.0.0.0/24"]}
        )
        # increase → decrease → increase; the last event is an increase so
        # the invalidation_rounds_last gauge reflects a mark fixpoint run
        # (a decrease correctly writes 0 — its inc_idx is empty)
        flaps = [
            _flap_publication(edges, 5, version=2),
            _flap_publication(edges, 1, version=3),
            _flap_publication(edges, 7, version=4),
        ]
        return run(run_convergence_trace("g0_0", [base, *flaps]))

    def test_link_flap_sequence_histograms_and_counters(self):
        monitor, decision, fib = self._run()
        hists = monitor.get_histograms()

        # acceptance: non-zero solve + e2e latency distributions
        solve = hists["decision.spf.solve_ms"]
        assert solve["count"] >= 4
        assert solve["p50"] > 0.0 and solve["p99"] > 0.0
        e2e = hists["convergence.e2e_ms"]
        assert e2e["count"] == 4
        assert e2e["p50"] > 0.0 and e2e["p99"] > 0.0

        # warm vs cold solves distinguishable: one cold ingest, three warm
        # weight-patch flaps
        assert hists["decision.spf.solve_cold_ms"]["count"] >= 1
        assert hists["decision.spf.solve_warm_ms"]["count"] >= 3

        # per-stage histograms populated once per debounced rebuild
        assert hists["decision.debounce_ms"]["count"] == 4
        assert hists["decision.route_build_ms"]["count"] == 4
        assert hists["fib.program_ms"]["count"] == 4

        counters = monitor.get_counters()
        assert counters["decision.spf.incremental_solves"] == 3
        # the increase event ran the boolean invalidation-mark fixpoint
        assert counters["decision.spf.invalidation_rounds_last"] >= 1
        assert counters["decision.spf.rounds_last"] >= 1
        # profiling: traffic crossed the host-device link both ways and
        # the executable cache compiled at least the cold + warm solvers
        assert counters["decision.spf.host_to_device_bytes"] > 0
        assert counters["decision.spf.device_to_host_bytes"] > 0
        assert counters["decision.spf.compile_cache_misses"] >= 1
        assert counters["fib.convergence_spans"] == 4

    def test_span_log_samples_reach_monitor(self):
        monitor, decision, fib = self._run()
        traces = [
            s
            for s in monitor.get_event_logs()
            if s.get("event") == SPAN_EVENT
        ]
        assert len(traces) == 4
        for sample in traces:
            # the full stage chain is present and non-negative
            for stage in (
                "decision.recv_ms",
                "decision.debounce_ms",
                "decision.route_build_ms",
                "fib.recv_ms",
                "fib.program_ms",
                "total_ms",
            ):
                assert sample.get(stage) is not None, stage
                assert sample.get(stage) >= 0.0, stage
            # debounce waited at least roughly the configured minimum
            assert sample.get("total_ms") >= sample.get(
                "decision.debounce_ms"
            )
            # node_name auto-filled by the monitor drain
            assert sample.get("node_name") == "g0_0"


class TestCpuBackendSolveHistogram:
    """The CPU oracle backend reports decision.spf.solve_ms too, so the
    observability surface does not depend on the device backend."""

    def test_cpu_solver_times_spf(self):
        edges = grid_edges(3)
        base = lsdb_publication(
            build_adj_dbs(edges).values(), {"g2_2": ["10.1.0.0/24"]}
        )
        monitor, decision, fib = run(
            run_convergence_trace("g0_0", [base], backend="cpu")
        )
        hists = monitor.get_histograms()
        assert hists["decision.spf.solve_ms"]["count"] >= 1
        assert hists["convergence.e2e_ms"]["count"] == 1
