"""Tier-1 coverage of the project static-analysis suite
(openr_tpu/analysis, docs/Analysis.md).

Three layers:
  - fixture tests per rule family: a positive snippet (the rule fires),
    a negative snippet (the repo's own idioms stay quiet), and a
    suppressed snippet (`# analysis: ignore[...]` works);
  - CLI exit-code contract: `python -m openr_tpu.analysis` (in-process
    main) demonstrably exits non-zero on each family's violation and 0 on
    the shipped tree;
  - the self-run: the whole package is clean at strict level — every
    rule's false-positive budget on real code is zero, pinned here.
"""

import functools
from pathlib import Path

import openr_tpu
from openr_tpu.analysis import (
    ANALYSIS_VERSION,
    RULES,
    build_context,
    get_analysis_info,
    run_analysis,
    run_rules,
)
from openr_tpu.analysis.__main__ import main as analysis_main

PKG = Path(openr_tpu.__file__).resolve().parent
ROOT = PKG.parent


def _write(tmp_path: Path, rel: str, text: str) -> Path:
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)
    return path


def _findings(paths, rule=None, strict=True):
    ctx = build_context([Path(p) for p in paths])
    found, suppressed = run_rules(ctx, strict=strict)
    if rule is not None:
        found = [f for f in found if f.rule == rule]
    return found, suppressed


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

_TRACE_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def branch_on_param(x):
    if x > 0:
        return x
    return -x

def fixpoint(d):
    while jnp.any(d > 0):
        d = d - 1
    return d

solver = jax.jit(fixpoint)

@jax.jit
def host_syncs(x):
    y = np.asarray(x)
    return x.item()

@jax.jit
def bad_carry(x):
    return jax.lax.while_loop(lambda s: s[1], lambda s: s, [x, True])
'''

_TRACE_GOOD = '''
import jax
import jax.numpy as jnp

@jax.jit
def shape_bucketed(x):
    if x.ndim == 2:
        x = x.sum(axis=0)
    n = x.shape[0]
    d = jnp.where(x > 0, x, 0)

    def body(s):
        d, it = s
        return jnp.minimum(d, d * 2), it + 1

    def cond(s):
        return s[1] < n

    d, _ = jax.lax.while_loop(cond, body, (d, 0))
    return d


def host_helper(rows):
    import numpy as np

    if len(rows) > 3:
        return np.asarray(rows)
    return rows
'''

_TRACE_SUPPRESSED = '''
import jax
import jax.numpy as jnp

@jax.jit
def waived(x):
    if x > 0:  # analysis: ignore[trace-safety]
        return x
    return -x
'''


def test_trace_safety_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_trace.py", _TRACE_BAD)
    found, _ = _findings([path], rule="trace-safety")
    checks = sorted(f.check for f in found)
    assert checks.count("python-branch") == 2, found  # param + jnp.any
    assert checks.count("host-sync") == 2, found  # np.asarray + .item()
    assert checks.count("nonstatic-carry") == 1, found  # list carry


def test_trace_safety_negative_on_shape_bucketing_idioms(tmp_path):
    path = _write(tmp_path, "good_trace.py", _TRACE_GOOD)
    found, _ = _findings([path], rule="trace-safety")
    assert found == [], found


def test_trace_safety_suppression(tmp_path):
    path = _write(tmp_path, "waived_trace.py", _TRACE_SUPPRESSED)
    found, suppressed = _findings([path], rule="trace-safety")
    assert found == [] and suppressed == 1


def test_trace_safety_quiet_on_known_good_solver_code():
    """Regression: the warm-start fixpoint (ops/spf.py) and its callers
    are the rule's raison d'etre AND its hardest false-positive test —
    static shape-key branches (`if zero_end`, `if dk <= _UNROLL_MAX`)
    must stay quiet."""
    targets = [
        PKG / "ops" / "spf.py",
        PKG / "solver" / "tpu.py",
        PKG / "parallel" / "mesh.py",
    ]
    found, _ = _findings(targets, rule="trace-safety")
    assert found == [], found


def test_trace_safety_quiet_on_flight_recorder_barrier_seams():
    """Regression (ISSUE 13): the flight recorder's sampled PhaseClock
    takes `block_until_ready` barriers at phase seams in solver/tpu.py
    (h2d/relax/delta_extract) and attributes the lazy mirror fetch in
    the `d` property — all host-side instrumentation OUTSIDE every
    traced function. Neither trace-safety nor device-transfer may flag
    the seams (the solver's transfer accounting still sanctions its
    copies), or sampling would be unshippable."""
    targets = [
        PKG / "solver" / "flight_recorder.py",
        PKG / "solver" / "tpu.py",
        PKG / "ops" / "spf.py",
    ]
    found, _ = _findings(targets)
    blocking = [
        f for f in found if f.rule in ("trace-safety", "device-transfer")
    ]
    assert blocking == [], blocking


def test_trace_safety_cli_exits_nonzero(tmp_path):
    path = _write(tmp_path, "bad_trace.py", _TRACE_BAD)
    assert analysis_main([str(path), "--no-baseline"]) == 1


def test_trace_safety_reaches_delta_extraction_functions():
    """Regression (ISSUE 6): the DeltaPath device-side extraction kernels
    must sit inside the rule's jit-reachability set — a refactor that
    renames a decorator or unhooks the `jax.jit(fn, ...)` factory call
    would otherwise silently drop them from coverage."""
    import ast

    from openr_tpu.analysis.trace_safety import _traced_functions

    tree = ast.parse((PKG / "ops" / "spf.py").read_text())
    traced, direct = _traced_functions(tree)
    traced_names = {fn.name for fn in traced}
    direct_names = {fn.name for fn in direct}
    # direct jit roots: decorated (_delta_extract) or passed to a
    # jax.jit(...) factory call (_bf_warm_core)
    assert {"_delta_extract", "_bf_warm_core"} <= direct_names
    # transitively traced helpers shared by the cold and warm edge-list
    # paths (called by name from traced functions in the same module)
    assert {"_bf_relax", "_bf_allow"} <= traced_names


def test_trace_safety_reaches_tiled_kernels():
    """Regression (ISSUE 9): the destination-tiled shard_map kernels and
    their halo-exchange helpers must sit inside the rule's traced set —
    they run under jit(shard_map(...)) and a Python branch on a traced
    value there would only surface on a real multi-chip mesh."""
    import ast

    from openr_tpu.analysis.trace_safety import _traced_functions

    tree = ast.parse((PKG / "ops" / "spf.py").read_text())
    traced, _ = _traced_functions(tree)
    traced_names = {fn.name for fn in traced}
    assert {
        "_tile_relax",
        "_tile_halo_min",
        "_tile_fold_min",
        "_tile_seg_min",
        "_tile_d0_allow",
    } <= traced_names


def test_trace_safety_reaches_te_grad_functions():
    """Regression (ISSUE 7): the differentiable-TE core must sit inside
    the rule's traced set. The softmin fixpoint and utilization kernels
    are `jax.jit(fn, ...)` factory seeds; the optimizer's objective is
    reachable ONLY through `jax.value_and_grad(_loss_core)` — the
    grad-entry extension this test pins (before it, a host sync added to
    the objective would have sailed past --strict)."""
    import ast

    from openr_tpu.analysis.trace_safety import _traced_functions

    tree = ast.parse((PKG / "te" / "objective.py").read_text())
    traced, direct = _traced_functions(tree)
    direct_names = {fn.name for fn in direct}
    assert {
        "_softmin_fixpoint_core",
        "_soft_utilization_core",
        "_soft_mlu_core",
    } <= direct_names
    # the hard numpy counterparts run host-side and must NOT be traced
    # (np.* calls inside them would otherwise be host-sync findings)
    traced_names = {fn.name for fn in traced}
    assert not {
        "hard_distances", "hard_utilization", "hard_max_util"
    } & traced_names

    tree = ast.parse((PKG / "te" / "optimizer.py").read_text())
    traced, direct = _traced_functions(tree)
    assert "_loss_core" in {fn.name for fn in direct}  # grad seed
    assert "_adam_scan_core" in {fn.name for fn in direct}  # jit factory
    assert "step" in {fn.name for fn in traced}  # nested scan body


def test_trace_safety_flags_host_sync_under_grad():
    """A value_and_grad-reachable function with a numpy host sync must be
    a finding — the seam the te/ traced-set extension exists to close."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def loss(w):\n"
        "    return jnp.sum(np.square(w))\n"
        "grad_fn = jax.value_and_grad(loss)\n"
    )
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as tmp:
        path = Path(tmp) / "grad_sync.py"
        path.write_text(src)
        found, _ = _findings([path], rule="trace-safety")
    assert len(found) == 1
    assert found[0].check == "host-sync"


# ---------------------------------------------------------------------------
# thread-ownership
# ---------------------------------------------------------------------------

_OWNERSHIP_COMMON = '''
def owned_by(owner):
    def mark(obj):
        return obj
    return mark


class CtrlServer:
    def m_poke(self, params):
        return self.decision.poke()

    def m_read(self, params):
        return self.decision.peek()

    def m_deep(self, params):
        return self.kvstore.db(params["area"]).merge(params)
'''

_OWNERSHIP_BAD = _OWNERSHIP_COMMON + '''

@owned_by("decision-loop")
class Decision:
    def __init__(self):
        self.state = 0
        self.waived = 0  # analysis: shared

    def poke(self):
        self.state += 1
        self.waived = 2

    def peek(self):
        return self.state


@owned_by("kvstore-loop")
class KvStoreDb:
    def __init__(self):
        self.key_vals = {}

    def merge(self, params):
        self.key_vals.update(params)
'''

_OWNERSHIP_GOOD = _OWNERSHIP_COMMON + '''

@owned_by("decision-loop")
class Decision:
    def __init__(self):
        self.state = 0

    # analysis: shared — sync, loop-serialized with the owner
    def poke(self):
        self.state += 1

    def peek(self):
        return self.state


@owned_by("kvstore-loop")
class KvStoreDb:
    def __init__(self):
        self.key_vals = {}
        self._lock = None

    def merge(self, params):
        with self._lock:
            self.key_vals.update(params)
'''

_OWNERSHIP_ASYNC_SHARED = _OWNERSHIP_COMMON + '''

@owned_by("decision-loop")
class Decision:
    def __init__(self):
        self.state = 0

    # analysis: shared
    async def poke(self):
        self.state += 1

    def peek(self):
        return self.state
'''

_OWNERSHIP_REBIND = _OWNERSHIP_COMMON + '''

@owned_by("fib-loop")
class Fib:
    def __init__(self):
        self.counters = {}

    def reset_counters(self):
        self.counters = {}
'''


def test_thread_ownership_flags_unowned_mutation(tmp_path):
    path = _write(tmp_path, "bad_own.py", _OWNERSHIP_BAD)
    found, _ = _findings([path], rule="thread-ownership")
    checks = [f.check for f in found]
    # Decision.poke mutates self.state; KvStoreDb.merge (reached through
    # the chained self.kvstore.db(...).merge receiver) mutates key_vals;
    # the '# analysis: shared' __init__ attr is exempt
    assert checks.count("unowned-mutation") == 2, found
    assert all("waived" not in f.message for f in found)


def test_thread_ownership_shared_and_lock_handovers(tmp_path):
    path = _write(tmp_path, "good_own.py", _OWNERSHIP_GOOD)
    found, _ = _findings([path], rule="thread-ownership")
    assert found == [], found


def test_thread_ownership_async_shared_is_flagged(tmp_path):
    path = _write(tmp_path, "async_own.py", _OWNERSHIP_ASYNC_SHARED)
    found, _ = _findings([path], rule="thread-ownership")
    assert [f.check for f in found] == ["async-shared"], found


def test_thread_ownership_monitor_rebind(tmp_path):
    path = _write(tmp_path, "rebind_own.py", _OWNERSHIP_REBIND)
    found, _ = _findings([path], rule="thread-ownership")
    assert [f.check for f in found] == ["monitor-rebind"], found


_OWNERSHIP_QUEUE = '''
def owned_by(owner):
    def mark(obj):
        return obj
    return mark


class CtrlServer:
    def m_sub(self, params):
        return self.stream_manager.add_kvstore_subscriber()

    def m_unsub(self, params):
        return self.stream_manager.remove_subscriber(params["sub"])

    def m_push(self, params):
        return self.stream_manager.enqueue_async(params)


@owned_by("ctrl-loop")
class StreamManager:
    def __init__(self):
        self._subs = []  # analysis: queue
        self.other = 0

    def add_kvstore_subscriber(self):
        self._subs.append(object())  # sanctioned: sync enqueue seam
        return self._subs[-1]

    def remove_subscriber(self, sub):
        self._subs.remove(sub)  # sanctioned (same handover)
        self.other += 1  # NOT the queue attr: still flagged

    async def enqueue_async(self, params):
        self._subs.append(params)  # async entry: NOT sanctioned
'''


def test_thread_ownership_queue_handover(tmp_path):
    """The subscriber-queue handover (docs/Streaming.md): mutations of
    `# analysis: queue` attributes from SYNC ctrl-reachable methods are
    the sanctioned publisher-side enqueue seam; the marker is
    per-attribute (unlike '# analysis: shared' it does not waive the
    rest of the method), and an async enqueue is still flagged."""
    path = _write(tmp_path, "queue_own.py", _OWNERSHIP_QUEUE)
    found, _ = _findings([path], rule="thread-ownership")
    checks = sorted(f.check for f in found)
    assert checks == ["async-enqueue", "unowned-mutation"], found
    by_check = {f.check: f for f in found}
    assert "self.other" in by_check["unowned-mutation"].message
    assert "enqueue_async" in by_check["async-enqueue"].message
    assert "_subs" in by_check["async-enqueue"].message


def test_thread_ownership_queue_handover_on_shipped_stream_manager():
    """The real StreamManager's add/remove/enqueue methods ride the
    queue handover (no blanket '# analysis: shared' waivers) and must
    stay quiet — pinned directly, not only via the package self-run.
    The ctrl server file is included so the external surface contains
    the subscriber-registry method names."""
    targets = [
        PKG / "streaming" / "subscription.py",
        PKG / "ctrl" / "server.py",
    ]
    found, _ = _findings(targets, rule="thread-ownership")
    assert found == [], found


def test_thread_ownership_is_advisory_unless_strict(tmp_path):
    path = _write(tmp_path, "bad_own.py", _OWNERSHIP_BAD)
    # advisory by default: CLI exits 0 ... but --strict promotes to error
    assert analysis_main([str(path), "--no-baseline"]) == 0
    assert analysis_main([str(path), "--no-baseline", "--strict"]) == 1


def test_analysis_strict_env_toggle(tmp_path, monkeypatch):
    path = _write(tmp_path, "bad_own.py", _OWNERSHIP_BAD)
    monkeypatch.setenv("ANALYSIS_STRICT", "1")
    assert analysis_main([str(path), "--no-baseline"]) == 1
    monkeypatch.setenv("ANALYSIS_STRICT", "0")
    assert analysis_main([str(path), "--no-baseline"]) == 0


# ---------------------------------------------------------------------------
# blocking-call
# ---------------------------------------------------------------------------

_BLOCKING_BAD = '''
import time
import subprocess


async def loop_body(fut, sock):
    time.sleep(1.0)
    fut.result()
    data = sock.recv(1024)
    subprocess.run(["true"])
    return data
'''

_BLOCKING_GOOD = '''
import asyncio
import time


async def loop_body(fut, reader):
    await asyncio.sleep(1.0)
    fut.result(timeout=5.0)
    return await reader.readline()


def host_side():
    time.sleep(0.1)  # sync helper, not event-loop code
'''

_BLOCKING_SUPPRESSED = '''
import time


async def loop_body():
    time.sleep(0.001)  # analysis: ignore[blocking-call]
'''


def test_blocking_call_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_block.py", _BLOCKING_BAD)
    found, _ = _findings([path], rule="blocking-call")
    checks = sorted(f.check for f in found)
    assert checks == [
        "blocking-socket",
        "blocking-subprocess",
        "time-sleep",
        "undeadlined-result",
    ], found


def test_blocking_call_negative(tmp_path):
    path = _write(tmp_path, "good_block.py", _BLOCKING_GOOD)
    found, _ = _findings([path], rule="blocking-call")
    assert found == [], found


def test_blocking_call_suppression(tmp_path):
    path = _write(tmp_path, "waived_block.py", _BLOCKING_SUPPRESSED)
    found, suppressed = _findings([path], rule="blocking-call")
    assert found == [] and suppressed == 1


def test_blocking_call_cli_exits_nonzero(tmp_path):
    path = _write(tmp_path, "bad_block.py", _BLOCKING_BAD)
    assert analysis_main([str(path), "--no-baseline"]) == 1


# ---------------------------------------------------------------------------
# registry-drift
# ---------------------------------------------------------------------------

_DRIFT_MONITORING = """# Monitoring

## Counters

| counter | meaning |
|---|---|
| `fib.good_counter` | emitted and documented |
| `fib.ghost_counter` | documented but never emitted |
| `fib.family.*` | wildcard family |

## Histograms

| histogram | stage |
|---|---|
| `fib.work_ms` | emitted and documented |

## Event logs

| event | emitted by |
|---|---|
| `GOOD_TRACE` | emitted and documented |
| `PHANTOM_EVENT` | documented but never emitted |
| `BRACE_{UP,DOWN}` | brace family, UP emitted below |

## Exporter

| exporter metric | meaning |
|---|---|
| `monitor.good_metric` | emitted and documented |
| `monitor.phantom_metric` | documented but never emitted |
"""

_DRIFT_ROBUSTNESS = """# Robustness

| fault point | seam | module |
|---|---|---|
| `fib.io` | declared and documented | mod.py |
| `fib.phantom` | documented but not declared | mod.py |
"""

_DRIFT_CODE = '''
GOOD_EVENT = "GOOD_TRACE"


def fault_point(name, ctx=None):
    pass


class CountersMixin:
    pass


class Widget(CountersMixin):
    def work(self):
        self._bump("fib.good_counter")
        self._bump("fib.family.alpha")
        self._bump("monitor.good_metric")
        self._bump("monitor.rogue_metric")
        self._bump("not a counter name")
        self._observe("fib.work_ms", 1.0)
        self._observe("fib.secret_ms", 1.0)
        self._observe("fib.bad_unit", 1.0)
        fault_point("fib.io")
        fault_point("fib.rogue")

    def emit(self, sample):
        sample.add_string("event", GOOD_EVENT)
        sample.add_string("event", "ROGUE_EVENT")
        self._emit_sample("BRACE_UP", {}, {})
'''

_DRIFT_CONFIG = '''
class DecisionConfigSection:
    documented_knob: int = 1
    mystery_knob: int = 2
'''

_DRIFT_DOC_KNOBS = """# Decision

The `documented_knob` knob is documented here.
"""


def _drift_tree(tmp_path: Path) -> Path:
    root = tmp_path / "proj"
    _write(root, "docs/Monitoring.md", _DRIFT_MONITORING)
    _write(root, "docs/Robustness.md", _DRIFT_ROBUSTNESS)
    _write(root, "docs/Decision.md", _DRIFT_DOC_KNOBS)
    _write(root, "pkg/mod.py", _DRIFT_CODE)
    _write(root, "pkg/config.py", _DRIFT_CONFIG)
    # presence of monitor/monitor.py marks the scan as whole-package,
    # which is what arms the doc cross-checks (docs/Analysis.md)
    _write(root, "pkg/monitor/monitor.py", "")
    return root


def test_registry_drift_fixture_violations(tmp_path):
    root = _drift_tree(tmp_path)
    ctx = build_context([root / "pkg"], root=root)
    assert ctx.full_package and ctx.docs_dir is not None
    found = [
        f
        for f in RULES["registry-drift"].run(ctx)
        if f.rule == "registry-drift"
    ]
    by_check = {}
    for f in found:
        by_check.setdefault(f.check, []).append(f.message)
    assert any(
        "not a counter name" in m for m in by_check["counter-name"]
    ), found
    assert any("fib.bad_unit" in m for m in by_check["histogram-unit"])
    assert any("fib.ghost_counter" in m for m in by_check["doc-ghost"])
    undocumented = by_check["undocumented-histogram"]
    assert any("fib.secret_ms" in m for m in undocumented)
    assert any(
        "fib.rogue" in m for m in by_check["undocumented-fault-point"]
    )
    assert any(
        "fib.phantom" in m for m in by_check["ghost-fault-point"]
    )
    assert any(
        "mystery_knob" in m for m in by_check["undocumented-config-knob"]
    )
    assert not any(
        "documented_knob" in m
        for m in by_check["undocumented-config-knob"]
    )
    # LogSample event catalog, both directions: a literal AND a
    # module-constant emission must resolve; brace rows expand
    assert any("ROGUE_EVENT" in m for m in by_check["undocumented-event"])
    assert any("PHANTOM_EVENT" in m for m in by_check["ghost-event"])
    assert any("BRACE_DOWN" in m for m in by_check["ghost-event"])
    # the exporter-metric table (monitor.* namespace), both directions:
    # emitted-but-undocumented and documented-but-never-emitted
    assert any(
        "monitor.rogue_metric" in m
        for m in by_check["undocumented-metric"]
    )
    assert any(
        "monitor.phantom_metric" in m for m in by_check["ghost-metric"]
    )
    # the consistent names stay quiet
    joined = " ".join(m for ms in by_check.values() for m in ms)
    assert "fib.good_counter" not in joined
    assert "'monitor.good_metric'" not in joined
    assert "'fib.work_ms'" not in joined
    assert "'fib.io'" not in joined
    assert "GOOD_TRACE" not in joined
    assert "'BRACE_UP'" not in joined


def test_registry_drift_doc_checks_skip_partial_scans(tmp_path):
    """A single-file scan must not report the unscanned rest of the tree
    as ghosts — doc cross-checks only arm on whole-package scans."""
    root = _drift_tree(tmp_path)
    ctx = build_context([root / "pkg" / "mod.py"], root=root)
    assert not ctx.full_package
    checks = {f.check for f in RULES["registry-drift"].run(ctx)}
    assert "doc-ghost" not in checks and "ghost-fault-point" not in checks
    # naming-convention checks still run
    assert "counter-name" in checks


def test_registry_drift_cli_exits_nonzero(tmp_path):
    root = _drift_tree(tmp_path)
    assert analysis_main([str(root / "pkg"), "--no-baseline"]) == 1


# ---------------------------------------------------------------------------
# baseline + self-run + metadata
# ---------------------------------------------------------------------------


def test_baseline_waives_findings(tmp_path):
    path = _write(tmp_path, "bad_block.py", _BLOCKING_BAD)
    result = run_analysis([path])
    assert result["exit_code"] == 1
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "# waived for the test\n"
        + "\n".join(f.key() for f in result["findings"])
        + "\n"
    )
    waived = run_analysis([path], baseline_path=baseline)
    assert waived["exit_code"] == 0
    assert waived["baselined"] == len(result["findings"])


@functools.lru_cache(maxsize=1)
def _package_result():
    return run_analysis(
        [PKG],
        strict=True,
        baseline_path=ROOT / "analysis-baseline.txt",
    )


def test_self_run_shipped_tree_is_clean_strict():
    """The acceptance gate: `python -m openr_tpu.analysis openr_tpu/`
    exits 0 on the shipped tree, with zero waivers consumed, even with
    advisory rules promoted."""
    result = _package_result()
    assert result["exit_code"] == 0, result["findings"]
    assert result["findings"] == [], result["findings"]
    assert result["baselined"] == 0  # the shipped baseline is empty
    assert result["files"] > 80  # the walk really saw the package


def test_self_run_covers_all_rule_families():
    result = _package_result()
    assert set(result["rules"]) == {
        "trace-safety",
        "thread-ownership",
        "blocking-call",
        "registry-drift",
        "device-transfer",
        "recompile-risk",
        "shard-spec",
        "shape-mismatch",
        "sentinel-overflow",
        "dtype-promotion",
        "collective-conformance",
        "resident-accounting",
    }


def test_cli_self_run_exits_zero():
    rc = analysis_main(
        [str(PKG), "--baseline", str(ROOT / "analysis-baseline.txt")]
    )
    assert rc == 0


def test_analysis_metadata_surfaces_through_build_info():
    from openr_tpu.utils.build_info import get_build_info

    info = get_build_info()
    assert info["build_analysis_version"] == ANALYSIS_VERSION
    rules = info["build_analysis_rules"].split(",")
    assert set(rules) == set(get_analysis_info()["analysis_rules"])
    assert analysis_main(["--list-rules"]) == 0
    assert analysis_main(["--version"]) == 0


# ---------------------------------------------------------------------------
# DeepFlow (v2.0): callgraph + dataflow infrastructure
# ---------------------------------------------------------------------------

_XMOD_HELPER = '''
import numpy as np


def helper(x):
    return np.asarray(x)  # analysis: ignore[trace-safety] — fixture waiver
'''

_XMOD_ENTRY = '''
import jax
import jax.numpy as jnp

from mod_b import helper


@jax.jit
def entry(x):
    return helper(x) + jnp.sum(x)
'''


def _strip_waivers(src: str) -> str:
    import re

    return re.sub(r"\s*# analysis: ignore\[[a-z-]+\][^\n]*", "", src)


def test_cross_module_reachability_fixture(tmp_path):
    """ISSUE 8 acceptance: a host sync in a helper that is only traced
    THROUGH an import (mod_a jits entry -> entry calls mod_b.helper) is a
    finding — and the file's waiver is the only thing keeping it quiet."""
    _write(tmp_path, "mod_b.py", _XMOD_HELPER)
    _write(tmp_path, "mod_a.py", _XMOD_ENTRY)
    found, suppressed = _findings([tmp_path], rule="trace-safety")
    assert found == [] and suppressed == 1
    # remove the suppression: strict analysis fails on the helper's module
    _write(tmp_path, "mod_b.py", _strip_waivers(_XMOD_HELPER))
    assert (
        analysis_main([str(tmp_path), "--no-baseline", "--strict"]) == 1
    )
    found, _ = _findings([tmp_path], rule="trace-safety")
    assert len(found) == 1 and found[0].check == "host-sync"
    assert found[0].path.endswith("mod_b.py")


def test_traced_set_spans_modules_and_excludes_numpy_counterparts():
    """The package-level traced set (callgraph closure) keeps the
    DeltaPath extraction kernels and the TE softmin core in, and the hard
    numpy counterparts out — the ISSUE 8 pin, now at whole-package scope
    (the per-module pins above would miss a cross-module unhooking)."""
    from openr_tpu.analysis import build_context
    from openr_tpu.analysis.trace_safety import traced_function_infos

    ctx = build_context([PKG])
    traced, direct = traced_function_infos(ctx)
    names = {(fi.module, fi.name) for fi in traced}
    assert ("openr_tpu.ops.spf", "_delta_extract") in names
    assert ("openr_tpu.ops.spf", "_bf_warm_core") in names
    assert ("openr_tpu.te.objective", "_softmin_fixpoint_core") in names
    assert ("openr_tpu.te.objective", "_soft_utilization_core") in names
    assert ("openr_tpu.te.optimizer", "_loss_core") in names
    for host_side in ("hard_distances", "hard_utilization", "hard_max_util"):
        assert ("openr_tpu.te.objective", host_side) not in names
    assert ("openr_tpu.solver.tpu", "prefetch_ksp") not in names
    direct_names = {(fi.module, fi.name) for fi in direct}
    assert ("openr_tpu.ops.spf", "_delta_extract") in direct_names


def test_callgraph_classifies_solver_producers():
    """Device-producer classification drives device-transfer: the jit
    bindings, the factories returning jit callables, and the functions
    whose return value flows out of one must all classify."""
    from openr_tpu.analysis import build_context
    from openr_tpu.analysis.callgraph import build_callgraph

    ctx = build_context([PKG])
    cg = build_callgraph(ctx)
    spf = cg.modules["openr_tpu.ops.spf"]
    assert "_delta_extract" in spf.jit_bindings
    assert "_bf_fixpoint" in spf.jit_bindings
    assert "_sell_solver_warm" in spf.factories
    assert "_sell_solver" in spf.factories
    assert "batched_spf" in spf.device_fns
    opt = cg.modules["openr_tpu.te.optimizer"]
    assert "_adam_solver" in opt.jit_bindings


# ---------------------------------------------------------------------------
# thread-ownership: alias + escape awareness (the ROADMAP example)
# ---------------------------------------------------------------------------

_ALIAS_BAD = _OWNERSHIP_COMMON + '''

@owned_by("decision-loop")
class Decision:
    def __init__(self):
        self.x = {}
        self.q = None

    def poke(self):
        d = self.x
        d["k"] = 1  # analysis: ignore[thread-ownership] — fixture waiver

    def peek(self):
        row = self.x
        return dict(row)
'''

_ESCAPE_BAD = _OWNERSHIP_COMMON + '''

@owned_by("decision-loop")
class Decision:
    def __init__(self):
        self.x = {}
        self.q = None

    def poke(self):
        self.q.put(self.x)

    def peek(self):
        return self.x
'''


def test_thread_ownership_alias_chain_regression(tmp_path):
    """The ROADMAP carry-over verbatim: `d = self.x; d[k] = v` inside a
    ctrl-reachable method of an @owned_by class is a finding, with the
    alias chain in the message — and fails strict once unwaived."""
    path = _write(tmp_path, "alias_own.py", _ALIAS_BAD)
    found, suppressed = _findings([path], rule="thread-ownership")
    assert found == [] and suppressed == 1
    # peek's `row = self.x; dict(row)` is a read through an alias: quiet
    path = _write(
        tmp_path, "alias_own.py", _strip_waivers(_ALIAS_BAD)
    )
    found, _ = _findings([path], rule="thread-ownership")
    assert [f.check for f in found] == ["aliased-mutation"], found
    assert "d = self.x" in found[0].message
    assert "d[...]" in found[0].message
    assert (
        analysis_main([str(path), "--no-baseline", "--strict"]) == 1
    )
    assert analysis_main([str(path), "--no-baseline"]) == 0  # advisory


def test_thread_ownership_escape_to_queue(tmp_path):
    path = _write(tmp_path, "escape_own.py", _ESCAPE_BAD)
    found, _ = _findings([path], rule="thread-ownership")
    assert [f.check for f in found] == ["escaped-state"], found
    assert "queue" in found[0].message
    # returning owned state from a sync handler is the ctrl API: quiet
    assert not any("peek" in f.message for f in found)


# ---------------------------------------------------------------------------
# device-transfer
# ---------------------------------------------------------------------------

_DEVICE_BAD = '''
import jax
import numpy as np


@jax.jit
def _solve_core(x):
    return x


def consume(x):
    d = _solve_core(x)
    out = np.asarray(d)
    for row in d:
        pass
    return float(d[0])
'''

_DEVICE_GOOD = '''
import jax
import numpy as np


@jax.jit
def _solve_core(x):
    return x


class Holder:
    def fetch(self, x):
        d = _solve_core(x)
        out = np.asarray(d)
        self.d2h_bytes += out.nbytes  # sanctioned seam, by construction
        return out


def scalar_read(x):
    d = _solve_core(x)
    return int(d[0])  # int() is the sanctioned 4-byte scalar read


def host_only(rows):
    return np.asarray(rows)  # no device flow: plain host numpy
'''

_DELTA_PATH_SYNC = '''
import jax
import numpy as np


@jax.jit
def _delta_extract_fixture(mask, d):
    return mask, d


def poll_delta(mask, d):
    cols, dcols = _delta_extract_fixture(mask, d)
    out = np.asarray(dcols)  # analysis: ignore[device-transfer] — fixture
    return out
'''


def test_device_transfer_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_dev.py", _DEVICE_BAD)
    found, _ = _findings([path], rule="device-transfer")
    checks = sorted(f.check for f in found)
    assert checks == [
        "device-iteration", "host-sync", "host-sync",
    ], found
    assert any("d = _solve_core(...)" in f.message for f in found)


def test_device_transfer_sanctioned_seams_stay_quiet(tmp_path):
    path = _write(tmp_path, "good_dev.py", _DEVICE_GOOD)
    found, _ = _findings([path], rule="device-transfer")
    assert found == [], found


def test_device_transfer_host_sync_in_delta_path_fixture(tmp_path):
    """ISSUE 8 acceptance: the DeltaPath shape — unpack a compacted
    extraction, np.asarray the columns WITHOUT accounting — fails strict
    analysis the moment its waiver is removed."""
    path = _write(tmp_path, "delta_sync.py", _DELTA_PATH_SYNC)
    found, suppressed = _findings([path], rule="device-transfer")
    assert found == [] and suppressed == 1
    path = _write(
        tmp_path, "delta_sync.py", _strip_waivers(_DELTA_PATH_SYNC)
    )
    assert (
        analysis_main([str(path), "--no-baseline", "--strict"]) == 1
    )
    found, _ = _findings([path], rule="device-transfer")
    assert len(found) == 1 and found[0].check == "host-sync"
    assert "dcols" in found[0].message


def test_device_transfer_quiet_on_shipped_solver_consumers():
    """The real DeltaPath seams (_AreaSolve.d mirror fetch,
    _finish_delta's compacted extraction, the KSP/audit fetches) account
    their bytes and must stay quiet — pinned directly, not only via the
    package self-run."""
    targets = [PKG / "solver" / "tpu.py", PKG / "te" / "optimizer.py"]
    found, _ = _findings(targets, rule="device-transfer")
    assert found == [], found


_DEVICE_ATTR_PRODUCERS = '''
import jax
import numpy as np


@jax.jit
def solve(x):
    return x


class Holder:
    def __init__(self):
        self._d_dev = None

    def fill(self):
        # tuple-unpacked store: BOTH attributes become device-tagged
        self._d_dev, self.rounds_last = self._resident(1)

    def _resident(self, x):
        # device-returning METHOD: self._resident(...) call sites are
        # producers after the per-class fixpoint
        return solve(x), 0

    def bad_attr_consumer(self):
        return np.asarray(self._d_dev)

    def bad_method_consumer(self):
        d = self._resident(2)
        return float(d)

    def accounted_consumer(self):
        out = np.asarray(self._d_dev)
        self.d2h_bytes = out.nbytes
        return out

    def host_attr_is_untainted(self):
        # a host copy breaks the taint: storing it makes a HOST attr
        self._d_host = np.array([1, 2])
        return float(self._d_host[0])
'''


def test_device_transfer_tracks_attribute_and_method_producers(tmp_path):
    """The ROADMAP analysis carry-over: `self._d_dev`-style producers
    are covered by dataflow — an attribute stored from a device value
    (through a method-return, through tuple unpacking) taints its loads
    in EVERY method of the class; consumers that account `*d2h*` bytes
    stay sanctioned; host-copied attributes stay untainted."""
    path = _write(tmp_path, "attr_dev.py", _DEVICE_ATTR_PRODUCERS)
    found, _ = _findings([path], rule="device-transfer")
    by_line = {f.line: f for f in found}
    assert sorted(f.check for f in found) == ["host-sync", "host-sync"], (
        found
    )
    messages = " | ".join(f.message for f in found)
    assert "bad_attr_consumer" in messages
    assert "self._d_dev" in messages
    assert "bad_method_consumer" in messages
    assert "accounted_consumer" not in messages
    assert "host_attr_is_untainted" not in messages
    assert by_line  # anchored to real lines


def test_device_transfer_attr_producer_cli_exits_nonzero(tmp_path):
    path = _write(tmp_path, "attr_dev.py", _DEVICE_ATTR_PRODUCERS)
    assert analysis_main([str(path), "--no-baseline", "--strict"]) == 1


# ---------------------------------------------------------------------------
# recompile-risk
# ---------------------------------------------------------------------------

_RECOMPILE_BAD = '''
import jax


def _core(x, cap):
    return x


solver = jax.jit(_core, static_argnames=("cap",))


def dispatch(x):
    solver(x, cap=len(x))
    solver(x, len(x) + 1)
'''

_RECOMPILE_GOOD = '''
import jax


def _next_bucket(n, minimum=8):
    return max(n, minimum)


def _core(x, cap):
    return x


solver = jax.jit(_core, static_argnames=("cap",))


def dispatch(x, cfg):
    cap = _next_bucket(len(x))
    solver(x, cap=cap)
    solver(x, cap=cfg.cap)
    solver(x, cap=min(len(x), 128))
    solver(x, cap=8)
'''

_RECOMPILE_SUPPRESSED = '''
import jax


def _core(x, cap):
    return x


solver = jax.jit(_core, static_argnames=("cap",))


def dispatch(x):
    solver(x, cap=len(x))  # analysis: ignore[recompile-risk]
'''


def test_recompile_risk_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_rc.py", _RECOMPILE_BAD)
    found, _ = _findings([path], rule="recompile-risk")
    checks = [f.check for f in found]
    assert checks == ["unbucketed-static", "unbucketed-static"], found
    # keyword form names the arg, positional form names the position
    assert any("'cap'" in f.message for f in found)
    assert any("#1" in f.message for f in found)


def test_recompile_risk_bucketing_idioms_stay_quiet(tmp_path):
    path = _write(tmp_path, "good_rc.py", _RECOMPILE_GOOD)
    found, _ = _findings([path], rule="recompile-risk")
    assert found == [], found


def test_recompile_risk_suppression_and_severity(tmp_path):
    path = _write(tmp_path, "waived_rc.py", _RECOMPILE_SUPPRESSED)
    found, suppressed = _findings([path], rule="recompile-risk")
    assert found == [] and suppressed == 1
    bad = _write(tmp_path, "bad_rc.py", _RECOMPILE_BAD)
    assert analysis_main([str(bad), "--no-baseline"]) == 0  # advisory
    assert analysis_main([str(bad), "--no-baseline", "--strict"]) == 1


def test_recompile_risk_quiet_on_shipped_dispatchers():
    """_delta_extract's `cap` (bucketed), _adam_solver's n/rounds/steps
    (config + clamps): the repo's own static-arg call sites are the
    hardest negative fixtures."""
    targets = [
        PKG / "solver" / "tpu.py",
        PKG / "te" / "optimizer.py",
        PKG / "te" / "service.py",
    ]
    found, _ = _findings(targets, rule="recompile-risk")
    assert found == [], found


# ---------------------------------------------------------------------------
# shard-spec
# ---------------------------------------------------------------------------

_SHARD_BAD = '''
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return Mesh(np.array(devices).reshape(shape), axis_names)


def solve(a, b, c):
    return a, b


def build(mesh):
    row = NamedSharding(mesh, P("batchs"))
    n = mesh.shape["grap"]
    return jax.jit(
        solve,
        in_shardings=(row, row),
        out_shardings=(row, row, row),
    )
'''

_SHARD_GOOD = '''
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return Mesh(np.array(devices).reshape(shape), axis_names)


def solve(a, b, c):
    return a, b


def factory():
    def inner(a, b):
        return a

    return jax.jit(inner)


def build(mesh, shardings):
    row = NamedSharding(mesh, P("batch", None))
    n = mesh.shape["batch"]
    jax.jit(solve, in_shardings=(row, row, row), out_shardings=(row, row))
    # computed specs are skipped, not guessed at
    jax.jit(solve, in_shardings=shardings + (row,))
    return n
'''


def test_shard_spec_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_shard.py", _SHARD_BAD)
    found, _ = _findings([path], rule="shard-spec")
    checks = sorted(f.check for f in found)
    assert checks == [
        "spec-arity",
        "spec-arity",
        "unknown-mesh-axis",
        "unknown-mesh-axis",
    ], found
    msgs = " | ".join(f.message for f in found)
    assert "'batchs'" in msgs and "'grap'" in msgs
    assert "2 entries" in msgs and "3 entries" in msgs


_SHARD_WAIVED = _SHARD_BAD.replace(
    "    row = NamedSharding",
    "    # analysis: ignore[shard-spec]\n    row = NamedSharding",
).replace(
    "    n = mesh.shape",
    "    # analysis: ignore[shard-spec]\n    n = mesh.shape",
).replace(
    "    return jax.jit(",
    "    return jax.jit(  # analysis: ignore[shard-spec]",
)


def test_shard_spec_negative_and_suppression(tmp_path):
    path = _write(tmp_path, "good_shard.py", _SHARD_GOOD)
    found, _ = _findings([path], rule="shard-spec")
    assert found == [], found
    path = _write(tmp_path, "waived_shard.py", _SHARD_WAIVED)
    found, suppressed = _findings([path], rule="shard-spec")
    assert found == [] and suppressed == 4


def test_shard_spec_axis_check_disarms_without_vocabulary(tmp_path):
    """A consumer module using P('batch') with no make_mesh/Mesh literal
    in scope cannot be judged — the axis check must disarm, not guess."""
    src = (
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "def build(mesh):\n"
        "    return NamedSharding(mesh, P('anything'))\n"
    )
    path = _write(tmp_path, "consumer.py", src)
    found, _ = _findings([path], rule="shard-spec")
    assert found == [], found


def test_shard_spec_quiet_on_shipped_mesh_code():
    targets = [
        PKG / "parallel" / "mesh.py",
        PKG / "ops" / "spf.py",
        PKG / "te" / "optimizer.py",
    ]
    found, _ = _findings(targets, rule="shard-spec")
    assert found == [], found


# ---------------------------------------------------------------------------
# --changed selection, --update-baseline, stale-baseline errors
# ---------------------------------------------------------------------------


def test_changed_closure_selects_dependents(tmp_path):
    pkg = tmp_path / "pkg"
    _write(pkg, "mod_b.py", "def helper(x):\n    return x\n")
    _write(
        pkg,
        "mod_a.py",
        "from mod_b import helper\n\ndef entry(x):\n"
        "    return helper(x)\n",
    )
    _write(pkg, "mod_c.py", "def unrelated():\n    return 1\n")
    from openr_tpu.analysis.__main__ import changed_closure

    selected = changed_closure(pkg, ["pkg/mod_b.py"], tmp_path)
    rels = sorted(p.name for p in selected)
    assert rels == ["mod_a.py", "mod_b.py"]  # dependent pulled in, c not
    assert changed_closure(pkg, ["pkg/nothing.py"], tmp_path) == []


def _scratch_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    _write(pkg, "mod_b.py", "def helper(x):\n    return x\n")
    _write(
        pkg,
        "mod_a.py",
        "from mod_b import helper\n\ndef entry(x):\n"
        "    return helper(x)\n",
    )
    _write(pkg, "mod_c.py", "def unrelated():\n    return 1\n")
    return pkg


def test_changed_closure_cache_hit_miss(tmp_path):
    """The persistent import-graph cache: first run parses everything,
    the second is pure hash hits, an edit re-parses exactly that file —
    and the cached closure always equals the uncached reference."""
    from openr_tpu.analysis.__main__ import changed_closure
    from openr_tpu.analysis.cache import changed_closure_cached

    pkg = _scratch_pkg(tmp_path)
    cache = tmp_path / "cache.json"
    sel1, s1 = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert s1 == {"hits": 0, "misses": 3, "files": 3}
    assert sorted(p.name for p in sel1) == ["mod_a.py", "mod_b.py"]
    assert cache.exists()
    sel2, s2 = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert s2 == {"hits": 3, "misses": 0, "files": 3}
    assert sel2 == sel1
    # the cached closure is pinned to the uncached reference
    ref = changed_closure(pkg, ["pkg/mod_b.py"], tmp_path)
    assert sorted(map(str, sel2)) == sorted(map(str, ref))
    # an edit re-parses only the touched file, and a NEW dependency edge
    # (c now imports b) changes the closure through the refreshed entry
    _write(
        pkg,
        "mod_c.py",
        "from mod_b import helper\n\ndef unrelated():\n"
        "    return helper(1)\n",
    )
    sel3, s3 = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert s3 == {"hits": 2, "misses": 1, "files": 3}
    assert sorted(p.name for p in sel3) == [
        "mod_a.py", "mod_b.py", "mod_c.py",
    ]
    # untouched / unknown files select nothing, stats still returned
    sel4, _ = changed_closure_cached(pkg, ["pkg/nothing.py"], tmp_path, cache)
    assert sel4 == []


def test_changed_closure_cache_survives_corruption(tmp_path):
    from openr_tpu.analysis.cache import changed_closure_cached

    pkg = _scratch_pkg(tmp_path)
    cache = tmp_path / "cache.json"
    cache.write_text("{not json")
    sel, stats = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert stats["misses"] == 3  # wholesale re-parse, no crash
    assert sorted(p.name for p in sel) == ["mod_a.py", "mod_b.py"]
    # a version bump invalidates entries wholesale
    import json

    payload = json.loads(cache.read_text())
    payload["version"] = -1
    cache.write_text(json.dumps(payload))
    _, stats = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert stats["misses"] == 3


def test_changed_closure_cached_matches_uncached_on_package(tmp_path):
    """Parity on the real package: the cached closure (content-hash import
    graph) and the uncached reference (full CallGraph) must select the
    same module set for a hot ops-layer edit."""
    from openr_tpu.analysis.__main__ import changed_closure
    from openr_tpu.analysis.cache import changed_closure_cached

    root = PKG.parent
    changed = ["openr_tpu/ops/graph.py"]
    cache = tmp_path / "cache.json"  # fresh: every module parses once
    sel_cached, stats = changed_closure_cached(PKG, changed, root, cache)
    sel_ref = changed_closure(PKG, changed, root)
    assert sorted(map(str, sel_cached)) == sorted(map(str, sel_ref))
    assert stats["files"] == stats["misses"]
    assert len(sel_cached) > 5  # the ops layer has real dependents


def test_git_changed_files_in_scratch_repo(tmp_path):
    import subprocess

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=repo, check=True, capture_output=True
        )

    git("init", "-b", "main")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    _write(repo, "a.py", "x = 1\n")
    git("add", "-A")
    git("commit", "-m", "seed")
    git("checkout", "-b", "feature")
    _write(repo, "a.py", "x = 2\n")
    git("commit", "-am", "edit")
    _write(repo, "b.py", "y = 1\n")  # untracked counts too
    from openr_tpu.analysis.__main__ import _git_changed_files

    changed = _git_changed_files(repo)
    assert changed is not None and set(changed) == {"a.py", "b.py"}


def test_update_baseline_round_trip(tmp_path):
    path = _write(tmp_path, "bad_block.py", _BLOCKING_BAD)
    baseline = tmp_path / "baseline.txt"
    assert analysis_main([str(path), "--no-baseline"]) == 1
    rc = analysis_main(
        [str(path), "--update-baseline", "--baseline", str(baseline)]
    )
    assert rc == 0 and baseline.exists()
    body = baseline.read_text()
    assert "blocking-call\t" in body and body.startswith("#")
    # the rewritten baseline waives exactly the current findings
    assert (
        analysis_main([str(path), "--baseline", str(baseline)]) == 0
    )


def test_stale_baseline_entry_is_an_error(tmp_path):
    """ISSUE 8 acceptance: a waived key no rule produces anymore fails
    the (full-package) run — a stale waiver could shadow a future
    regression with the same key."""
    pkg = tmp_path / "pkg"
    _write(pkg, "clean.py", "def f():\n    return 1\n")
    # monitor/monitor.py marks the scan as full-package (core.py), which
    # is what arms the stale check — partial scans cannot judge staleness
    _write(pkg, "monitor/monitor.py", "")
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "blocking-call\tpkg/clean.py\tsome finding long since fixed\n"
    )
    result = run_analysis([pkg], baseline_path=baseline)
    assert result["exit_code"] == 1
    stale = [f for f in result["findings"] if f.check == "stale-entry"]
    assert len(stale) == 1 and "blocking-call" in stale[0].message
    # the same baseline against a partial scan is not judged
    partial = run_analysis([pkg / "clean.py"], baseline_path=baseline)
    assert partial["exit_code"] == 0


# ---------------------------------------------------------------------------
# registry-drift: the rule table itself
# ---------------------------------------------------------------------------

_RULE_TABLE_DOC = """# Analysis

| rule | severity | invariant |
|---|---|---|
| `trace-safety` | error | documented |
| `bogus-rule` | error | documented but never registered |
"""


def test_registry_drift_rule_table_both_ways(tmp_path):
    root = tmp_path / "proj"
    _write(root, "docs/Analysis.md", _RULE_TABLE_DOC)
    _write(root, "pkg/monitor/monitor.py", "")
    ctx = build_context([root / "pkg"], root=root)
    assert ctx.full_package
    found = [
        f
        for f in RULES["registry-drift"].run(ctx)
        if f.check in ("undocumented-rule", "ghost-rule")
    ]
    ghosts = [f for f in found if f.check == "ghost-rule"]
    undoc = [f for f in found if f.check == "undocumented-rule"]
    assert len(ghosts) == 1 and "bogus-rule" in ghosts[0].message
    # every registered rule except the documented one is reported
    assert {m for f in undoc for m in [f.message]} and len(undoc) == len(
        RULES
    ) - 1
    assert not any("trace-safety" in f.message for f in undoc)


# ---------------------------------------------------------------------------
# analysis cost through build info
# ---------------------------------------------------------------------------


def test_analysis_cost_surfaces_through_build_info():
    """ISSUE 8: per-rule finding counts and wall time ride
    get_build_info -> ctrl getBuildInfo -> `breeze openr version`. The
    stats reflect the MOST RECENT run in the process, so run one here and
    compare against its result dict."""
    from openr_tpu.utils.build_info import get_build_info

    result = run_analysis([PKG / "analysis"])
    info = get_build_info()
    assert float(info["build_analysis_wall_ms"]) > 0
    assert int(info["build_analysis_files"]) == result["files"] > 5
    stats = dict(
        pair.split("=", 1)
        for pair in info["build_analysis_rule_stats"].split(",")
    )
    assert set(stats) == set(RULES)
    for name, value in stats.items():
        findings, ms = value.split(":")
        assert int(findings) == result["per_rule"][name]["findings"]
        assert ms.endswith("ms")


def test_analysis_cost_rides_ctrl_get_build_info():
    from openr_tpu.ctrl.server import CtrlServer

    run_analysis([PKG / "analysis"])
    handler = CtrlServer.__new__(CtrlServer)
    info = handler.m_getBuildInfo({})
    assert "build_analysis_wall_ms" in info
    assert "build_analysis_rule_stats" in info
    assert info["build_analysis_version"] == ANALYSIS_VERSION


def test_trace_safety_reaches_fw_apsp_kernels():
    """Regression (ISSUE 12): the blocked Floyd–Warshall APSP kernels —
    diagonal block close, panel/outer sweep stages, the warm seed and the
    dirty-block re-close round — must sit inside the rule's traced set
    (they are `jax.jit(fn)` factory seeds inside lru_cache factories),
    while the numpy Floyd–Warshall fallback/oracle stays OUT (its np.*
    calls would otherwise be host-sync findings)."""
    import ast

    from openr_tpu.analysis.trace_safety import _traced_functions

    tree = ast.parse((PKG / "apsp" / "kernels.py").read_text())
    traced, direct = _traced_functions(tree)
    direct_names = {fn.name for fn in direct}
    traced_names = {fn.name for fn in traced}
    # jit roots: the cold close, the warm seed, the re-close round
    assert {"close", "seed", "reclose"} <= direct_names
    # transitively traced helpers: the (min,+) tile product, the block
    # reshapes, the per-stage sweep bodies
    assert {"_mp", "_to_blocks", "_from_blocks", "stage"} <= traced_names
    # the numpy fallback/oracle and the host-side matrix builders are
    # never traced
    assert not {
        "np_floyd_warshall",
        "build_weight_matrix",
        "build_allow_matrix",
    } & traced_names


# ---------------------------------------------------------------------------
# ShapeFlow (v3.0): the four abstract-interpretation families
# ---------------------------------------------------------------------------

_SF_SHAPE_BAD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:float32", returns="[N,N]:float32")
@jax.jit
def outer(x):
    return x


@jax.jit
def mixed():
    return jnp.zeros((4,)) + jnp.zeros((8,))


def split(n_pad, g):
    n_tile = n_pad // g
    return n_tile
'''

_SF_SHAPE_GOOD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:float32", returns="[N]:float32")
@jax.jit
def outer(x):
    return x * 2.0


@jax.jit
def mixed():
    return jnp.zeros((4, 1)) + jnp.zeros((4, 8))


def split(n_pad, g):
    assert n_pad % g == 0, (n_pad, g)
    n_tile = n_pad // g
    return n_tile
'''

_SF_SHAPE_SUPPRESSED = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:float32", returns="[N,N]:float32")
@jax.jit
def outer(x):
    return x  # analysis: ignore[shape-mismatch]


@jax.jit
def mixed():
    # analysis: ignore[shape-mismatch]
    return jnp.zeros((4,)) + jnp.zeros((8,))


def split(n_pad, g):
    n_tile = n_pad // g  # analysis: ignore[shape-mismatch]
    return n_tile
'''


def test_shape_mismatch_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_shape.py", _SF_SHAPE_BAD)
    found, _ = _findings([path], rule="shape-mismatch")
    checks = sorted(f.check for f in found)
    assert checks == [
        "broadcast", "return-contract", "tile-divisibility",
    ], found
    assert all(f.severity == "error" for f in found)


def test_shape_mismatch_negative(tmp_path):
    path = _write(tmp_path, "good_shape.py", _SF_SHAPE_GOOD)
    found, _ = _findings([path], rule="shape-mismatch")
    assert found == [], found


def test_shape_mismatch_suppression(tmp_path):
    path = _write(tmp_path, "waived_shape.py", _SF_SHAPE_SUPPRESSED)
    found, suppressed = _findings([path], rule="shape-mismatch")
    assert found == [] and suppressed == 3


def test_shape_mismatch_cli_exits_nonzero(tmp_path):
    path = _write(tmp_path, "bad_shape.py", _SF_SHAPE_BAD)
    assert analysis_main([str(path), "--no-baseline"]) == 1


_SF_CONTRACT_BAD = '''
import jax
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:float13")
@jax.jit
def f(x):
    return x


@shape_contract("y:[N]:int32")
@jax.jit
def g(x):
    return x
'''


def test_shape_contract_decorator_findings(tmp_path):
    """A malformed spec string and a contract naming a non-parameter are
    findings on the decorator line, not silent no-ops."""
    path = _write(tmp_path, "bad_contract.py", _SF_CONTRACT_BAD)
    found, _ = _findings([path], rule="shape-mismatch")
    checks = sorted(f.check for f in found)
    assert checks == ["contract-params", "contract-syntax"], found


def test_shape_contract_runtime_decorator():
    """The runtime decorator validates eagerly, attaches the parsed
    contract, and returns the ORIGINAL function (zero wrapper overhead:
    jit traces the same object it would have without the annotation)."""
    import pytest

    from openr_tpu.utils.shape_contract import (
        ContractError,
        parse_contract,
        shape_contract,
    )

    def mp(a, b):
        return a

    wrapped = shape_contract(
        "a:[B,B]:int32:inf", "b:[B,B]:int32:inf",
        returns="[B,B]:int32:inf",
    )(mp)
    assert wrapped is mp
    contract = mp.__shape_contract__
    assert set(contract.params) == {"a", "b"}
    assert list(contract.params["a"].dims) == ["B", "B"]
    assert contract.params["a"].dtype == "int32"
    assert contract.params["a"].inf
    assert contract.returns is not None and contract.returns.inf
    with pytest.raises(ContractError):
        shape_contract("a:[B:int32")(lambda a: a)
    with pytest.raises(ContractError):
        shape_contract("a:[B]:notadtype")(lambda a: a)
    with pytest.raises(ContractError):
        parse_contract(["a:[B]:int32"], returns="[B]:int32:bogus")


_SF_CALL_BAD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract

B = 128


@shape_contract("a:[B,B]:int32", "b:[B,B]:int32", returns="[B,B]:int32")
def mp(a, b):
    return jnp.minimum(a, b)


@jax.jit
def sweep():
    tile = jnp.zeros((128, 64), dtype=jnp.int32)
    flat = jnp.zeros((128,), dtype=jnp.int32)
    mp(tile, tile)
    mp(flat, flat)
    return tile
'''

_SF_CALL_GOOD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("d:[S,n_pad]:int32:inf", returns="[S,n_pad]:int32:inf")
def relax(d):
    return jnp.minimum(d, 1 << 29)


@shape_contract("d0:[S,n_pad]:int32:inf")
@jax.jit
def drive(d0):
    d1 = relax(d0)
    d2 = relax(d1)
    return d2
'''


def test_call_contract_checked_at_the_seam(tmp_path):
    """Every resolved call against an annotated callee is verified: the
    module constant B = 128 binds the contract symbol, so a 64-wide tile
    is a dim conflict and a rank-1 operand is a rank conflict — for each
    mis-shaped parameter."""
    path = _write(tmp_path, "bad_call.py", _SF_CALL_BAD)
    found, _ = _findings([path], rule="shape-mismatch")
    assert [f.check for f in found] == ["call-contract"] * 4, found
    msgs = " | ".join(f.message for f in found)
    assert "B=128" in msgs  # the symbol carries its bound value
    assert "rank 1 != 2" in msgs


def test_call_contract_symbolic_dims_unify_across_calls(tmp_path):
    """Symbolic dims thread through call seams without false positives:
    the contract return of one call feeds the next call's params, each
    with fresh-renamed symbols unified against the caller's."""
    path = _write(tmp_path, "good_call.py", _SF_CALL_GOOD)
    found, _ = _findings([path], rule="shape-mismatch")
    assert found == [], found


_SF_SENT_BAD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract

INF = 1 << 29


@shape_contract("d:[N,N]:int32:inf", "w:[N,N]:int32:inf")
@jax.jit
def relax(d, w):
    return d + w


@jax.jit
def fold(d, w):
    best = jnp.minimum(d + w, INF)
    worst = d + w
    return best, worst


@shape_contract("d:[N,N]:int32:inf")
@jax.jit
def spread(d):
    return jax.lax.psum(d, "batch")
'''

_SF_SENT_GOOD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract

INF = 1 << 29


@shape_contract(
    "d:[N,N]:int32:inf", "w:[N,N]:int32:inf", returns="[N,N]:int32:inf"
)
@jax.jit
def relax(d, w):
    return jnp.minimum(d + w, INF)


@shape_contract("d:[N,N]:int32:inf")
@jax.jit
def spread(d):
    return jax.lax.pmin(d, "batch")
'''

_SF_SENT_SUPPRESSED = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract

INF = 1 << 29


@shape_contract("d:[N,N]:int32:inf", "w:[N,N]:int32:inf")
@jax.jit
def relax(d, w):
    return d + w  # analysis: ignore[sentinel-overflow]
'''


def test_sentinel_overflow_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_sent.py", _SF_SENT_BAD)
    found, _ = _findings([path], rule="sentinel-overflow")
    checks = sorted(f.check for f in found)
    assert checks == [
        "psum-sentinel", "unclamped-add", "unclamped-add",
    ], found
    assert all(f.severity == "error" for f in found)


def test_sentinel_overflow_negative(tmp_path):
    path = _write(tmp_path, "good_sent.py", _SF_SENT_GOOD)
    found, _ = _findings([path], rule="sentinel-overflow")
    assert found == [], found


def test_sentinel_overflow_suppression(tmp_path):
    path = _write(tmp_path, "waived_sent.py", _SF_SENT_SUPPRESSED)
    found, suppressed = _findings([path], rule="sentinel-overflow")
    assert found == [] and suppressed == 1


def test_sentinel_overflow_cli_exits_nonzero(tmp_path):
    path = _write(tmp_path, "bad_sent.py", _SF_SENT_BAD)
    assert analysis_main([str(path), "--no-baseline"]) == 1


def test_sentinel_inference_summaries_persist_per_file_sha(tmp_path):
    """Unannotated traced functions get their sentinel params INFERRED
    (fold's clamp marks d and w), and the summary lands in the shared
    cache keyed by file sha — the second run serves it from the cache
    (inferred == 0) and reports identically."""
    import json

    from openr_tpu.analysis.shapeflow import LAST_SHAPEFLOW_STATS

    path = _write(tmp_path, "fold.py", _SF_SENT_BAD)
    found1, _ = _findings([path], rule="sentinel-overflow")
    assert LAST_SHAPEFLOW_STATS["inferred"] == 1  # fold, no contract
    cache = tmp_path / ".analysis-cache.json"
    assert cache.exists()
    payload = json.loads(cache.read_text())
    entry = payload["shapeflow"]["files"]["fold.py"]
    assert entry["functions"]["fold::fold"] == ["d", "w"]
    found2, _ = _findings([path], rule="sentinel-overflow")
    assert LAST_SHAPEFLOW_STATS["inferred"] == 0  # served from cache
    assert [f.key() for f in found2] == [f.key() for f in found1]


_SF_DTYPE_BAD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:int32", "m:[N]:bool")
@jax.jit
def score(x, m):
    y = x * m
    z = x / 4
    w = x + 1.5
    return y, z, w


@jax.jit
def demote(x):
    return x.astype(jnp.float64)
'''

_SF_DTYPE_GOOD = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:int32", "m:[N]:bool")
@jax.jit
def score(x, m):
    xf = x.astype(jnp.float32)
    y = xf * m.astype(jnp.float32)
    z = x // 4
    w = xf + 1.5
    return y, z, w
'''

_SF_DTYPE_SUPPRESSED = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract


@shape_contract("x:[N]:int32", "m:[N]:bool")
@jax.jit
def score(x, m):
    y = x * m  # analysis: ignore[dtype-promotion]
    z = x / 4  # analysis: ignore[dtype-promotion]
    w = x + 1.5  # analysis: ignore[dtype-promotion]
    return y, z, w


@jax.jit
def demote(x):
    return x.astype(jnp.float64)  # analysis: ignore[dtype-promotion]
'''


def test_dtype_promotion_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_dtype.py", _SF_DTYPE_BAD)
    found, _ = _findings([path], rule="dtype-promotion")
    checks = sorted(f.check for f in found)
    assert checks == [
        "bool-arith", "int-true-div", "silent-promotion", "weak-float64",
    ], found
    # the family is registered advisory; strict promoted these to error
    assert RULES["dtype-promotion"].severity == "advisory"
    assert all(f.severity == "error" for f in found)


def test_dtype_promotion_negative(tmp_path):
    path = _write(tmp_path, "good_dtype.py", _SF_DTYPE_GOOD)
    found, _ = _findings([path], rule="dtype-promotion")
    assert found == [], found


def test_dtype_promotion_suppression(tmp_path):
    path = _write(tmp_path, "waived_dtype.py", _SF_DTYPE_SUPPRESSED)
    found, suppressed = _findings([path], rule="dtype-promotion")
    assert found == [] and suppressed == 4


def test_dtype_promotion_is_advisory_unless_strict(tmp_path):
    path = _write(tmp_path, "bad_dtype.py", _SF_DTYPE_BAD)
    assert analysis_main([str(path), "--no-baseline"]) == 0
    assert analysis_main([str(path), "--no-baseline", "--strict"]) == 1


_SF_COLL_BAD = '''
import jax
import jax.numpy as jnp


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return None


def halo(ctr, g):
    perm = [(i, (i + 1) % g) for i in range(g)]
    ctr = jax.lax.ppermute(ctr, "grahp", perm)
    return jax.lax.ppermute(ctr, "graph", [(0, 1), (0, 0)])
'''

_SF_COLL_GOOD = '''
import jax
import jax.numpy as jnp


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return None


def halo(ctr, g):
    perm = [(i, (i + 1) % g) for i in range(g)]
    ctr = jax.lax.ppermute(ctr, "graph", perm)
    return jax.lax.ppermute(ctr, "batch", [(0, 1), (1, 0)])
'''

_SF_COLL_SUPPRESSED = '''
import jax
import jax.numpy as jnp


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return None


def halo(ctr, g):
    perm = [(i, (i + 1) % g) for i in range(g)]
    # analysis: ignore[collective-conformance]
    ctr = jax.lax.ppermute(ctr, "grahp", perm)
    # analysis: ignore[collective-conformance]
    return jax.lax.ppermute(ctr, "graph", [(0, 1), (0, 0)])
'''


def test_collective_conformance_fixture_violations(tmp_path):
    path = _write(tmp_path, "bad_coll.py", _SF_COLL_BAD)
    found, _ = _findings([path], rule="collective-conformance")
    checks = sorted(f.check for f in found)
    assert checks == ["perm-malformed", "unknown-axis"], found
    msgs = " | ".join(f.message for f in found)
    assert "'grahp'" in msgs and "duplicates" in msgs
    assert all(f.severity == "error" for f in found)


def test_collective_conformance_negative(tmp_path):
    path = _write(tmp_path, "good_coll.py", _SF_COLL_GOOD)
    found, _ = _findings([path], rule="collective-conformance")
    assert found == [], found


def test_collective_conformance_suppression(tmp_path):
    path = _write(tmp_path, "waived_coll.py", _SF_COLL_SUPPRESSED)
    found, suppressed = _findings([path], rule="collective-conformance")
    assert found == [] and suppressed == 2


def test_collective_axis_check_disarms_without_vocabulary(tmp_path):
    """Like shard-spec: a module with no mesh vocabulary in scope cannot
    be judged — the axis check disarms instead of guessing."""
    src = (
        "import jax\n"
        "def halo(ctr):\n"
        "    return jax.lax.ppermute(ctr, 'anything', [(0, 1)])\n"
    )
    path = _write(tmp_path, "consumer.py", src)
    found, _ = _findings([path], rule="collective-conformance")
    assert found == [], found


# ---------------------------------------------------------------------------
# ShapeFlow: the three seeded mutations from the acceptance checklist
# ---------------------------------------------------------------------------

_MUT_FW_CLAMP = '''
import jax
import jax.numpy as jnp
from openr_tpu.utils.shape_contract import shape_contract

INF = 1 << 29


@shape_contract(
    "a:[B,B]:int32:inf", "b:[B,B]:int32:inf", returns="[B,B]:int32:inf"
)
@jax.jit
def _mp(a, b):
    return jnp.min(a[:, :, None] + b[None, :, :], axis=1)
'''

_MUT_FW_OK = _MUT_FW_CLAMP.replace(
    "jnp.min(a[:, :, None] + b[None, :, :], axis=1)",
    "jnp.min(jnp.minimum(a[:, :, None] + b[None, :, :], INF), axis=1)",
)

_MUT_HALO = '''
import jax
import jax.numpy as jnp


def make_mesh(devices=None, shape=None, axis_names=("batch", "graph")):
    return None


def _tile_halo_min(ctr, g):
    perm = [(i, (i + 1) % g) for i in range(g)]
    return jax.lax.ppermute(ctr, "grahp", perm)
'''

_MUT_SPLIT = '''
import jax.numpy as jnp


def fw_block_shape(n_pad):
    bsz = min(128, n_pad)
    return n_pad // bsz, bsz
'''


def test_mutation_deleted_fw_clamp_is_exactly_one_overflow(tmp_path):
    """ISSUE 19 acceptance: delete the INF clamp from a copy of the FW
    block product `_mp` — exactly one error-severity sentinel-overflow
    finding, and nothing else fires."""
    path = _write(tmp_path, "mut_mp.py", _MUT_FW_CLAMP)
    found, _ = _findings([path])
    assert len(found) == 1, found
    f = found[0]
    assert (f.rule, f.check, f.severity) == (
        "sentinel-overflow", "unclamped-add", "error",
    )
    # restoring the clamp (the shipped `_mp` body) is clean again
    ok = _write(tmp_path, "mut_mp_ok.py", _MUT_FW_OK)
    found, _ = _findings([ok])
    assert found == [], found


def test_mutation_swapped_ppermute_axis_is_exactly_one_conformance(
    tmp_path,
):
    """ISSUE 19 acceptance: swap the halo exchange's ppermute axis name
    for a typo — exactly one error-severity collective-conformance
    finding against the declared mesh vocabulary."""
    path = _write(tmp_path, "mut_halo.py", _MUT_HALO)
    found, _ = _findings([path])
    assert len(found) == 1, found
    f = found[0]
    assert (f.rule, f.check, f.severity) == (
        "collective-conformance", "unknown-axis", "error",
    )
    assert "'grahp'" in f.message and "batch" in f.message


def test_mutation_dropped_divisibility_guard_is_exactly_one_shape(
    tmp_path,
):
    """ISSUE 19 acceptance: drop fw_block_shape's divisibility assert —
    exactly one error-severity shape-mismatch finding; putting the
    guard back silences it."""
    path = _write(tmp_path, "mut_split.py", _MUT_SPLIT)
    found, _ = _findings([path])
    assert len(found) == 1, found
    f = found[0]
    assert (f.rule, f.check, f.severity) == (
        "shape-mismatch", "tile-divisibility", "error",
    )
    guarded = _MUT_SPLIT.replace(
        "    return n_pad // bsz, bsz",
        "    assert n_pad % bsz == 0, (n_pad, bsz)\n"
        "    return n_pad // bsz, bsz",
    )
    ok = _write(tmp_path, "mut_split_ok.py", guarded)
    found, _ = _findings([ok])
    assert found == [], found


# ---------------------------------------------------------------------------
# ShapeFlow: lattice + unification unit coverage
# ---------------------------------------------------------------------------


def test_sentinel_lattice_join_and_min():
    from openr_tpu.analysis.shapeflow import (
        S_EQ,
        S_LT,
        S_MAYBE,
        S_NON,
        S_SUM,
        sent_join,
        sent_min,
    )

    assert sent_join(S_LT, S_EQ) == S_MAYBE
    assert sent_join(S_NON, S_NON) == S_NON
    assert sent_join(S_NON, S_EQ) == S_MAYBE  # opaque branch: stay <=INF
    assert sent_join(S_SUM, S_LT) == S_SUM  # overflow is sticky
    assert sent_join(S_MAYBE, S_MAYBE) == S_MAYBE
    assert sent_min(S_SUM, S_EQ) == S_EQ
    assert sent_min(S_NON, S_EQ) == S_NON  # unknown side wins a minimum
    assert sent_min(S_MAYBE, S_LT) == S_LT


def test_dimenv_unification():
    from openr_tpu.analysis.shapeflow import DimEnv

    env = DimEnv({"B": 128})
    assert env.unify("B", 128)
    assert not env.unify("B", 64)  # concrete conflict
    assert env.unify("N", "M")  # symbols merge into one class
    assert env.unify("M", 32)  # binding one binds the class
    assert env.concrete("N") == 32
    assert not env.unify("N", 64)
    assert env.unify(None, 7)  # wildcard unifies with anything


def test_dimenv_broadcast_is_lenient():
    from openr_tpu.analysis.shapeflow import DimEnv

    env = DimEnv()
    d, ok = env.broadcast_pair(1, 7)
    assert ok and d == 7
    _, ok = env.broadcast_pair(4, 8)
    assert not ok
    # symbols never merge under broadcast: either side could be 1
    _, ok = env.broadcast_pair("N", "M")
    assert ok
    assert env.concrete("N") is None and env.concrete("M") is None
    # a bound symbol against a conflicting non-1 literal IS a conflict
    env2 = DimEnv({"N": 4})
    _, ok = env2.broadcast_pair("N", 8)
    assert not ok


# ---------------------------------------------------------------------------
# ShapeFlow: package pins + the fixed-at-source regressions
# ---------------------------------------------------------------------------

_SF_FAMILIES = {
    "shape-mismatch",
    "sentinel-overflow",
    "dtype-promotion",
    "collective-conformance",
}


def test_shapeflow_package_pins_fw_tile_softmin_clean():
    """The annotated kernels the families exist to protect analyze clean:
    the FW close (`_mp` + sweep stages), the destination-tiled halo
    exchange in ops/spf.py, the mesh tiling, and the TE softmin /
    utilization / loss cores — with every shipped @shape_contract
    collected and checked."""
    from openr_tpu.analysis.shapeflow import LAST_SHAPEFLOW_STATS

    targets = [
        PKG / "apsp" / "kernels.py",
        PKG / "ops" / "spf.py",
        PKG / "parallel" / "mesh.py",
        PKG / "te" / "objective.py",
        PKG / "te" / "optimizer.py",
    ]
    found, _ = _findings(targets)
    flagged = [f for f in found if f.rule in _SF_FAMILIES]
    assert flagged == [], flagged
    assert LAST_SHAPEFLOW_STATS["contracts"] >= 10
    assert LAST_SHAPEFLOW_STATS["calls_checked"] >= 1


def test_fw_block_shape_guards_divisibility():
    """Regression (fixed at source): fw_block_shape now asserts the
    power-of-two divisibility the blocking scheme relies on instead of
    silently truncating the last tile."""
    import pytest

    from openr_tpu.apsp.kernels import fw_block_shape

    assert fw_block_shape(256) == (2, 128)
    assert fw_block_shape(64) == (1, 64)
    with pytest.raises(AssertionError):
        fw_block_shape(192)  # 192 % 128 != 0: not a bucket-padded count


def test_objective_masks_cast_explicitly():
    """Regression (fixed at source): the soft-utilization bool gates cast
    through .astype(score.dtype) instead of promoting silently, and the
    dtype family pins the file clean."""
    found, _ = _findings(
        [PKG / "te" / "objective.py"], rule="dtype-promotion"
    )
    assert found == [], found
    src = (PKG / "te" / "objective.py").read_text()
    assert src.count(".astype(score.dtype)") >= 2


# ---------------------------------------------------------------------------
# SARIF output (--sarif): same findings, same exit codes, CI-consumable
# ---------------------------------------------------------------------------


def test_sarif_output_round_trip(tmp_path, capsys):
    import json

    path = _write(tmp_path, "bad_sent.py", _SF_SENT_BAD)
    rc = analysis_main([str(path), "--no-baseline", "--strict", "--sarif"])
    out = capsys.readouterr().out
    assert rc == 1  # the exit-code contract is exactly the --json one
    doc = json.loads(out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "openr-tpu-analysis"
    assert driver["version"] == ANALYSIS_VERSION
    assert {r["id"] for r in driver["rules"]} == set(RULES)
    for r in driver["rules"]:
        assert r["defaultConfiguration"]["level"] in ("error", "warning")
    ref = run_analysis([path], strict=True)
    got = {
        (
            r["ruleId"],
            r["locations"][0]["physicalLocation"]["artifactLocation"][
                "uri"
            ],
            r["locations"][0]["physicalLocation"]["region"]["startLine"],
            r["level"],
        )
        for r in run["results"]
    }
    want = {
        (
            f.rule,
            f.path,
            max(f.line, 1),
            "error" if f.severity == "error" else "warning",
        )
        for f in ref["findings"]
    }
    assert got == want and len(run["results"]) == len(ref["findings"])
    for r in run["results"]:
        assert r["message"]["text"].startswith("[")  # [check] prefix
    # a clean tree renders an empty result set and exits 0
    good = _write(tmp_path, "good_sent.py", _SF_SENT_GOOD)
    capsys.readouterr()
    assert analysis_main([str(good), "--no-baseline", "--sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


# ---------------------------------------------------------------------------
# ShapeFlow summary cache: fingerprint + version invalidation
# ---------------------------------------------------------------------------


def test_shapeflow_summary_cache_round_trip_and_fingerprint(tmp_path):
    from openr_tpu.analysis.cache import (
        load_shapeflow_summaries,
        store_shapeflow_summaries,
    )

    cache = tmp_path / "cache.json"
    files = {"pkg/mod.py": {"hash": "abc", "functions": {"f": ["d", "w"]}}}
    store_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp1", files)
    assert (
        load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp1") == files
    )
    # a contract edit (new fingerprint) drops every inferred summary
    assert load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp2") == {}
    # storing under the new fingerprint does not resurrect old entries
    store_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp2", {})
    assert load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp2") == {}
    assert load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp1") == {}


def test_shapeflow_cache_stale_version_and_corruption(tmp_path):
    import json

    from openr_tpu.analysis.cache import (
        load_shapeflow_summaries,
        store_shapeflow_summaries,
    )

    cache = tmp_path / "cache.json"
    files = {"pkg/mod.py": {"hash": "abc", "functions": {"f": []}}}
    store_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp", files)
    # an ANALYSIS_VERSION bump (rule semantics changed) invalidates all
    payload = json.loads(cache.read_text())
    payload["analysis_version"] = "0.0.0"
    cache.write_text(json.dumps(payload))
    assert load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp") == {}
    # corruption never crashes, and the next store heals the file
    cache.write_text("{not json")
    assert load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp") == {}
    store_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp", files)
    assert (
        load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp") == files
    )


def test_shapeflow_cache_coexists_with_import_graph(tmp_path):
    """The shapeflow section and the import-graph section share one cache
    file; writing either side preserves the other."""
    from openr_tpu.analysis.cache import (
        changed_closure_cached,
        load_shapeflow_summaries,
        store_shapeflow_summaries,
    )

    pkg = _scratch_pkg(tmp_path)
    cache = tmp_path / "cache.json"
    files = {"pkg/mod_b.py": {"hash": "abc", "functions": {"helper": []}}}
    store_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp", files)
    sel, _ = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert sorted(p.name for p in sel) == ["mod_a.py", "mod_b.py"]
    # the import-graph rewrite kept the shapeflow section
    assert (
        load_shapeflow_summaries(cache, ANALYSIS_VERSION, "fp") == files
    )
    _, stats = changed_closure_cached(pkg, ["pkg/mod_b.py"], tmp_path, cache)
    assert stats == {"hits": 3, "misses": 0, "files": 3}


# ---------------------------------------------------------------------------
# ShapeFlow: contract counts through build info
# ---------------------------------------------------------------------------


def test_shapeflow_contracts_surface_through_build_info():
    """Contract/function/inference counts and the pass wall time ride
    get_build_info -> ctrl getBuildInfo -> `breeze openr version`,
    alongside the existing per-rule stats."""
    from openr_tpu.utils.build_info import get_build_info

    run_analysis([PKG / "apsp"])
    sf = get_analysis_info()["analysis_contracts"]
    assert sf["contracts"] >= 1  # _mp is annotated
    assert sf["functions"] >= sf["contracts"]
    assert sf["wall_ms"] > 0
    field = get_build_info()["build_analysis_contracts"]
    head, ms = field.rsplit(":", 1)
    assert ms.endswith("ms")
    pairs = dict(p.split("=", 1) for p in head.split(","))
    assert int(pairs["contracts"]) == sf["contracts"]
    assert int(pairs["functions"]) == sf["functions"]
    assert int(pairs["inferred"]) == sf["inferred"]
    from openr_tpu.ctrl.server import CtrlServer

    handler = CtrlServer.__new__(CtrlServer)
    assert "build_analysis_contracts" in handler.m_getBuildInfo({})


# ---------------------------------------------------------------------------
# resident-accounting
# ---------------------------------------------------------------------------

_RESIDENT_BAD = '''
import jax


@jax.jit
def _solve_core(x):
    return x


class Solve:
    def warm(self, x):
        self._d_dev = _solve_core(x)  # resident, never registered
        return 1
'''

_RESIDENT_GOOD = '''
import jax


@jax.jit
def _solve_core(x):
    return x


class Solve:
    def warm(self, x):
        self._d_dev = _solve_core(x)
        self._mem_register("dist", arrays=(self._d_dev,))
        return 1

    def rebuild(self, x):
        self._w_dev = _solve_core(x)
        self._ledger.register("0/a", "w", arrays=(self._w_dev,))
        return 1

    def reset(self):
        self._d_dev = None  # not a device value: never flagged

    def _mem_register(self, structure, arrays=()):
        pass
'''


def test_resident_accounting_flags_unledgered_store(tmp_path):
    path = _write(
        tmp_path, "openr_tpu/solver/bad_res.py", _RESIDENT_BAD
    )
    found, _ = _findings([path], rule="resident-accounting")
    assert [f.check for f in found] == ["unledgered-store"], found
    assert "self._d_dev" in found[0].message


def test_resident_accounting_ledger_seams_stay_quiet(tmp_path):
    path = _write(
        tmp_path, "openr_tpu/solver/good_res.py", _RESIDENT_GOOD
    )
    found, _ = _findings([path], rule="resident-accounting")
    assert found == [], found


def test_resident_accounting_scoped_to_resident_packages(tmp_path):
    # the same store outside solver/apsp/te is transient working state
    path = _write(tmp_path, "openr_tpu/ops/bad_res.py", _RESIDENT_BAD)
    found, _ = _findings([path], rule="resident-accounting")
    assert found == [], found


def test_resident_accounting_is_advisory_unless_strict(tmp_path):
    path = _write(
        tmp_path, "openr_tpu/apsp/bad_res.py", _RESIDENT_BAD
    )
    found, _ = _findings(
        [path], rule="resident-accounting", strict=False
    )
    assert [f.severity for f in found] == ["advisory"], found
    found, _ = _findings(
        [path], rule="resident-accounting", strict=True
    )
    assert [f.severity for f in found] == ["error"], found


def test_resident_accounting_repo_is_clean_strict():
    """The real solver/apsp/te packages pass their own rule at strict
    level: every device-resident store meets a ledger seam."""
    found, _ = _findings(
        [PKG / "solver", PKG / "apsp", PKG / "te"],
        rule="resident-accounting",
    )
    assert found == [], found
