"""State journal tests (ISSUE 17, docs/Journal.md): codec round-trips,
bounded-ring accounting with lossless eviction folds, durable-log crash
consistency (the PR 14 truncate-at-every-byte fuzz, re-aimed at the
journal's RecordLog), and deterministic replay + provenance on a live
emulated network — replay(T) must element-equal the live RIB at T, and
explain-route must resolve a complete provenance chain for every route
in the final RIB."""

import asyncio
import os
import time

from openr_tpu.journal import (
    JournalConfig,
    LsdbFolder,
    StateJournal,
    codec,
    resolve_ts,
)
from openr_tpu.solver.routes import (
    DecisionRouteUpdate,
    RibUnicastEntry,
)
from openr_tpu.types import (
    AdjacencyDatabase,
    IpPrefix,
    NextHop,
    PrefixDatabase,
    PrefixEntry,
    Publication,
    Value,
    adj_key,
    prefix_key,
)
from openr_tpu.utils import serializer


def run(coro, timeout=60.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()


def make_publication(
    adj_dbs=(), prefix_dbs=(), expired=(), area="0", version=1
):
    pub = Publication(area=area)
    for db in adj_dbs:
        pub.key_vals[adj_key(db.this_node_name)] = Value(
            version, db.this_node_name, serializer.dumps(db)
        )
    for db in prefix_dbs:
        pub.key_vals[prefix_key(db.this_node_name)] = Value(
            version, db.this_node_name, serializer.dumps(db)
        )
    pub.expired_keys.extend(expired)
    return pub


def make_rib_update(prefix="10.7.0.0/24", address="fe80::7", delete=()):
    entry = RibUnicastEntry(
        prefix=IpPrefix(prefix),
        nexthops={
            NextHop(address=address, iface="if7"),
            NextHop(address="fe80::8", iface="if8"),
        },
        best_prefix_entry=PrefixEntry(prefix=IpPrefix(prefix)),
        best_area="0",
    )
    return DecisionRouteUpdate(
        unicast_routes_to_update=[entry],
        unicast_routes_to_delete=[IpPrefix(p) for p in delete],
    )


class TestCodec:
    def test_publication_roundtrip(self):
        adj = AdjacencyDatabase(this_node_name="a", area="0")
        pdb = PrefixDatabase(
            "a", [PrefixEntry(prefix=IpPrefix("10.1.0.0/24"))]
        )
        pub = make_publication(
            adj_dbs=[adj], prefix_dbs=[pdb], expired=["adj:gone"],
            version=3,
        )
        decoded = codec.decode_publication(codec.encode_publication(pub))
        assert decoded.area == pub.area
        assert decoded.expired_keys == ["adj:gone"]
        assert set(decoded.key_vals) == set(pub.key_vals)
        for key, val in pub.key_vals.items():
            got = decoded.key_vals[key]
            assert got.version == val.version
            assert got.originator_id == val.originator_id
            assert got.value == val.value  # bytes survive the hex hop
            assert serializer.loads(got.value) == serializer.loads(
                val.value
            )

    def test_route_update_roundtrip(self):
        update = make_rib_update(delete=["10.66.0.0/24"])
        decoded = codec.decode_route_update(
            codec.encode_route_update(update)
        )
        assert (
            decoded.unicast_routes_to_update
            == update.unicast_routes_to_update
        )
        assert decoded.unicast_routes_to_delete == [
            IpPrefix("10.66.0.0/24")
        ]
        # nexthop sets re-assemble from the sorted wire lists
        assert decoded.unicast_routes_to_update[0].nexthops == {
            NextHop(address="fe80::7", iface="if7"),
            NextHop(address="fe80::8", iface="if8"),
        }

    def test_host_local_fields_dropped(self):
        pub = make_publication(
            prefix_dbs=[
                PrefixDatabase(
                    "a", [PrefixEntry(prefix=IpPrefix("10.1.0.0/24"))]
                )
            ]
        )
        pub.ts_monotonic = 123.4
        payload = codec.encode_publication(pub)
        assert "ts_monotonic" not in payload
        assert "span_stages" not in payload

    def test_resolve_ts(self):
        assert resolve_ts(None) is None
        assert resolve_ts(1234.5) == 1234.5
        # negative = relative to now
        assert abs(resolve_ts(-10.0) - (time.time() - 10.0)) < 1.0


class TestRingAccounting:
    def _feed(self, journal, n=10):
        for i in range(1, n + 1):
            adj = AdjacencyDatabase(this_node_name="b", area="0")
            journal.record_publication(
                make_publication(adj_dbs=[adj], version=i)
            )
        journal.record_publication(
            make_publication(
                prefix_dbs=[
                    PrefixDatabase(
                        "b", [PrefixEntry(prefix=IpPrefix("10.2.0.0/24"))]
                    )
                ],
                version=1,
            )
        )
        journal.record_route_update(make_rib_update())

    def test_records_equals_retained_plus_evicted(self):
        journal = StateJournal(
            "me", JournalConfig(enabled=True, ring_size=3)
        )
        self._feed(journal)
        stats = journal.stats()
        counters = stats["counters"]
        assert counters["journal.evicted"] > 0
        assert (
            counters["journal.records"]
            == stats["retained"] + counters["journal.evicted"]
        )
        assert stats["retained"] <= 3

    def test_eviction_fold_is_lossless_for_replay(self):
        """CRDT fold: a tiny ring that evicted most of its history must
        replay to the SAME LSDB and RIB as an unbounded ring fed the
        identical record sequence."""
        big = StateJournal(
            "me", JournalConfig(enabled=True, ring_size=4096)
        )
        small = StateJournal(
            "me", JournalConfig(enabled=True, ring_size=2)
        )
        for journal in (big, small):
            self._feed(journal)
        r_big, r_small = big.replay_at(), small.replay_at()
        assert r_big.rib.unicast_entries == r_small.rib.unicast_entries
        for area, ls in r_big.folder.area_link_states.items():
            other = r_small.folder.area_link_states[area]
            assert (
                ls.get_adjacency_databases()
                == other.get_adjacency_databases()
            )
        assert r_big.fold_errors == 0 and r_small.fold_errors == 0

    def test_empty_route_updates_not_recorded(self):
        journal = StateJournal("me", JournalConfig(enabled=True))
        journal.record_route_update(DecisionRouteUpdate())
        assert (
            journal.stats()["counters"].get("journal.records", 0) == 0
        )

    def test_key_history_bounded_and_ordered(self):
        journal = StateJournal(
            "me", JournalConfig(enabled=True, key_history=4)
        )
        for i in range(1, 9):
            adj = AdjacencyDatabase(this_node_name="b", area="0")
            journal.record_publication(
                make_publication(adj_dbs=[adj], version=i)
            )
        journal.record_publication(
            make_publication(expired=[adj_key("b")])
        )
        hist = journal.key_history(adj_key("b"))
        assert len(hist) == 4  # bounded
        assert [e["seq"] for e in hist] == sorted(
            e["seq"] for e in hist
        )
        assert hist[-1]["deleted"] is True
        assert hist[-2]["version"] == 8
        # area filter
        assert journal.key_history(adj_key("b"), area="other") == []

    def test_ttl_refresh_skipped_by_fold(self):
        folder = LsdbFolder("me")
        pub = Publication(area="0")
        pub.key_vals[adj_key("b")] = Value(2, "b", None)  # ttl refresh
        folder.apply_publication(pub, 1, time.time())
        assert folder.errors == 0
        assert (
            folder.area_link_states["0"].get_adjacency_databases() == {}
        )


class TestDurability:
    def _journaled(self, path, n=6):
        """A journal whose file holds one snapshot + n-1 separate
        appends (no event loop: every record flushes synchronously)."""
        journal = StateJournal(
            "me",
            JournalConfig(enabled=True, path=path, ring_size=64),
        )
        for i in range(1, n + 1):
            adj = AdjacencyDatabase(this_node_name="b", area="0")
            journal.record_publication(
                make_publication(adj_dbs=[adj], version=i)
            )
        assert journal.stats()["counters"]["journal.appends"] >= 1
        return journal

    def test_survives_reopen(self, tmp_path):
        path = str(tmp_path / "journal.bin")
        journal = self._journaled(path)
        before = journal.replay_at()

        reopened = StateJournal(
            "me",
            JournalConfig(enabled=True, path=path, ring_size=64),
        )
        stats = reopened.stats()
        assert stats["last_seq"] == 6
        assert stats["counters"].get("journal.load_truncations", 0) == 0
        after = reopened.replay_at()
        assert (
            before.folder.area_link_states["0"].get_adjacency_databases()
            == after.folder.area_link_states[
                "0"
            ].get_adjacency_databases()
        )
        # key history rebuilt from disk
        hist = reopened.key_history(adj_key("b"))
        assert hist and hist[-1]["version"] == 6

    def test_compaction_when_tail_outgrows(self, tmp_path):
        path = str(tmp_path / "journal.bin")
        journal = StateJournal(
            "me",
            JournalConfig(
                enabled=True,
                path=path,
                ring_size=4,
                min_compact_bytes=256,
            ),
        )
        for i in range(1, 40):
            adj = AdjacencyDatabase(this_node_name="b", area="0")
            journal.record_publication(
                make_publication(adj_dbs=[adj], version=i)
            )
        counters = journal.stats()["counters"]
        assert counters["journal.snapshots"] >= 2  # compacted at least once
        # the compacted file reopens to the same tip
        reopened = StateJournal(
            "me", JournalConfig(enabled=True, path=path, ring_size=4)
        )
        assert reopened.stats()["last_seq"] == 39
        assert reopened.replay_at().fold_errors == 0

    def test_truncate_at_every_byte_recovers_prefix(self, tmp_path):
        """Fuzz: truncate the durable log at EVERY byte offset. Load must
        never crash and must always recover a prefix of the recorded
        sequence — the last durable state, never garbage."""
        path = str(tmp_path / "journal.bin")
        self._journaled(path, n=6)
        raw = open(path, "rb").read()
        cfg = dict(enabled=True, path=path, ring_size=64)
        for cut in range(len(raw)):
            with open(path, "wb") as fh:
                fh.write(raw[:cut])
            reopened = StateJournal("me", JournalConfig(**cfg))
            stats = reopened.stats()
            assert 0 <= stats["last_seq"] <= 6, (cut, stats)
            if stats["last_seq"]:
                # the recovered history is a PREFIX: the newest surviving
                # version equals the newest surviving seq (pub i carried
                # version i), and replay folds it cleanly
                hist = reopened.key_history(adj_key("b"))
                assert hist[-1]["version"] == stats["last_seq"], cut
                assert reopened.replay_at().fold_errors == 0

        # a truncated load marks the file suspect: the next flush
        # compacts (never appends after garbage) and a fresh reopen
        # reads cleanly
        with open(path, "wb") as fh:
            fh.write(raw[: len(raw) - 3])
        survivor = StateJournal("me", JournalConfig(**cfg))
        counters = survivor.stats()["counters"]
        assert counters["journal.load_truncations"] == 1
        adj = AdjacencyDatabase(this_node_name="b", area="0")
        survivor.record_publication(
            make_publication(adj_dbs=[adj], version=99)
        )
        survivor.flush()
        assert survivor.stats()["counters"]["journal.snapshots"] >= 1
        final = StateJournal("me", JournalConfig(**cfg))
        assert (
            final.stats()["counters"].get("journal.load_truncations", 0)
            == 0
        )
        hist = final.key_history(adj_key("b"))
        assert hist[-1]["version"] == 99

    def test_write_failure_keeps_pending_and_retries(self, tmp_path):
        journal = self._journaled(str(tmp_path / "journal.bin"))
        # break the log under the journal: the flush must bump
        # journal.write_failures and keep the batch pending, not raise
        class _Broken:
            def exists(self):
                return True

            def append(self, blob):
                raise OSError("disk full")

            def rewrite(self, blob):
                raise OSError("disk full")

        journal._log = _Broken()
        adj = AdjacencyDatabase(this_node_name="b", area="0")
        journal.record_publication(
            make_publication(adj_dbs=[adj], version=7)
        )
        journal.flush()
        counters = journal.stats()["counters"]
        assert counters["journal.write_failures"] >= 1
        assert journal._pending  # batch survives for the retry


class TestLiveReplay:
    """Replay determinism + provenance on a live emulated network with a
    randomized-enough flap wave (fail + restore the middle link): the
    ISSUE 17 acceptance criteria."""

    def _network(self, n=4):
        from openr_tpu.testing.wrapper import VirtualNetwork

        net = VirtualNetwork()
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                config_overrides={"journal_config": {"enabled": True}},
            )
        return net

    def test_replay_matches_live_rib_after_flaps(self):
        from openr_tpu.testing.wrapper import wait_until

        n = 4
        mid = n // 2

        async def body():
            net = self._network(n)
            await net.start_all()
            for i in range(n - 1):
                net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

            def converged():
                for i in range(n):
                    got = set(
                        net.wrappers[f"n{i}"].programmed_prefixes()
                    )
                    want = {
                        f"10.{j}.0.0/24" for j in range(n) if j != i
                    }
                    if not want.issubset(got):
                        return False
                return True

            try:
                await wait_until(converged, timeout=30.0)
                t_before_flap = time.time()
                await asyncio.sleep(0.05)
                # flap wave: partition and heal the middle link
                net.fail_link(
                    f"n{mid - 1}", f"if{mid - 1}r", f"n{mid}", f"if{mid}l"
                )
                await wait_until(
                    lambda: f"10.{n - 1}.0.0/24"
                    not in net.wrappers["n0"].programmed_prefixes(),
                    timeout=30.0,
                )
                t_partition = time.time()
                await asyncio.sleep(0.05)
                net.restore_link(
                    f"n{mid - 1}", f"if{mid - 1}r", f"n{mid}", f"if{mid}l"
                )
                await wait_until(converged, timeout=30.0)
                await asyncio.sleep(0.5)  # quiesce

                for i in range(n):
                    name = f"n{i}"
                    daemon = net.wrappers[name].daemon
                    journal = daemon.journal

                    # replay(T=now) element-equals the live RIB
                    live = daemon.decision.get_decision_route_db()
                    replayed = journal.replay_at().rib
                    assert (
                        replayed.unicast_entries == live.unicast_entries
                    ), name

                    # the CPU-oracle audit agrees at quiescence
                    verdict = journal.verify_replay()
                    assert verdict["match"], (name, verdict["mismatches"])

                    # explain-route resolves a COMPLETE provenance chain
                    # for every route in the final RIB
                    for prefix in live.unicast_entries:
                        explained = journal.explain_route(str(prefix))
                        assert explained["found"], (name, str(prefix))
                        assert explained["complete"], (
                            name,
                            str(prefix),
                            explained,
                        )
                        assert explained["prefix_keys"], explained

                # time travel: during the partition n0 had no route to
                # the far end; the rib-diff across heal shows it return
                j0 = net.wrappers["n0"].daemon.journal
                partitioned = j0.replay_at(t_partition).rib
                far = IpPrefix(f"10.{n - 1}.0.0/24")
                assert far not in partitioned.unicast_entries
                diff = j0.rib_diff(t_partition, None)
                assert diff["changed"] is True
                restored = {
                    e["prefix"]
                    for e in diff["delta"]["unicast_update"]
                }
                assert str(far) in restored
                # ... and across the whole wave the RIB returned to its
                # pre-flap state
                steady = j0.rib_diff(t_before_flap, None)
                assert steady["changed"] is False
            finally:
                await net.stop_all()

        run(body())

    def test_journal_disabled_by_default(self):
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            net = VirtualNetwork()
            net.add_node("n0", loopback_prefix="10.0.0.0/24")
            net.add_node("n1", loopback_prefix="10.1.0.0/24")
            await net.start_all()
            net.connect("n0", "if0r", "n1", "if1l")
            try:
                await wait_until(
                    lambda: "10.1.0.0/24"
                    in net.wrappers["n0"].programmed_prefixes(),
                    timeout=30.0,
                )
                journal = net.wrappers["n0"].daemon.journal
                assert journal.stats()["enabled"] is False
                assert (
                    journal.stats()["counters"].get("journal.records", 0)
                    == 0
                )
            finally:
                await net.stop_all()

        run(body())

    def test_ctrl_rpcs_roundtrip(self):
        from openr_tpu.ctrl import CtrlClient
        from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

        async def body():
            net = self._network(3)
            await net.start_all()
            for i in range(2):
                net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")
            try:
                await wait_until(
                    lambda: {"10.1.0.0/24", "10.2.0.0/24"}
                    <= set(net.wrappers["n0"].programmed_prefixes()),
                    timeout=30.0,
                )
                await asyncio.sleep(0.3)
                client = await CtrlClient(
                    "127.0.0.1", net.wrappers["n0"].ctrl_port
                ).connect()
                try:
                    stats = await client.call("getJournalStats")
                    assert stats["enabled"] is True
                    assert stats["counters"]["journal.records"] > 0

                    tail = await client.call("getJournalTail", last_n=4)
                    assert tail["enabled"] and len(tail["records"]) <= 4

                    hist = await client.call(
                        "getKvStoreKeyHistory", key=adj_key("n1")
                    )
                    assert hist["history"], hist

                    explained = await client.call(
                        "explainRoute", prefix="10.2.0.0/24"
                    )
                    assert explained["found"] and explained["complete"]
                    assert explained["prefix_keys"]
                    assert explained["adjacency_keys"]

                    verdict = await client.call("verifyJournalReplay")
                    assert verdict["match"] is True

                    diff = await client.call(
                        "getRibDiff", from_ts=time.time() - 120, to_ts=None
                    )
                    assert diff["changed"] is True  # from empty pre-boot
                finally:
                    await client.close()
            finally:
                await net.stop_all()

        run(body())

    def test_rpcs_report_disabled_without_journal(self):
        from openr_tpu.ctrl import CtrlClient
        from openr_tpu.testing.wrapper import VirtualNetwork

        async def body():
            net = VirtualNetwork()
            net.add_node("n0", loopback_prefix="10.0.0.0/24")
            await net.start_all()
            try:
                client = await CtrlClient(
                    "127.0.0.1", net.wrappers["n0"].ctrl_port
                ).connect()
                try:
                    stats = await client.call("getJournalStats")
                    assert stats["enabled"] is False
                    explained = await client.call(
                        "explainRoute", prefix="10.1.0.0/24"
                    )
                    assert explained["enabled"] is False
                finally:
                    await client.close()
            finally:
                await net.stop_all()

        run(body())
