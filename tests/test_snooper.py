"""KvStore snooper tool: stream decode of adj/prefix deltas
(reference: openr/kvstore/tools/KvStoreSnooper.cpp)."""

import asyncio
import io
import threading

from openr_tpu.ctrl import CtrlServer
from openr_tpu.kvstore import InProcessTransport, KvStore
from openr_tpu.kvstore.snooper import snoop
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    Value,
)
from openr_tpu.utils import serializer


def test_snooper_decodes_stream():
    async def body():
        store = KvStore("n1", ["0"], InProcessTransport())
        adj_db = AdjacencyDatabase(
            "n1",
            [Adjacency("n2", "if-n1-n2", metric=7)],
            area="0",
        )
        store.set_key("adj:n1", Value(1, "n1", serializer.dumps(adj_db)))
        server = CtrlServer("n1", port=0, kvstore=store)
        port = await server.start()

        out = io.StringIO()
        result = {}

        def run_snoop():
            result["frames"] = snoop(
                "127.0.0.1", port, out=out, max_frames=2
            )

        t = threading.Thread(target=run_snoop)
        t.start()
        await asyncio.sleep(0.3)
        pfx_db = PrefixDatabase(
            "n3", [PrefixEntry(IpPrefix("10.0.0.0/24"))]
        )
        store.set_key("prefix:n3", Value(1, "n3", serializer.dumps(pfx_db)))
        await asyncio.to_thread(t.join, 5)
        assert not t.is_alive()
        await server.stop()

        text = out.getvalue()
        assert result["frames"] == 2
        assert "[SNAPSHOT] adj:n1" in text
        assert "n2/if-n1-n2:7" in text  # decoded adjacency
        assert "[DELTA] prefix:n3" in text
        assert "10.0.0.0/24" in text  # decoded prefix entry

    asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(body(), 15)
    )
