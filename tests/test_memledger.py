"""Device-memory ledger (monitor/memledger.py): the exact-accounting
invariant, the leak-regression contract (solver lifecycles return the
ledger to baseline), predict_fit accuracy against measured residency,
and the `solver.mem.retain` leak pin — docs/Monitoring.md
"Device-memory observatory"."""

import random

import numpy as np
import pytest

from openr_tpu.apsp import ApspState
from openr_tpu.monitor.memledger import MemLedger, get_ledger
from openr_tpu.ops.graph import compile_edges
from openr_tpu.parallel import resolve_mesh
from openr_tpu.solver import TpuSpfSolver
from openr_tpu.solver.tpu import _AreaSolve
from openr_tpu.testing.faults import FaultInjector, injected
from openr_tpu.topology import build_adj_dbs, grid_edges, wan_edges

from test_tpu_solver import apply_random_event
from test_tpu_solver_mesh import build_ls, make_prefix_state

PFXS = ["10.1.0.0/16"]


def _totals(ledger):
    return ledger.snapshot()["totals"]


def assert_exact(ledger):
    snap = ledger.snapshot()
    t = snap["totals"]
    assert snap["exact"], t
    assert t["registered_bytes"] == t["live_bytes"] + t["freed_bytes"], t
    live = sum(e["nbytes"] for e in snap["entries"])
    assert live == t["live_bytes"], (live, t)


def _live_handles(ledger):
    return {e["handle"] for e in ledger.snapshot()["entries"]}


# ---------------------------------------------------------------------------
# exact accounting (standalone ledger)
# ---------------------------------------------------------------------------


class TestExactAccounting:
    def test_register_update_release_cycle(self):
        led = MemLedger()
        a = np.zeros((8, 16), np.int32)
        h = led.register("0/a", "dist", layout="sell", arrays=(a,))
        assert_exact(led)
        t = _totals(led)
        assert t["live_bytes"] == a.nbytes
        assert t["registered_bytes"] == a.nbytes
        assert t["peak_bytes"] == a.nbytes

        # grow in place: delta flows through registered, not freed
        b = np.zeros((16, 16), np.int32)
        led.update(h, arrays=(b,))
        assert_exact(led)
        assert _totals(led)["live_bytes"] == b.nbytes

        # shrink in place: delta flows through freed
        led.update(h, arrays=(a,))
        assert_exact(led)
        t = _totals(led)
        assert t["live_bytes"] == a.nbytes
        assert t["freed_bytes"] == b.nbytes - a.nbytes
        assert t["peak_bytes"] == b.nbytes

        assert led.release(h) is True
        assert_exact(led)
        t = _totals(led)
        assert t["live_bytes"] == 0
        assert t["registered_bytes"] == t["freed_bytes"]
        # double release is inert
        assert led.release(h) is False
        assert led.release(None) is False
        assert_exact(led)

    def test_structure_and_area_folds(self):
        led = MemLedger()
        led.register("0/a", "dist", layout="sell",
                     arrays=(np.zeros(64, np.int32),))
        led.register("0/a", "sell", layout="sell", nbytes=100)
        led.register("0/b", "apsp", layout="apsp", nbytes=900)
        led.register("0/b", "weird", layout="host", nbytes=7)
        snap = led.snapshot()
        assert snap["structures"]["dist"] == 256
        assert snap["structures"]["sell"] == 100
        assert snap["structures"]["apsp"] == 900
        # unknown structures fold onto the fixed gauge vocabulary
        assert snap["structures"]["other"] == 7
        assert snap["areas"]["0/a"] == 356
        assert snap["areas"]["0/b"] == 907
        # per-area filter narrows entries but keeps process totals
        sub = led.snapshot(area="0/b")
        assert {e["structure"] for e in sub["entries"]} == {
            "apsp", "weird"
        }
        assert sub["totals"] == snap["totals"]

    def test_release_area(self):
        led = MemLedger()
        led.register("0/a", "dist", layout="sell", nbytes=10)
        led.register("0/a", "sell", layout="sell", nbytes=20)
        led.register("0/b", "dist", layout="sell", nbytes=30)
        assert led.release_area("0/a") == 2
        assert_exact(led)
        t = _totals(led)
        assert t["live_bytes"] == 30
        assert t["freed_bytes"] == 30

    def test_capacity_override_and_refusal(self):
        led = MemLedger(capacity_bytes=1 << 20)
        cap = led.capacity()
        assert cap["capacity_bytes"] == 1 << 20
        assert cap["source"] == "override"
        # 4096 nodes of FW triple cannot fit a 1 MiB budget
        verdict = led.predict_fit(4096, "apsp")
        assert verdict["fits"] is False
        assert verdict["predicted_bytes"] > verdict["headroom_bytes"]
        led.record_refusal(verdict)
        snap = led.snapshot()
        assert snap["totals"]["capacity_refusals"] == 1
        assert snap["last_refusal"]["layout"] == "apsp"
        # a small graph fits the same budget
        assert led.predict_fit(16, "apsp")["fits"] is True

    def test_no_capacity_source_yields_open_verdict(self):
        # the tier-1 CPU backend exposes no bytes_limit: fits must be
        # None ("no capacity source, callers use their fallback gate"),
        # never a definite yes/no invented from thin air
        led = MemLedger()
        if led.capacity()["capacity_bytes"] is None:
            assert led.predict_fit(64, "bf")["fits"] is None


# ---------------------------------------------------------------------------
# the solver.mem.retain leak pin (standalone ledger, global fault seam)
# ---------------------------------------------------------------------------


class TestRetainFault:
    def test_retain_pins_entry_live_and_stays_exact(self):
        led = MemLedger()
        h = led.register("0/a", "dist", layout="sell", nbytes=512)
        led.register("0/a", "sell", layout="sell", nbytes=128)
        with injected(FaultInjector(seed=1)) as inj:
            inj.arm(
                "solver.mem.retain",
                times=1,
                action=lambda ctx: setattr(ctx, "retain", True),
            )
            # the release is pinned: not freed, still live
            assert led.release(h) is False
            assert inj.fired("solver.mem.retain") == 1
        assert_exact(led)
        t = _totals(led)
        assert t["retained"] == 1
        assert t["live_bytes"] == 512 + 128
        assert t["freed_bytes"] == 0
        pinned = [
            e for e in led.snapshot()["entries"] if e["retained"]
        ]
        assert len(pinned) == 1 and pinned[0]["structure"] == "dist"
        # a pinned entry stays pinned: later releases are inert
        assert led.release(h) is False
        assert _totals(led)["live_bytes"] == 512 + 128

    def test_unarmed_release_is_a_real_free(self):
        led = MemLedger()
        h = led.register("0/a", "dist", layout="sell", nbytes=64)
        with injected(FaultInjector(seed=1)):
            assert led.release(h) is True  # armed point, no spec
        t = _totals(led)
        assert t["retained"] == 0 and t["live_bytes"] == 0


# ---------------------------------------------------------------------------
# leak regression: solver lifecycles return the ledger to baseline
# ---------------------------------------------------------------------------


class TestLeakRegression:
    def test_warm_solves_and_teardown_return_to_baseline(self):
        led = get_ledger()
        base = _live_handles(led)
        edges = wan_edges(16, seed=3)
        dbs = build_adj_dbs(edges)
        # build the LinkState from the same dbs so events mutate it
        from openr_tpu.lsdb import LinkState

        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        ps = make_prefix_state({"w1": PFXS})
        tpu = TpuSpfSolver("w0")
        tpu.build_route_db("w0", {"0": ls}, ps)
        assert _live_handles(led) - base, "solver registered nothing"
        assert_exact(led)
        rng = random.Random(7)
        links = list(edges)
        for _ in range(3):
            apply_random_event(rng, dbs, ls, links)
            tpu.build_route_db("w0", {"0": ls}, ps)
            assert_exact(led)
        tpu.close()
        assert _live_handles(led) == base
        assert_exact(led)

    def test_mesh_degrade_and_invalidation_return_to_baseline(self):
        led = get_ledger()
        base = _live_handles(led)
        edges = grid_edges(4)
        ls = build_ls(edges)
        ps = make_prefix_state({"g1_1": PFXS})
        tpu = TpuSpfSolver("g0_0", mesh=(2, 2))
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert _live_handles(led) - base
        # mesh degradation drops every cached solve -> baseline
        assert tpu.degrade_mesh() is True
        assert _live_handles(led) == base
        # the next solve re-registers on the degraded mesh
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        assert _live_handles(led) - base
        # warm-state invalidation (breaker trip / audit mismatch path)
        tpu.invalidate_warm_state()
        assert _live_handles(led) == base
        tpu.build_route_db("g0_0", {"0": ls}, ps)
        tpu.close()
        assert _live_handles(led) == base
        assert_exact(led)

    def test_apsp_invalidation_returns_to_baseline(self):
        led = get_ledger()
        base = _live_handles(led)
        g = compile_edges(wan_edges(32, degree=4, seed=7))
        apsp = ApspState(max_nodes=64, area="test/apsp")
        assert apsp.ensure(g) is True
        grown = _live_handles(led) - base
        assert grown
        assert_exact(led)
        apsp.invalidate("test_staleness")
        assert _live_handles(led) == base
        assert apsp.ensure(g) is True
        apsp.close()
        assert _live_handles(led) == base
        assert_exact(led)


# ---------------------------------------------------------------------------
# predict_fit accuracy: the forward model vs measured residency
# ---------------------------------------------------------------------------


def _area_live_bytes(ledger, area, skip=("mirror",), exclude=frozenset()):
    # `exclude` carries the handles live before the structure under test
    # was built: the ledger is process-global, and earlier tests in the
    # same pytest process (the bench contract tests especially) may hold
    # entries under the same area string
    return sum(
        e["nbytes"]
        for e in ledger.snapshot(area=area)["entries"]
        if e["area"] == area
        and e["structure"] not in skip
        and e["handle"] not in exclude
    )


def assert_within(predicted, live, frac=0.10):
    assert live > 0
    assert abs(predicted - live) <= frac * live, (predicted, live)


class TestPredictFitAccuracy:
    def test_sell_layout_within_ten_percent(self):
        led = get_ledger()
        before = _live_handles(led)
        ls = build_ls(wan_edges(24, seed=2))
        solve = _AreaSolve(ls, "w0", mesh=None)
        try:
            kind = (solve._dev or {}).get("kind")
            assert kind == "sell", kind
            verdict = led.predict_fit(
                solve.graph.n,
                kind,
                n_sources=len(getattr(solve, "sources", ())) or 1,
                graph=solve.graph,
            )
            live = _area_live_bytes(
                led, solve._mem_area, exclude=before
            )
            assert_within(verdict["predicted_bytes"], live)
        finally:
            solve.close()

    def test_edge_list_layout_within_ten_percent(self, monkeypatch):
        # the resident edge-list planes (src/dst/w + ov) are the bf
        # layout; `replicated` (the sharded full-solve path) shares the
        # same predict_fit arithmetic but keeps no resident planes, so
        # accuracy is pinned on the resident variant. Sell is always
        # built for real edge lists — strip it to force this path.
        import openr_tpu.solver.tpu as tpu_mod

        real_compile = tpu_mod.compile_graph

        def no_sell(ls):
            g = real_compile(ls)
            g.sell = None
            return g

        monkeypatch.setattr(tpu_mod, "compile_graph", no_sell)
        led = get_ledger()
        before = _live_handles(led)
        ls = build_ls(wan_edges(24, seed=2))
        solve = _AreaSolve(ls, "w0", mesh=None)
        try:
            kind = (solve._dev or {}).get("kind")
            assert kind == "bf", kind
            verdict = led.predict_fit(
                solve.graph.n,
                kind,
                n_sources=len(getattr(solve, "sources", ())) or 1,
                graph=solve.graph,
            )
            live = _area_live_bytes(
                led, solve._mem_area, exclude=before
            )
            assert_within(verdict["predicted_bytes"], live)
            # the replicated layout is the same logical footprint
            repl = led.predict_fit(
                solve.graph.n,
                "replicated",
                n_sources=len(getattr(solve, "sources", ())) or 1,
                graph=solve.graph,
            )
            assert (
                repl["predicted_bytes"] == verdict["predicted_bytes"]
            ), (repl, verdict)
        finally:
            solve.close()

    def test_tile2d_layout_within_ten_percent(self):
        led = get_ledger()
        before = _live_handles(led)
        mesh = resolve_mesh((2, 2))
        ls = build_ls(grid_edges(4))
        solve = _AreaSolve(ls, "g0_0", mesh=mesh)
        try:
            kind = (solve._dev or {}).get("kind")
            assert kind == "tile2d", kind
            verdict = led.predict_fit(
                solve.graph.n,
                kind,
                n_sources=len(getattr(solve, "sources", ())) or 1,
                graph=solve.graph,
                mesh_shape=(
                    mesh.shape["batch"], mesh.shape["graph"]
                ),
            )
            live = _area_live_bytes(
                led, solve._mem_area, exclude=before
            )
            assert_within(verdict["predicted_bytes"], live)
        finally:
            solve.close()

    def test_apsp_layout_is_exact(self):
        led = get_ledger()
        before = _live_handles(led)
        g = compile_edges(wan_edges(48, degree=4, seed=7))
        apsp = ApspState(max_nodes=64, area="test/apsp-fit")
        try:
            assert apsp.ensure(g) is True
            verdict = led.predict_fit(g.n, "apsp", graph=g)
            live = _area_live_bytes(
                led, "test/apsp-fit", exclude=before
            )
            # the FW triple is fully determined by n_pad: exact, not
            # merely within tolerance
            assert verdict["predicted_bytes"] == live, (
                verdict["components"],
                live,
            )
        finally:
            apsp.close()
