"""CHAOS_SMOKE tier-1: the hostile-network hardening proof.

A 5-node emulated line runs a seeded chaos schedule
(openr_tpu/testing/chaos.py): per-direction loss, duplication,
reordering, bounded delay, byte corruption and an asymmetric partition.
The dissemination plane must survive all of it:

  - flood-storm damping holds a flapping key at the originator and the
    *latest* value is served everywhere on release;
  - corrupted frames are rejected with typed counters, never crashing
    the store;
  - the storm's failures/duplicates arm adaptive anti-entropy rounds;
  - the asymmetric partition trips peer quarantine (with a forensics
    dump), and the peer provably recovers through the probe path after
    heal;
  - the network ends oracle-equal: pairwise-identical stores and route
    tables matching a never-chaosed oracle network.
"""

from openr_tpu.testing.chaos import (
    ChaosLinkSpec,
    ChaosMesh,
    run_chaos_smoke,
)


class TestChaosSmoke:
    def test_chaos_smoke(self):
        report = run_chaos_smoke()
        # damping: the flap crossed the suppress limit at the originator
        # and released exactly the latest value (the harness raises if
        # any node ends on a stale flap value)
        assert report["damping"]["holds"] >= 1
        assert report["damping"]["suppressed"] >= 1
        assert report["damping"]["released"] >= 1
        # quarantine: tripped under the asymmetric partition, recovered
        # through the probe path after heal
        assert report["quarantine"]["trips"] >= 1
        assert report["quarantine"]["probes"] >= 1
        assert report["quarantine"]["recoveries"] >= 1
        # wire hardening: the corrupted frames were rejected, typed
        assert report["wire_rejects"] >= 1
        # adaptive anti-entropy armed under the storm
        assert report["anti_entropy_rounds"] >= 1
        # the mesh actually did something hostile
        stats = report["mesh_stats"]
        assert stats.get("kv_dropped", 0) >= 1
        assert stats.get("kv_partitioned", 0) >= 1
        assert stats.get("kv_corrupted", 0) >= 1
        # oracle differential: chaos may not bend routing
        assert report["oracle_equal"] is True


class TestChaosMesh:
    def test_seeded_schedules_replay(self):
        a, b = ChaosMesh(seed=7), ChaosMesh(seed=7)
        spec = ChaosLinkSpec(loss=0.3, dup=0.2, delay_ms=(1.0, 5.0))
        a.set_default(spec)
        b.set_default(spec)
        va = [a.packet_verdict("x", "y") for _ in range(200)]
        vb = [b.packet_verdict("x", "y") for _ in range(200)]
        assert va == vb
        assert a.stats == b.stats

    def test_clear_heals_everything(self):
        mesh = ChaosMesh(seed=1)
        mesh.set_default(ChaosLinkSpec(loss=1.0))
        mesh.set_link("a", "b", ChaosLinkSpec(partition=True))
        mesh.clear()
        assert mesh.spec("a", "b") == ChaosLinkSpec()
        assert mesh.packet_verdict("a", "b") == (1, 0.0)

    def test_asymmetric_partition_is_directional(self):
        mesh = ChaosMesh(seed=1)
        mesh.set_link("a", "b", ChaosLinkSpec(partition=True))
        assert mesh.spec("a", "b").partition is True
        assert mesh.spec("b", "a").partition is False
