"""Ctrl server/client tests, mirroring
openr/ctrl-server/tests/OpenrCtrlHandlerTest.cpp and
OpenrCtrlLongPollTest.cpp: per-module APIs over the wire, KvStore
get/set/dump, streaming subscription, long-poll, drain controls."""

import asyncio

import pytest

from openr_tpu.ctrl import CtrlClient, CtrlServer
from openr_tpu.ctrl.client import CtrlError, decode_obj, encode_obj
from openr_tpu.fib import Fib, FibConfig
from openr_tpu.kvstore import InProcessTransport, KvStore, PeerSpec
from openr_tpu.messaging import RWQueue
from openr_tpu.monitor import LogSample, Monitor
from openr_tpu.platform import MockFibHandler
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.solver.routes import RibUnicastEntry
from openr_tpu.types import (
    AdjacencyDatabase,
    IpPrefix,
    NextHop,
    PrefixEntry,
    PrefixType,
    Value,
    adj_key,
)
from openr_tpu.utils import serializer


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


async def make_server(**modules):
    server = CtrlServer("test-node", port=0, **modules)
    port = await server.start()
    client = await CtrlClient("127.0.0.1", port).connect()
    return server, client


class TestBasics:
    def test_get_my_node_name(self):
        async def body():
            server, client = await make_server()
            assert await client.call("getMyNodeName") == "test-node"
            await client.close()
            await server.stop()

        run(body())

    def test_unknown_method_errors(self):
        async def body():
            server, client = await make_server()
            with pytest.raises(CtrlError, match="unknown method"):
                await client.call("noSuchMethod")
            # connection still usable after an error
            assert await client.call("getMyNodeName") == "test-node"
            await client.close()
            await server.stop()

        run(body())

    def test_counters_and_event_logs(self):
        async def body():
            monitor = Monitor("test-node")

            class Fake:
                counters = {"kvstore.sent_publications": 5}

            monitor.register_module("kvstore", Fake())
            monitor.add_event_log(LogSample().add_string("event", "NB_UP"))
            server, client = await make_server(monitor=monitor)
            counters = await client.call("getCounters")
            assert counters["kvstore.sent_publications"] == 5
            logs = await client.call("getEventLogs")
            assert len(logs) == 1 and "NB_UP" in logs[0]
            await client.close()
            await server.stop()

        run(body())

    def test_get_histograms(self):
        from openr_tpu.utils.counters import Histogram

        async def body():
            monitor = Monitor("test-node")

            class Fake:
                histograms = {}

            hist = Histogram()
            hist.record(2.0)
            hist.record(6.0)
            Fake.histograms = {"decision.spf.solve_ms": hist}
            monitor.register_module("decision", Fake())
            server, client = await make_server(monitor=monitor)
            hists = await client.call("getHistograms")
            solve = hists["decision.spf.solve_ms"]
            assert solve["count"] == 2
            assert solve["min"] == 2.0 and solve["max"] == 6.0
            assert 0.0 < solve["p50"] <= solve["p99"] <= 6.0
            await client.close()
            await server.stop()

        run(body())

    def test_get_histograms_reset_on_read(self):
        """reset: true turns lifetime-cumulative histograms into
        per-window snapshots (the dashboard rate mode)."""
        from openr_tpu.utils.counters import Histogram

        async def body():
            monitor = Monitor("test-node")

            class Fake:
                histograms = {}

            hist = Histogram()
            hist.record(2.0)
            Fake.histograms = {"decision.spf.solve_ms": hist}
            monitor.register_module("decision", Fake())
            server, client = await make_server(monitor=monitor)
            first = await client.call("getHistograms", reset=True)
            assert first["decision.spf.solve_ms"]["count"] == 1
            # the source was cleared: a fresh window starts empty
            empty = await client.call("getHistograms")
            assert empty["decision.spf.solve_ms"]["count"] == 0
            hist.record(4.0)
            hist.record(8.0)
            second = await client.call("getHistograms", reset=True)
            assert second["decision.spf.solve_ms"]["count"] == 2
            assert second["decision.spf.solve_ms"]["min"] == 4.0
            await client.close()
            await server.stop()

        run(body())

    def test_get_solver_health(self):
        """The solver fault-domain degraded flag rides the ctrl surface."""

        async def body():
            class FakeDecision:
                @staticmethod
                def get_solver_health():
                    return {
                        "degraded": True,
                        "breaker_state": "open",
                        "fallback_active": 1,
                    }

            server, client = await make_server(decision=FakeDecision())
            health = await client.call("getSolverHealth")
            assert health["degraded"] is True
            assert health["breaker_state"] == "open"
            await client.close()
            await server.stop()

        run(body())

    def test_get_histograms_without_monitor_merges_modules(self):
        """Monitor-less fallback merges the attached modules' histograms
        (same shape the monitor path serves)."""
        from openr_tpu.utils.counters import Histogram

        async def body():
            class FakeDecision:
                histograms = {}

            hist = Histogram()
            hist.record(1.0)
            FakeDecision.histograms = {"decision.debounce_ms": hist}
            server, client = await make_server(decision=FakeDecision())
            hists = await client.call("getHistograms")
            assert hists["decision.debounce_ms"]["count"] == 1
            await client.close()
            await server.stop()

        run(body())


class TestKvStoreApis:
    def test_set_get_dump(self):
        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            server, client = await make_server(kvstore=store)
            await client.call(
                "setKvStoreKeyVals",
                key_vals={
                    "k1": {
                        "version": 1,
                        "originator_id": "n1",
                        "value": encode_obj("payload"),
                    }
                },
            )
            result = await client.call("getKvStoreKeyVals", keys=["k1"])
            assert "k1" in result["key_vals"]
            assert (
                decode_obj(result["key_vals"]["k1"]["value"]) == "payload"
            )
            # filtered dump
            result = await client.call(
                "getKvStoreKeyValsFiltered", prefixes=["k"]
            )
            assert list(result["key_vals"]) == ["k1"]
            result = await client.call(
                "getKvStoreKeyValsFiltered", prefixes=["zzz"]
            )
            assert result["key_vals"] == {}
            # hash dump carries no values
            result = await client.call("getKvStoreHashFiltered")
            assert result["key_vals"]["k1"]["value"] is None
            assert result["key_vals"]["k1"]["hash"] is not None
            await client.close()
            await server.stop()

        run(body())

    def test_get_kvstore_peer_health(self):
        async def body():
            transport = InProcessTransport()
            a = KvStore("a", ["0"], transport)
            b = KvStore("b", ["0"], transport)
            a.add_peers({"b": PeerSpec("b")})
            await asyncio.sleep(0.05)  # let the initial full sync land
            server, client = await make_server(kvstore=a)
            health = await client.call("getKvStorePeerHealth")
            assert set(health) == {"b"}
            assert health["b"]["health"] == "HEALTHY"
            assert health["b"]["failures"] == 0
            assert health["b"]["quarantined_ms"] == 0.0
            await client.close()
            await server.stop()
            a.stop()
            b.stop()

        run(body())

    def test_streaming_subscription(self):
        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            store.set_key("adj:n1", Value(1, "n1", b"initial"))
            server, client = await make_server(kvstore=store)

            frames = []

            async def consume():
                async for frame in client.subscribe(
                    "subscribeKvStoreFilter", prefixes=["adj:"]
                ):
                    frames.append(frame)
                    if len(frames) >= 2:
                        return

            task = asyncio.get_event_loop().create_task(consume())
            await asyncio.sleep(0.1)
            # initial snapshot frame arrived
            assert len(frames) == 1
            assert "adj:n1" in frames[0]["key_vals"]
            # a matching update streams through; non-matching filtered out
            store.set_key("prefix:n2", Value(1, "n2", b"x"))
            store.set_key("adj:n2", Value(1, "n2", b"adj"))
            await asyncio.wait_for(task, 5)
            assert "adj:n2" in frames[1]["key_vals"]
            assert "prefix:n2" not in frames[1]["key_vals"]
            await client.close()
            await server.stop()

        run(body())

    def test_long_poll_adj(self):
        async def body():
            store = KvStore("n1", ["0"], InProcessTransport())
            server, client = await make_server(kvstore=store)

            async def poll():
                return await client.call(
                    "longPollKvStoreAdj", snapshot={}, timeout_s=5.0
                )

            task = asyncio.get_event_loop().create_task(poll())
            await asyncio.sleep(0.05)
            assert not task.done()  # blocked: no adj keys yet
            store.set_key("adj:n9", Value(1, "n9", b"db"))
            assert await asyncio.wait_for(task, 5) is True
            # snapshot already current -> times out quickly with False
            pub = store.dump_all()
            snapshot = {
                k: v.version
                for k, v in pub.key_vals.items()
                if k.startswith("adj:")
            }
            result = await client.call(
                "longPollKvStoreAdj", snapshot=snapshot, timeout_s=0.2
            )
            assert result is False
            await client.close()
            await server.stop()

        run(body())


class TestRouteApis:
    def test_fib_route_apis(self):
        async def body():
            handler = MockFibHandler()
            route_q = RWQueue()
            fib = Fib(
                FibConfig(my_node_name="test-node", dryrun=True),
                handler,
                route_q,
            )
            await fib.process_route_updates(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        RibUnicastEntry(
                            prefix=IpPrefix("10.0.0.0/24"),
                            nexthops={NextHop("fe80::1", iface="eth0")},
                        )
                    ]
                )
            )
            server, client = await make_server(fib=fib)
            db = await client.call("getRouteDb")
            assert db["this_node_name"] == "test-node"
            routes = [decode_obj(r) for r in db["unicast_routes"]]
            assert str(routes[0].dest) == "10.0.0.0/24"
            filtered = await client.call(
                "getUnicastRoutesFiltered", prefixes=["10.0.0.5"]
            )
            assert len(filtered) == 1
            filtered = await client.call(
                "getUnicastRoutesFiltered", prefixes=["99.0.0.1"]
            )
            assert filtered == []
            await client.close()
            await server.stop()

        run(body())


class TestConfigAndMiscApis:
    def test_dryrun_config_valid_and_invalid(self):
        import json

        async def body():
            server, client = await make_server()
            parsed = await client.call(
                "dryrunConfig",
                file=json.dumps(
                    {"node_name": "n1", "spark_config": {"hello_time_s": 5}}
                ),
            )
            assert parsed["node_name"] == "n1"
            assert parsed["spark_config"]["hello_time_s"] == 5
            # running config untouched
            assert await client.call("getRunningConfig") is None
            with pytest.raises(CtrlError):
                await client.call(
                    "dryrunConfig", file=json.dumps({"bogus_key": 1})
                )
            await client.close()
            await server.stop()

        run(body())

    def test_get_all_decision_adjacency_dbs(self):
        async def body():
            class FakeDecision:
                def get_adjacency_databases(self):
                    return {
                        "b": AdjacencyDatabase(this_node_name="b"),
                        "a": AdjacencyDatabase(this_node_name="a"),
                    }

            server, client = await make_server(decision=FakeDecision())
            dbs = await client.call("getAllDecisionAdjacencyDbs")
            names = [decode_obj(blob).this_node_name for blob in dbs]
            assert names == ["a", "b"]
            await client.close()
            await server.stop()

        run(body())

    def test_process_kvstore_dual_message(self):
        async def body():
            from openr_tpu.kvstore import KvStoreParams

            kv = KvStore(
                "test-node",
                ["0"],
                InProcessTransport(),
                params=KvStoreParams(
                    node_id="test-node", enable_flood_optimization=True
                ),
            )
            server, client = await make_server(kvstore=kv)
            await client.call(
                "processKvStoreDualMessage",
                area="0",
                messages={
                    "src_id": "peer-1",
                    "messages": [
                        {"dst_id": "root-1", "distance": 10,
                         "type": "UPDATE"},
                    ],
                },
            )
            await client.close()
            await server.stop()

        run(body())
