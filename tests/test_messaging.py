"""Queue tests mirroring openr/messaging/tests/QueueTest.cpp."""

import asyncio

import pytest

from openr_tpu.messaging import (
    QueueClosedError,
    ReplicateQueue,
    RWQueue,
)


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestRWQueue:
    def test_push_then_get(self):
        async def body():
            q = RWQueue()
            assert q.push(1)
            assert q.push(2)
            assert q.size() == 2
            assert await q.get() == 1
            assert await q.get() == 2
            assert q.size() == 0

        run(body())

    def test_get_blocks_until_push(self):
        async def body():
            q = RWQueue()
            getter = asyncio.ensure_future(q.get())
            await asyncio.sleep(0)
            assert not getter.done()
            q.push("hello")
            assert await getter == "hello"

        run(body())

    def test_try_get(self):
        q = RWQueue()
        assert q.try_get() is None
        q.push(7)
        assert q.try_get() == 7
        assert q.try_get() is None

    def test_push_after_close_fails(self):
        q = RWQueue()
        q.push(1)
        q.close()
        assert not q.push(2)

    def test_drain_after_close(self):
        # items pushed before close are still readable (QueueTest.cpp close
        # semantics: pending data drains, then error)
        async def body():
            q = RWQueue()
            q.push(1)
            q.close()
            assert await q.get() == 1
            with pytest.raises(QueueClosedError):
                await q.get()

        run(body())

    def test_close_wakes_pending_readers(self):
        async def body():
            q = RWQueue()
            getter = asyncio.ensure_future(q.get())
            await asyncio.sleep(0)
            q.close()
            with pytest.raises(QueueClosedError):
                await getter

        run(body())

    def test_multiple_readers_fifo(self):
        async def body():
            q = RWQueue()
            g1 = asyncio.ensure_future(q.get())
            g2 = asyncio.ensure_future(q.get())
            await asyncio.sleep(0)
            q.push("a")
            q.push("b")
            assert await g1 == "a"
            assert await g2 == "b"

        run(body())

    def test_stats(self):
        async def body():
            q = RWQueue()
            q.push(1)
            q.push(2)
            await q.get()
            assert q.num_writes == 2
            assert q.num_reads == 1

        run(body())


class TestReplicateQueue:
    def test_fanout(self):
        async def body():
            rq = ReplicateQueue()
            r1 = rq.get_reader()
            r2 = rq.get_reader()
            assert rq.get_num_readers() == 2
            rq.push(42)
            assert await r1.get() == 42
            assert await r2.get() == 42

        run(body())

    def test_reader_after_push_misses_old(self):
        async def body():
            rq = ReplicateQueue()
            r1 = rq.get_reader()
            rq.push(1)
            r2 = rq.get_reader()
            rq.push(2)
            assert await r1.get() == 1
            assert await r1.get() == 2
            assert await r2.get() == 2
            assert r2.size() == 0

        run(body())

    def test_close_propagates(self):
        async def body():
            rq = ReplicateQueue()
            r1 = rq.get_reader()
            rq.push(1)
            rq.close()
            assert not rq.push(2)
            assert await r1.get() == 1
            with pytest.raises(QueueClosedError):
                await r1.get()
            with pytest.raises(QueueClosedError):
                rq.get_reader()

        run(body())


class TestUtils:
    def test_exponential_backoff(self):
        from openr_tpu.utils import ExponentialBackoff

        t = [0.0]
        b = ExponentialBackoff(1.0, 8.0, clock=lambda: t[0])
        assert b.can_try_now()
        b.report_error()
        assert b.get_current_backoff() == 1.0
        assert not b.can_try_now()
        assert b.get_time_remaining_until_retry() == 1.0
        b.report_error()
        assert b.get_current_backoff() == 2.0
        b.report_error()
        b.report_error()
        assert b.get_current_backoff() == 8.0
        b.report_error()
        assert b.get_current_backoff() == 8.0  # capped
        assert b.at_max_backoff()
        t[0] = 100.0
        assert b.can_try_now()
        b.report_success()
        assert b.get_current_backoff() == 0.0

    def test_async_debounce_batches(self):
        from openr_tpu.utils import AsyncDebounce

        async def body():
            fired = []
            d = AsyncDebounce(0.01, 0.05, lambda: fired.append(1))
            for _ in range(10):
                d()
            assert d.is_scheduled()
            await asyncio.sleep(0.2)
            assert fired == [1]  # many invocations collapse to one

        run(body())

    def test_async_throttle(self):
        from openr_tpu.utils import AsyncThrottle

        async def body():
            fired = []
            th = AsyncThrottle(0.02, lambda: fired.append(1))
            th()
            th()
            th()
            assert th.is_active()
            await asyncio.sleep(0.1)
            assert fired == [1]
            th()
            await asyncio.sleep(0.1)
            assert fired == [1, 1]

        run(body())

    def test_step_detector(self):
        from openr_tpu.utils import StepDetector

        steps = []
        sd = StepDetector(
            steps.append,
            fast_window_size=4,
            slow_window_size=16,
            lower_threshold=2.0,
            upper_threshold=5.0,
            abs_threshold=10_000.0,
            sample_period=1.0,
        )
        t = 0.0
        for _ in range(20):
            sd.add_value(t, 100.0)
            t += 1.0
        assert steps == []  # stable series, no steps
        for _ in range(20):
            sd.add_value(t, 200.0)
            t += 1.0
        assert len(steps) == 1  # one step detected
        assert abs(steps[0] - 200.0) < 10.0


class TestTypes:
    def test_prefix_normalization(self):
        from openr_tpu.types import IpPrefix

        p = IpPrefix("10.0.0.5/24")
        assert p.prefix == "10.0.0.0/24"
        assert p.is_v4
        assert p.prefix_length == 24
        assert IpPrefix("fc00::1/64").prefix == "fc00::/64"
        assert not IpPrefix("fc00::1/64").is_v4

    def test_prefix_key_roundtrip(self):
        from openr_tpu.types import IpPrefix, parse_prefix_key, prefix_key

        k = prefix_key("node-1", IpPrefix("10.1.0.0/16"), "area51")
        node, area, pfx = parse_prefix_key(k)
        assert node == "node-1"
        assert area == "area51"
        assert pfx == IpPrefix("10.1.0.0/16")

        node, area, pfx = parse_prefix_key(prefix_key("node-2"))
        assert node == "node-2" and area is None and pfx is None

    def test_value_merge_hash(self):
        from openr_tpu.types import generate_hash

        h1 = generate_hash(1, "node", b"abc")
        h2 = generate_hash(1, "node", b"abc")
        h3 = generate_hash(2, "node", b"abc")
        assert h1 == h2
        assert h1 != h3
