"""TTL expiry under lost refreshes — the immortal-key regression
(ISSUE 18).

A key with a finite TTL stays alive only while its originator keeps
refreshing it. On a hostile network the refreshes get lost but full
syncs keep succeeding — and a store that serves the ORIGINAL ttl out of
a dump re-arms a dead originator's key to full lifetime on every sync,
so the key never ages out anywhere. The fix
(KvStoreDb._update_publication_ttl) serves the REMAINING lifetime from
the countdown deadline, so repeated syncs only ever shorten the clock.

The main test is a randomized differential run: the same seeded
sync-storm schedule executed twice, once with the originator dead (the
key must expire on every survivor despite continuous re-syncing) and
once with the originator refreshing (the identical schedule must NOT
expire the key) — proving expiry is driven by the lost refreshes, not
by the sync machinery eating live keys.
"""

import asyncio
import random

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreParams,
    PeerSpec,
)
from openr_tpu.types import Value


def run(coro, timeout=30.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def make_stores(names):
    transport = InProcessTransport()
    return {
        name: KvStore(
            name,
            ["0"],
            transport,
            params=KvStoreParams(node_id=name),
        )
        for name in names
    }, transport


async def _sync_storm(stores, rng, rounds, gap_s, refresher=None):
    """Seeded peer-to-peer sync pressure: each round, a random store
    serves a full dump straight into another — the wire-level shape of a
    full sync, with zero loss. With `refresher`, the originator also
    re-advertises the key each round (the healthy-network control arm)."""
    names = sorted(stores)
    for i in range(rounds):
        if refresher is not None:
            refresher(i)
        src, dst = rng.sample(names, 2)
        pub = stores[src].handle_dump("0", None)
        if pub.key_vals:
            stores[dst].handle_set_key_vals("0", pub.key_vals, [src])
        await asyncio.sleep(gap_s)


class TestTtlUnderLostRefreshes:
    def test_differential_dead_vs_refreshing_originator(self):
        async def arm(refresh, tail_s):
            stores, _ = make_stores(["a", "b", "c"])
            stores["a"].add_peers({"b": PeerSpec("b")})
            stores["b"].add_peers({"c": PeerSpec("c")})
            await asyncio.sleep(0.05)
            ttl_ms = 400
            stores["a"].set_key(
                "prefix:mortal", Value(1, "origin", b"payload", ttl_ms, 0)
            )
            await asyncio.sleep(0.05)
            for s in stores.values():
                assert s.get_key("prefix:mortal") is not None
            refresher = None
            if refresh:
                # the originator survives: ttl-refresh (no value body,
                # bumped ttl_version) re-arms the countdown every round
                def refresher(i):
                    stores["a"].set_key(
                        "prefix:mortal",
                        Value(1, "origin", None, ttl_ms, i + 1),
                    )

            # 25 rounds x 40ms = 1s of sync pressure across a 400ms ttl:
            # every key would be re-armed ~2.5x over if dumps served the
            # original ttl
            rng = random.Random(1805)
            await _sync_storm(
                stores, rng, rounds=25, gap_s=0.04, refresher=refresher
            )
            await asyncio.sleep(tail_s)
            alive = {
                name: s.get_key("prefix:mortal") is not None
                for name, s in stores.items()
            }
            expired = {
                name: s.counters.get("kvstore.expired_key_vals", 0)
                for name, s in stores.items()
            }
            for s in stores.values():
                s.stop()
            return alive, expired

        async def body():
            # dead originator: the same sync schedule must age the key
            # out everywhere — any survivor still serving it has been
            # re-armed by a full sync (the immortal-key bug); the 0.6s
            # tail outlives the final 400ms countdown
            alive, expired = await arm(refresh=False, tail_s=0.6)
            assert not any(alive.values()), (
                f"immortal key: still alive on {alive} after ttl + "
                f"sync storm with a dead originator"
            )
            assert all(n >= 1 for n in expired.values()), expired
            # refreshing originator, identical seeded schedule: the key
            # must survive the storm — expiry above is the lost
            # refreshes, not the sync machinery eating live keys. The
            # check lands inside the last refresh's 400ms window (the
            # originator stops with the storm, so a long tail would be
            # an honest age-out, not a differential signal)
            alive, _ = await arm(refresh=True, tail_s=0.1)
            assert all(alive.values()), (
                f"live key aged out under refreshes: {alive}"
            )

        run(body())

    def test_dump_serves_remaining_ttl(self):
        """The unit-level pin for the fix: a dump taken mid-countdown
        carries the remaining lifetime, never the original."""

        async def body():
            stores, _ = make_stores(["a"])
            stores["a"].set_key(
                "prefix:k", Value(1, "origin", b"x", 1000, 0)
            )
            await asyncio.sleep(0.3)
            pub = stores["a"].handle_dump("0", None)
            served = pub.key_vals["prefix:k"].ttl
            assert served < 1000, "dump re-armed the key to full ttl"
            assert 400 <= served <= 750, served
            # the stored value keeps the ORIGINAL ttl (the countdown is
            # tracked separately); only the wire copy is rewritten
            assert stores["a"].get_key("prefix:k").ttl == 1000
            stores["a"].stop()

        run(body())
