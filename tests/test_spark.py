"""Spark + LinkMonitor tests mirroring openr/spark/tests/SparkTest.cpp and
openr/link-monitor/tests/LinkMonitorTest.cpp core scenarios, over MockIo."""

import asyncio

import pytest

from openr_tpu.kvstore import InProcessTransport, KvStore, KvStoreParams
from openr_tpu.linkmonitor import LinkMonitor, LinkMonitorConfig
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.spark import (
    MockIoNetwork,
    NeighborEventType,
    Spark,
    SparkConfig,
    SparkNeighState,
)
from openr_tpu.types import adj_key
from openr_tpu.utils import serializer


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def fast_config(name, **kw):
    defaults = dict(
        fastinit_hello_time=0.02,
        hello_time=0.5,
        handshake_time=0.02,
        keepalive_time=0.05,
        hold_time=0.25,
        graceful_restart_time=0.5,
        negotiate_hold_time=0.2,
    )
    defaults.update(kw)
    return SparkConfig(node_name=name, **defaults)


def make_spark(name, net, **kw):
    q = ReplicateQueue()
    spark = Spark(fast_config(name, **kw), net.provider(name), q)
    return spark, q.get_reader(), q


async def wait_event(reader, event_type, timeout=5.0):
    while True:
        ev = await asyncio.wait_for(reader.get(), timeout)
        if ev.event_type == event_type:
            return ev


class TestSparkDiscovery:
    def test_two_nodes_establish(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"), latency_ms=2)
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            up_a = await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            up_b = await wait_event(rb, NeighborEventType.NEIGHBOR_UP)
            assert up_a.node_name == "b"
            assert up_a.local_if_name == "if-a"
            assert up_a.remote_if_name == "if-b"
            assert up_a.area == "0"
            assert up_b.node_name == "a"
            # transport addresses learned through the handshake
            assert up_a.transport_address_v6 == "fe80::1"
            spark_a.stop()
            spark_b.stop()

        run(body())

    def test_rtt_measured(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"), latency_ms=20)
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            nbr = spark_a.get_neighbors(SparkNeighState.ESTABLISHED)[0]
            # rtt should be about 2x 20ms = 40000us (mock latency)
            assert 20_000 < nbr.rtt_us < 120_000, nbr.rtt_us
            spark_a.stop()
            spark_b.stop()

        run(body())

    def test_hold_expiry_neighbor_down(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            # kill b entirely (no graceful restart)
            spark_b.stop()
            down = await wait_event(ra, NeighborEventType.NEIGHBOR_DOWN)
            assert down.node_name == "b"
            spark_a.stop()

        run(body())

    def test_graceful_restart_flow(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, qb = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            # b announces graceful restart, then "restarts"
            spark_b.flood_restarting()
            restarting = await wait_event(
                ra, NeighborEventType.NEIGHBOR_RESTARTING
            )
            assert restarting.node_name == "b"
            nbr = spark_a.get_neighbors(SparkNeighState.RESTART)
            assert len(nbr) == 1
            spark_b.stop()
            # new incarnation of b comes back before GR expires
            spark_b2, rb2, _ = make_spark("b", net)
            spark_b2.update_interfaces(["if-b"])
            restarted = await wait_event(
                ra, NeighborEventType.NEIGHBOR_RESTARTED
            )
            assert restarted.node_name == "b"
            assert spark_a.get_neighbors(SparkNeighState.ESTABLISHED)
            spark_a.stop()
            spark_b2.stop()

        run(body())

    def test_gr_expiry_neighbor_down(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            spark_b.flood_restarting()
            assert spark_b.counters.get("spark.gr_hellos_sent") == 1
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTING)
            assert spark_a.counters.get("spark.gr_holds_active") == 1
            spark_b.stop()  # never comes back
            down = await wait_event(ra, NeighborEventType.NEIGHBOR_DOWN)
            assert down.node_name == "b"
            assert spark_a.counters.get("spark.gr_holds_active") == 0
            assert spark_a.counters.get("spark.gr_hold_expiries") == 1
            spark_a.stop()

        run(body())

    def test_gr_hold_counters_roundtrip_on_restart(self):
        """The gauge enters on NEIGHBOR_RESTARTING and exits cleanly on
        NEIGHBOR_RESTARTED (no expiry counted)."""

        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            spark_b.flood_restarting()
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTING)
            assert spark_a.counters.get("spark.gr_holds_active") == 1
            spark_b.stop()
            spark_b2, rb2, _ = make_spark("b", net)
            spark_b2.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTED)
            assert spark_a.counters.get("spark.gr_holds_active") == 0
            assert spark_a.counters.get("spark.gr_hold_expiries", 0) == 0
            spark_a.stop()
            spark_b2.stop()

        run(body())

    def test_double_restart_extends_gr_window(self):
        """A second restarting hello while the neighbor is already in
        RESTART re-arms the GR timer: back-to-back restarts survive as
        long as each announcement lands inside the previous window."""

        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net, graceful_restart_time=0.6)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            spark_b.flood_restarting()
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTING)
            # second announcement 0.35s in: without the re-arm the hold
            # would expire at 0.6s; with it, the window restarts
            await asyncio.sleep(0.35)
            spark_b.flood_restarting()
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTING)
            await asyncio.sleep(0.4)  # past the ORIGINAL expiry
            assert spark_a.get_neighbors(SparkNeighState.RESTART), (
                "GR window was not re-armed by the second restart"
            )
            assert spark_a.counters.get("spark.gr_holds_active") == 1
            spark_b.stop()
            spark_b2, rb2, _ = make_spark("b", net)
            spark_b2.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTED)
            assert spark_a.get_neighbors(SparkNeighState.ESTABLISHED)
            assert spark_a.counters.get("spark.gr_holds_active") == 0
            spark_a.stop()
            spark_b2.stop()

        run(body())

    def test_gr_expiry_then_late_return_is_fresh_discovery(self):
        """GR expiry mid-boot: the neighbor comes back AFTER the window
        expired — the adjacency was torn down (NEIGHBOR_DOWN) and the
        late return is an ordinary fresh NEIGHBOR_UP, not RESTARTED."""

        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            spark_a, ra, _ = make_spark("a", net)
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            spark_b.flood_restarting()
            await wait_event(ra, NeighborEventType.NEIGHBOR_RESTARTING)
            spark_b.stop()
            await wait_event(ra, NeighborEventType.NEIGHBOR_DOWN)
            assert spark_a.counters.get("spark.gr_hold_expiries") == 1
            spark_b2, rb2, _ = make_spark("b", net)
            spark_b2.update_interfaces(["if-b"])
            up = await wait_event(ra, NeighborEventType.NEIGHBOR_UP)
            assert up.node_name == "b"
            assert spark_a.get_neighbors(SparkNeighState.ESTABLISHED)
            spark_a.stop()
            spark_b2.stop()

        run(body())

    def test_area_negotiation_failure(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            # a only accepts neighbors matching 'x.*' into area 1
            spark_a, ra, _ = make_spark(
                "a", net, area_configs=[("1", "x.*")]
            )
            spark_b, rb, _ = make_spark("b", net)
            spark_a.update_interfaces(["if-a"])
            spark_b.update_interfaces(["if-b"])
            await asyncio.sleep(0.5)
            assert spark_a.get_neighbors(SparkNeighState.ESTABLISHED) == []
            assert spark_a.counters.get("spark.invalid_area", 0) >= 1
            spark_a.stop()
            spark_b.stop()

        run(body())

    def test_three_nodes_on_lan(self):
        async def body():
            # hub-like wiring: every pair connected (multicast LAN emulation)
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            net.connect(("a", "if-a"), ("c", "if-c"))
            net.connect(("b", "if-b"), ("c", "if-c"))
            sparks = {}
            readers = {}
            for n in "abc":
                sparks[n], readers[n], _ = make_spark(n, net)
                sparks[n].update_interfaces([f"if-{n}"])
            for n in "abc":
                await wait_event(readers[n], NeighborEventType.NEIGHBOR_UP)
            await asyncio.sleep(0.3)
            for n in "abc":
                established = sparks[n].get_neighbors(
                    SparkNeighState.ESTABLISHED
                )
                assert len(established) == 2, (n, established)
            for s in sparks.values():
                s.stop()

        run(body())


class TestLinkMonitor:
    def make_node(self, name, net, transport, loop_areas=("0",)):
        kv = KvStore(
            name, list(loop_areas), transport,
            params=KvStoreParams(node_id=name),
        )
        events = ReplicateQueue()
        spark = Spark(fast_config(name), net.provider(name), events)
        lm = LinkMonitor(
            LinkMonitorConfig(
                node_name=name, node_label=100 + ord(name[-1])
            ),
            events.get_reader(),
            kv,
            spark,
        )
        lm.start()
        return kv, spark, lm

    def test_adjacency_advertised_into_kvstore(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            kv_b, spark_b, lm_b = self.make_node("b", net, transport)
            lm_a.update_interface("if-a", True)
            lm_b.update_interface("if-b", True)

            async def adj_in_store():
                while True:
                    val = kv_a.get_key(adj_key("a"))
                    if val is not None:
                        db = serializer.loads(val.value)
                        if db.adjacencies:
                            return db
                    await asyncio.sleep(0.02)

            adj_db = await asyncio.wait_for(adj_in_store(), 5)
            assert adj_db.adjacencies[0].other_node_name == "b"
            assert adj_db.node_label == lm_a.config.node_label
            # peering established -> b's store learns a's key by flooding
            async def synced():
                while kv_b.get_key(adj_key("a")) is None:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(synced(), 5)
            # and vice versa
            async def synced_b():
                while kv_a.get_key(adj_key("b")) is None:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(synced_b(), 5)
            for x in (lm_a, lm_b):
                x.stop()
            for s in (spark_a, spark_b):
                s.stop()

        run(body())

    def test_neighbor_down_withdraws_adjacency(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            kv_b, spark_b, lm_b = self.make_node("b", net, transport)
            lm_a.update_interface("if-a", True)
            lm_b.update_interface("if-b", True)

            async def until(pred):
                while not pred():
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(
                until(lambda: ("b", "if-a") in lm_a.adjacencies), 5
            )
            spark_b.stop()  # hard kill
            await asyncio.wait_for(
                until(lambda: ("b", "if-a") not in lm_a.adjacencies), 5
            )
            # advertised db now empty
            await asyncio.wait_for(
                until(
                    lambda: (
                        kv_a.get_key(adj_key("a")) is not None
                        and not serializer.loads(
                            kv_a.get_key(adj_key("a")).value
                        ).adjacencies
                    )
                ),
                5,
            )
            # peering torn down
            assert "b" not in kv_a.dbs["0"].get_peers()
            lm_a.stop()
            lm_b.stop()
            spark_a.stop()

        run(body())

    def test_drain_sets_overload_bit(self):
        async def body():
            net = MockIoNetwork()
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            lm_a.set_node_overload(True)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert db.is_overloaded
            lm_a.set_node_overload(False)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert not db.is_overloaded
            lm_a.stop()
            spark_a.stop()

        run(body())

    def test_link_metric_override(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            kv_b, spark_b, lm_b = self.make_node("b", net, transport)
            lm_a.update_interface("if-a", True)
            lm_b.update_interface("if-b", True)

            async def until(pred):
                while not pred():
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(
                until(lambda: ("b", "if-a") in lm_a.adjacencies), 5
            )
            lm_a.set_link_metric("if-a", 42)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert db.adjacencies[0].metric == 42
            lm_a.set_link_metric("if-a", None)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert db.adjacencies[0].metric == 1
            for x in (lm_a, lm_b):
                x.stop()
            for s in (spark_a, spark_b):
                s.stop()

        run(body())

    def test_adjacency_metric_override_wins_over_link_metric(self):
        async def body():
            net = MockIoNetwork()
            net.connect(("a", "if-a"), ("b", "if-b"))
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            kv_b, spark_b, lm_b = self.make_node("b", net, transport)
            lm_a.update_interface("if-a", True)
            lm_b.update_interface("if-b", True)

            async def until(pred):
                while not pred():
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(
                until(lambda: ("b", "if-a") in lm_a.adjacencies), 5
            )
            lm_a.set_link_metric("if-a", 42)
            lm_a.set_adjacency_metric("if-a", "b", 7)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert db.adjacencies[0].metric == 7
            lm_a.set_adjacency_metric("if-a", "b", None)
            await asyncio.sleep(0.05)
            db = serializer.loads(kv_a.get_key(adj_key("a")).value)
            assert db.adjacencies[0].metric == 42
            for x in (lm_a, lm_b):
                x.stop()
            for s in (spark_a, spark_b):
                s.stop()

        run(body())

    def test_flap_dampening(self):
        async def body():
            net = MockIoNetwork()
            transport = InProcessTransport()
            kv_a, spark_a, lm_a = self.make_node("a", net, transport)
            lm_a.update_interface("flappy", True)
            assert spark_a.interfaces  # first up is immediate
            # flap repeatedly: interface goes into dampening
            for _ in range(4):
                lm_a.update_interface("flappy", False)
                lm_a.update_interface("flappy", True)
            assert not lm_a.interfaces["flappy"].is_active()
            assert "flappy" not in spark_a.interfaces
            # after backoff expires it comes back
            await asyncio.sleep(1.1)
            assert "flappy" in spark_a.interfaces
            lm_a.stop()
            spark_a.stop()

        run(body())
