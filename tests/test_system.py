"""Whole-stack multi-node system tests.

Equivalent of openr/tests/OpenrSystemTest.cpp:247-535: ring topologies of
OpenrWrapper nodes over the mock fabric, asserting end-to-end route
convergence (discovery → adjacency → KvStore flood → SPF → FIB
programming), failure reaction, and drain behavior."""

import asyncio

import pytest

from openr_tpu.testing import OpenrWrapper, VirtualNetwork
from openr_tpu.testing.wrapper import wait_until


def run(coro, timeout=60.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def build_ring(net, n):
    """n-node ring: node-i connects to node-(i+1) via iface ring-<i>."""
    for i in range(n):
        net.add_node(f"node-{i}", loopback_prefix=f"10.{i}.0.0/24")
    for i in range(n):
        j = (i + 1) % n
        net.connect(
            f"node-{i}", f"if-{i}-{j}", f"node-{j}", f"if-{j}-{i}"
        )


class TestTwoNodes:
    def test_adjacency_and_routes(self):
        async def body():
            net = VirtualNetwork()
            a = net.add_node("node-a", loopback_prefix="10.1.0.0/24")
            b = net.add_node("node-b", loopback_prefix="10.2.0.0/24")
            await net.start_all()
            net.connect("node-a", "eth0", "node-b", "eth0")

            # discovery → adjacency on both sides
            await wait_until(lambda: a.adjacent_nodes() == ["node-b"])
            await wait_until(lambda: b.adjacent_nodes() == ["node-a"])
            # each programs a route to the other's loopback
            await wait_until(
                lambda: "10.2.0.0/24" in a.programmed_prefixes()
            )
            await wait_until(
                lambda: "10.1.0.0/24" in b.programmed_prefixes()
            )
            # no route to self
            assert "10.1.0.0/24" not in a.programmed_prefixes()
            await net.stop_all()

        run(body())

    def test_link_failure_withdraws_routes(self):
        async def body():
            net = VirtualNetwork()
            a = net.add_node("node-a", loopback_prefix="10.1.0.0/24")
            b = net.add_node("node-b", loopback_prefix="10.2.0.0/24")
            await net.start_all()
            net.connect("node-a", "eth0", "node-b", "eth0")
            await wait_until(
                lambda: "10.2.0.0/24" in a.programmed_prefixes()
            )

            net.fail_link("node-a", "eth0", "node-b", "eth0")
            # hold timer expiry → neighbor down → route withdrawn
            await wait_until(
                lambda: "10.2.0.0/24" not in a.programmed_prefixes(),
                timeout=30,
            )
            await net.stop_all()

        run(body())


class TestRing:
    def test_three_node_ring_full_convergence(self):
        async def body():
            net = VirtualNetwork()
            build_ring(net, 3)
            await net.start_all()
            for i in range(3):
                wrapper = net.wrappers[f"node-{i}"]
                others = {
                    f"10.{j}.0.0/24" for j in range(3) if j != i
                }
                await wait_until(
                    lambda w=wrapper, o=others: o.issubset(
                        set(w.programmed_prefixes())
                    ),
                    timeout=30,
                )
                # ring: every node has exactly 2 neighbors
                assert len(wrapper.adjacent_nodes()) == 2
            await net.stop_all()

        run(body())

    def test_ring_reroutes_around_failed_link(self):
        async def body():
            net = VirtualNetwork()
            build_ring(net, 3)
            await net.start_all()
            a = net.wrappers["node-0"]
            await wait_until(
                lambda: {"10.1.0.0/24", "10.2.0.0/24"}.issubset(
                    set(a.programmed_prefixes())
                ),
                timeout=30,
            )
            # direct link 0-1 dies; node-0 must reroute to node-1 via node-2
            route_before = a.programmed_route("10.1.0.0/24")
            assert route_before is not None
            net.fail_link("node-0", "if-0-1", "node-1", "if-1-0")

            async def rerouted():
                route = a.programmed_route("10.1.0.0/24")
                return (
                    route is not None
                    and all(
                        nh.iface == "if-0-2" for nh in route.nexthops
                    )
                    and len(route.nexthops) > 0
                )

            await wait_until(
                lambda: a.programmed_route("10.1.0.0/24") is not None
                and all(
                    nh.iface == "if-0-2"
                    for nh in a.programmed_route("10.1.0.0/24").nexthops
                ),
                timeout=30,
            )
            await net.stop_all()

        run(body())


class TestDrain:
    def test_node_overload_diverts_transit_traffic(self):
        async def body():
            # line topology a - b - c plus direct a - c: overloading b
            # must keep a→c traffic off b
            net = VirtualNetwork()
            for name, prefix in (
                ("node-a", "10.1.0.0/24"),
                ("node-b", "10.2.0.0/24"),
                ("node-c", "10.3.0.0/24"),
            ):
                net.add_node(name, loopback_prefix=prefix)
            await net.start_all()
            net.connect("node-a", "ab", "node-b", "ba")
            net.connect("node-b", "bc", "node-c", "cb")
            net.connect("node-a", "ac", "node-c", "ca", latency_ms=1.0)
            a = net.wrappers["node-a"]
            await wait_until(
                lambda: {"10.2.0.0/24", "10.3.0.0/24"}.issubset(
                    set(a.programmed_prefixes())
                ),
                timeout=30,
            )
            # drain node-b
            net.wrappers["node-b"].daemon.link_monitor.set_node_overload(
                True
            )
            # a's route to c must avoid b (iface 'ac' only); metric-equal
            # paths would otherwise ECMP through b
            await wait_until(
                lambda: a.programmed_route("10.3.0.0/24") is not None
                and all(
                    nh.iface == "ac"
                    for nh in a.programmed_route("10.3.0.0/24").nexthops
                ),
                timeout=30,
            )
            # b's loopback still reachable (overloaded nodes accept
            # terminating traffic, LinkState.cpp overload semantics)
            assert "10.2.0.0/24" in a.programmed_prefixes()
            await net.stop_all()

        run(body())
