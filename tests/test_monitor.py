"""Monitor + Watchdog tests (openr/monitor, openr/watchdog equivalents)."""

import asyncio

from openr_tpu.messaging import RWQueue
from openr_tpu.monitor import LogSample, Monitor, Watchdog, WatchdogConfig
from openr_tpu.utils.counters import Histogram


def run(coro, timeout=10.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


class TestLogSample:
    def test_roundtrip(self):
        sample = LogSample(timestamp=1000)
        sample.add_string("event", "NB_UP").add_int("count", 3)
        sample.add_string_vector("perf_events", ["a", "b"])
        decoded = LogSample.from_json(sample.to_json())
        assert decoded.get("event") == "NB_UP"
        assert decoded.get("count") == 3
        assert decoded.get("perf_events") == ["a", "b"]


class TestMonitor:
    def test_event_log_ring_bounded(self):
        async def body():
            q = RWQueue()
            mon = Monitor("n1", q, max_event_log=5)
            mon.start()
            for i in range(10):
                q.push(LogSample().add_int("i", i))
            await asyncio.sleep(0.05)
            logs = mon.get_event_logs()
            assert len(logs) == 5
            assert logs[-1].get("i") == 9
            assert logs[0].get("i") == 5
            # node name auto-filled
            assert logs[0].get("node_name") == "n1"
            mon.stop()

        run(body())

    def test_counter_aggregation(self):
        class FakeModule:
            counters = {"decision.spf_runs": 12}

        mon = Monitor("n1")
        mon.register_module("decision", FakeModule())
        counters = mon.get_counters()
        assert counters["decision.spf_runs"] == 12
        assert "process.uptime.seconds" in counters

    def test_histogram_aggregation_merges_across_modules(self):
        """Same-name histograms from different modules fold into one
        exported distribution; module-owned histograms stay untouched."""

        def module(*values):
            class FakeModule:
                histograms = {}

            h = Histogram()
            for v in values:
                h.record(v)
            FakeModule.histograms = {"convergence.e2e_ms": h}
            return FakeModule()

        a, b = module(1.0, 3.0), module(10.0)
        mon = Monitor("n1")
        mon.register_module("decision", a)
        mon.register_module("fib", b)
        # a module without histograms must not break aggregation
        mon.register_module("bare", object())
        hists = mon.get_histograms()
        e2e = hists["convergence.e2e_ms"]
        assert e2e["count"] == 3
        assert e2e["min"] == 1.0 and e2e["max"] == 10.0
        # export merged copies, never the modules' own objects
        assert a.histograms["convergence.e2e_ms"].count == 2
        assert b.histograms["convergence.e2e_ms"].count == 1


class TestWatchdog:
    def test_stall_fires(self):
        async def body():
            fired = []
            wd = Watchdog(
                WatchdogConfig(interval_s=0.05, thread_timeout_s=0.2),
                fire=fired.append,
            )
            wd.add_module("decision")
            # stall: cancel the heartbeat task to simulate a stuck module
            wd._tasks["decision"].cancel()
            wd.start()
            await asyncio.sleep(0.5)
            assert fired and "decision" in fired[0]
            wd.stop()

        run(body())

    def test_healthy_module_does_not_fire(self):
        async def body():
            fired = []
            wd = Watchdog(
                WatchdogConfig(interval_s=0.05, thread_timeout_s=0.3),
                fire=fired.append,
            )
            wd.add_module("kvstore")
            wd.start()
            await asyncio.sleep(0.4)
            assert not fired
            wd.stop()

        run(body())

    def test_memory_limit_fires(self):
        fired = []
        wd = Watchdog(
            WatchdogConfig(thread_timeout_s=1000, max_memory_mb=1),
            fire=fired.append,
        )
        wd.check_once()
        assert fired and "RSS" in fired[0]
