"""Exporter tests: Prometheus exposition render/parse round-trip, the
non-resetting cumulative view (reset-race double-consumer contract), the
push loop with backoff, and the ctrl scrape surfaces (getMetricsText +
the HTTP-ish GET /metrics handler on the ctrl port)."""

import asyncio

import pytest

from openr_tpu.ctrl import CtrlClient, CtrlServer
from openr_tpu.monitor import (
    LogSample,
    MetricsExporter,
    Monitor,
    parse_metrics_text,
    render_metrics_text,
)
from openr_tpu.monitor.exporter import prom_name
from openr_tpu.monitor.spans import Span
from openr_tpu.utils.counters import Histogram


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


def _module(counters=None, histograms=None):
    class Fake:
        pass

    mod = Fake()
    mod.counters = dict(counters or {})
    mod.histograms = dict(histograms or {})
    return mod


def _hist(*values):
    h = Histogram()
    for v in values:
        h.record(v)
    return h


def _monitor_with_registry():
    mon = Monitor("n1", rollup_window_s=1.0)
    mon.register_module(
        "decision",
        _module(
            counters={
                "decision.spf.full_solves": 4,
                "decision.spf.rounds_last": 9,  # gauge-typed
            },
            histograms={"decision.spf.solve_ms": _hist(0.5, 2.0, 40.0)},
        ),
    )
    mon.register_module(
        "fib",
        _module(
            counters={"fib.num_of_route_updates": 7},
            histograms={"fib.program_ms": _hist(1.25)},
        ),
    )
    return mon


class TestRenderParse:
    def test_round_trip_covers_every_registered_name(self):
        """The acceptance contract: the exposition parses and covers every
        registered counter and histogram (the exporter's own overhead
        metrics appear from the second scrape on, so scrape twice)."""
        mon = _monitor_with_registry()
        exporter = MetricsExporter(mon)
        mon.register_module("monitor", exporter)
        exporter.render()
        text = exporter.render()
        parsed = parse_metrics_text(text)
        exported = set(parsed["samples"])
        for name in mon.get_counters():
            assert prom_name(name) in exported, name
        for name in mon.get_cumulative_histograms():
            assert prom_name(name) + "_count" in exported, name
        # self-telemetry rode along
        assert parsed["counters"]["openr_monitor_exporter_scrapes"] == 1
        assert "openr_monitor_exporter_render_ms" in parsed["histograms"]

    def test_counter_and_histogram_values_round_trip(self):
        counters = {"decision.spf.full_solves": 4}
        hist = _hist(0.5, 2.0, 40.0)
        text = render_metrics_text(
            counters, {"decision.spf.solve_ms": hist}, node_name="n1"
        )
        parsed = parse_metrics_text(text)
        assert parsed["counters"]["openr_decision_spf_full_solves"] == 4
        h = parsed["histograms"]["openr_decision_spf_solve_ms"]
        assert h["count"] == 3
        assert h["sum"] == pytest.approx(42.5)
        # bucket series is cumulative and ends at the +Inf total
        assert h["buckets"]["+Inf"] == 3
        assert sorted(h["buckets"].values())[-1] == 3

    def test_gauge_vs_counter_typing(self):
        text = render_metrics_text(
            {
                "decision.spf.rounds_last": 3,
                "decision.spf.fallback_active": 1,
                "process.uptime.seconds": 12,
                "decision.spf.full_solves": 9,
            },
            {},
        )
        types = parse_metrics_text(text)["types"]
        assert types["openr_decision_spf_rounds_last"] == "gauge"
        assert types["openr_decision_spf_fallback_active"] == "gauge"
        assert types["openr_process_uptime_seconds"] == "gauge"
        assert types["openr_decision_spf_full_solves"] == "counter"

    def test_rollup_split_rides_the_exposition(self):
        mon = Monitor("n1", rollup_window_s=60.0)
        span = Span("flap")
        span.mark("kvstore.publish")
        span.mark("fib.program")
        mon.add_event_log(span.to_log_sample())
        text = render_metrics_text(
            {}, {}, node_name="n1", rollup=mon.rollup
        )
        parsed = parse_metrics_text(text)
        assert parsed["counters"]["openr_monitor_rollup_events_total"] == 1
        assert parsed["gauges"]["openr_convergence_window_events"] == 1
        assert (
            parsed["types"]["openr_convergence_window_e2e_ms"] == "gauge"
        )

    def test_malformed_text_raises(self):
        with pytest.raises(ValueError):
            parse_metrics_text("this is { not exposition\n")

    def test_node_label_escaped(self):
        text = render_metrics_text(
            {"decision.adj_db_update": 1}, {}, node_name='we"ird'
        )
        parsed = parse_metrics_text(text)
        assert parsed["counters"]["openr_decision_adj_db_update"] == 1


class TestResetRace:
    def test_exporter_view_survives_reset_on_read(self):
        """The double-consumer contract: a --reset histogram snapshot
        racing the exporter must not drop samples from the scrape — the
        cumulative view folds in everything a reset cleared."""
        hist = _hist(1.0, 2.0)
        mon = Monitor("n1")
        mon.register_module(
            "decision", _module(histograms={"decision.spf.solve_ms": hist})
        )
        # consumer A: reset-on-read dashboard takes a snapshot
        snap1 = mon.get_histograms(reset=True)
        assert snap1["decision.spf.solve_ms"]["count"] == 2
        assert hist.count == 0  # sources cleared
        hist.record(5.0)
        # consumer B: the exporter still sees ALL three samples
        cum = mon.get_cumulative_histograms()
        assert cum["decision.spf.solve_ms"].count == 3
        assert cum["decision.spf.solve_ms"].max == 5.0
        # a second reset window and another scrape: still cumulative
        snap2 = mon.get_histograms(reset=True)
        assert snap2["decision.spf.solve_ms"]["count"] == 1
        cum = mon.get_cumulative_histograms()
        assert cum["decision.spf.solve_ms"].count == 3
        # while the reset consumer keeps seeing disjoint windows
        assert mon.get_histograms(reset=True)[
            "decision.spf.solve_ms"
        ]["count"] == 0


class TestPushLoop:
    def test_push_to_file_sink(self, tmp_path):
        """Push mode renders on the interval and atomically replaces the
        sink file with parseable exposition text."""
        target = tmp_path / "metrics.prom"

        async def body():
            mon = _monitor_with_registry()
            exporter = MetricsExporter(
                mon,
                push_target=str(target),
                push_interval_s=0.02,
            )
            mon.register_module("monitor", exporter)
            exporter.start()
            try:
                for _ in range(200):
                    if (
                        target.exists()
                        and exporter.counters.get(
                            "monitor.exporter.pushes", 0
                        )
                        >= 2
                    ):
                        break
                    await asyncio.sleep(0.01)
                parsed = parse_metrics_text(target.read_text())
                assert (
                    "openr_decision_spf_full_solves" in parsed["counters"]
                )
                assert (
                    exporter.counters["monitor.exporter.pushes"] >= 2
                )
                assert (
                    exporter.counters.get(
                        "monitor.exporter.push_failures", 0
                    )
                    == 0
                )
            finally:
                exporter.stop()

        run(body())

    def test_push_failure_backs_off_and_recovers(self, tmp_path):
        """An injected sink failure counts a push_failure, arms the
        backoff, and the loop keeps going (later pushes succeed)."""
        from openr_tpu.testing.faults import FaultInjector, injected

        target = tmp_path / "metrics.prom"

        async def body():
            mon = _monitor_with_registry()
            exporter = MetricsExporter(
                mon,
                push_target=str(target),
                push_interval_s=0.01,
                backoff_min_s=0.01,
                backoff_max_s=0.05,
            )
            with injected(FaultInjector(seed=1)) as inj:
                inj.arm("monitor.exporter.push", times=2)
                exporter.start()
                try:
                    for _ in range(400):
                        if (
                            exporter.counters.get(
                                "monitor.exporter.pushes", 0
                            )
                            >= 1
                        ):
                            break
                        await asyncio.sleep(0.01)
                finally:
                    exporter.stop()
                assert (
                    exporter.counters["monitor.exporter.push_failures"]
                    == 2
                )
                assert exporter.counters["monitor.exporter.pushes"] >= 1
                assert inj.fired("monitor.exporter.push") == 2

        run(body())

    def test_socket_sink_target_parsing(self):
        from openr_tpu.monitor.exporter import _socket_target

        assert _socket_target("127.0.0.1:9091") == ("127.0.0.1", 9091)
        assert _socket_target("/var/run/metrics.prom")[1] is None
        assert _socket_target("relative/path.prom")[1] is None

    def test_push_to_socket_sink(self):
        """host:port sinks get one TCP write per interval."""

        async def body():
            received = []

            async def sink(reader, writer):
                received.append(await reader.read())
                writer.close()

            server = await asyncio.start_server(sink, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            mon = _monitor_with_registry()
            exporter = MetricsExporter(
                mon,
                push_target=f"127.0.0.1:{port}",
                push_interval_s=0.02,
            )
            exporter.start()
            try:
                for _ in range(200):
                    if received:
                        break
                    await asyncio.sleep(0.01)
            finally:
                exporter.stop()
                server.close()
                await server.wait_closed()
            assert received
            parsed = parse_metrics_text(received[0].decode())
            assert "openr_decision_spf_full_solves" in parsed["counters"]

        run(body())


class TestCtrlScrape:
    async def _server(self):
        mon = _monitor_with_registry()
        exporter = MetricsExporter(mon)
        mon.register_module("monitor", exporter)
        server = CtrlServer(
            "scrape-node", port=0, monitor=mon, exporter=exporter
        )
        port = await server.start()
        return server, port

    def test_get_metrics_text_method(self):
        async def body():
            server, port = await self._server()
            client = await CtrlClient("127.0.0.1", port).connect()
            text = await client.call("getMetricsText")
            parsed = parse_metrics_text(text)
            assert (
                parsed["counters"]["openr_decision_spf_full_solves"] == 4
            )
            # same connection still serves JSON afterwards
            assert await client.call("getMyNodeName") == "scrape-node"
            await client.close()
            await server.stop()

        run(body())

    def test_http_get_metrics_on_ctrl_port(self):
        """A stock HTTP GET against the ctrl port returns a one-shot
        text/plain exposition response (the Prometheus scrape path)."""

        async def http_get(port, path):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port
            )
            writer.write(
                f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                "Accept: */*\r\n\r\n".encode()
            )
            await writer.drain()
            raw = await reader.read()
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            return head.decode(), body.decode()

        async def body():
            server, port = await self._server()
            head, text = await http_get(port, "/metrics")
            assert head.startswith("HTTP/1.0 200 OK")
            assert "text/plain; version=0.0.4" in head
            parsed = parse_metrics_text(text)
            assert (
                parsed["counters"]["openr_decision_spf_full_solves"] == 4
            )
            head, _ = await http_get(port, "/nope")
            assert head.startswith("HTTP/1.0 404")
            await server.stop()

        run(body())

    def test_monitorless_fallback_renders_modules(self):
        async def body():
            fib = _module(
                counters={"fib.num_of_route_updates": 2},
                histograms={"fib.program_ms": _hist(3.0)},
            )
            server = CtrlServer("bare-node", port=0, fib=fib)
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            parsed = parse_metrics_text(
                await client.call("getMetricsText")
            )
            assert (
                parsed["counters"]["openr_fib_num_of_route_updates"] == 2
            )
            assert "openr_fib_program_ms" in parsed["histograms"]
            await client.close()
            await server.stop()

        run(body())


class TestLogSampleTimestamp:
    def test_span_rollup_uses_sample_timestamp(self):
        """Spans fold into the window of their LogSample stamp, not the
        drain time — queue lag cannot smear events across windows."""
        mon = Monitor("n1", rollup_window_s=10.0, rollup_max_windows=4)
        span = Span("flap")
        span.mark("fib.program")
        sample = span.to_log_sample()
        sample.timestamp = 1005.0
        mon.add_event_log(sample)
        snap = mon.rollup.snapshot()
        assert snap["windows"][0]["start"] == 1000.0
        assert snap["events_total"] == 1

    def test_non_span_samples_do_not_touch_rollup(self):
        mon = Monitor("n1")
        mon.add_event_log(LogSample().add_string("event", "FLOOD_TRACE"))
        assert mon.rollup.events_total == 0
