"""Differential tests: native C++ SPF oracle vs the Python LinkState oracle
and the TPU batched solver (three independent implementations of the
reference Dijkstra semantics, openr/decision/LinkState.cpp:806-880)."""

import random

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.ops import INF, batched_spf, compile_graph
from openr_tpu.ops.graph import refresh_graph
from openr_tpu.solver.native_spf import NativeSpfSolver, native_spf_available
from openr_tpu.topology import build_adj_dbs, grid_edges

pytestmark = pytest.mark.skipif(
    not native_spf_available(), reason="native toolchain unavailable"
)


def _random_link_state(rng: random.Random, n: int, extra_edges: int):
    """Connected random graph: a random tree plus extra random links, with a
    couple of drained (overloaded) nodes."""
    edges = []
    seen = set()
    for v in range(1, n):
        u = rng.randrange(v)
        edges.append((f"n{u:03d}", f"n{v:03d}", rng.randint(1, 10)))
        seen.add((u, v))
    for _ in range(extra_edges):
        u, v = sorted(rng.sample(range(n), 2))
        if (u, v) in seen:
            continue
        seen.add((u, v))
        edges.append((f"n{u:03d}", f"n{v:03d}", rng.randint(1, 10)))
    overloaded = set(rng.sample([f"n{i:03d}" for i in range(n)], 2))
    ls = LinkState("0")
    for db in build_adj_dbs(edges, overloaded_nodes=overloaded).values():
        ls.update_adjacency_database(db)
    return ls


def _python_oracle(ls: LinkState, graph, src_name: str):
    res = ls.run_spf(src_name)
    dist = np.full(graph.n, INF, dtype=np.int32)
    nh = [set() for _ in range(graph.n)]
    for node, r in res.items():
        i = graph.node_index[node]
        dist[i] = r.metric
        nh[i] = {graph.node_index[h] for h in r.next_hops}
    return dist, nh


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_native_matches_python_oracle_random(seed):
    rng = random.Random(seed)
    n = rng.randint(8, 40)
    ls = _random_link_state(rng, n, extra_edges=n // 2)
    graph = compile_graph(ls)
    solver = NativeSpfSolver(graph)
    for src in range(graph.n):
        d_py, nh_py = _python_oracle(ls, graph, graph.names[src])
        d_c, nh_c = solver.run_with_nexthops(src)
        np.testing.assert_array_equal(d_c, d_py)
        assert nh_c == nh_py, f"src {graph.names[src]}"
    solver.close()


def test_native_matches_tpu_batched_grid():
    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(6)).values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    solver = NativeSpfSolver(graph)
    d_dev = np.asarray(batched_spf(graph, np.arange(graph.n_pad)))
    for src in range(graph.n):
        np.testing.assert_array_equal(d_dev[src, : graph.n], solver.run(src))
    solver.close()


def test_native_weight_patch_tracks_metric_change():
    """A metric change lands on both solvers as a weight patch (the native
    set_weight positions are the CompiledGraph edge positions)."""
    ls = LinkState("0")
    dbs = build_adj_dbs(grid_edges(4))
    for db in dbs.values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    solver = NativeSpfSolver(graph)

    # bump every adjacency metric of one node via an adj-db update
    import dataclasses

    victim = "g1_1"
    db = dbs[victim]
    db = dataclasses.replace(
        db,
        adjacencies=[
            dataclasses.replace(adj, metric=7) for adj in db.adjacencies
        ],
    )
    ls.update_adjacency_database(db)

    graph2 = refresh_graph(graph, ls)
    assert graph2 is not graph and graph2.src is graph.src  # patched, not rebuilt
    changed = np.nonzero(graph2.w != graph.w)[0]
    assert len(changed) > 0
    for pos in changed:
        solver.set_weight(int(pos), int(graph2.w[pos]))

    d_dev = np.asarray(batched_spf(graph2, np.arange(graph2.n_pad)))
    for src in range(graph.n):
        np.testing.assert_array_equal(d_dev[src, : graph.n], solver.run(src))

    # cross-check against a freshly built Python oracle too
    d_py, _ = _python_oracle(ls, graph2, victim)
    np.testing.assert_array_equal(
        solver.run(graph.node_index[victim]), d_py
    )
    solver.close()


def test_native_overload_patch():
    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(4)).values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    solver = NativeSpfSolver(graph)
    mid = graph.node_index["g1_1"]
    solver.set_overloaded(mid, True)

    ls2 = LinkState("0")
    for db in build_adj_dbs(
        grid_edges(4), overloaded_nodes={"g1_1"}
    ).values():
        ls2.update_adjacency_database(db)
    for src_name in ("g0_0", "g3_3", "g1_1"):
        d_py, nh_py = _python_oracle(ls2, graph, src_name)
        d_c, nh_c = solver.run_with_nexthops(graph.node_index[src_name])
        np.testing.assert_array_equal(d_c, d_py)
        assert nh_c == nh_py
    solver.close()


def test_run_many_counts_settled_nodes():
    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(4)).values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    solver = NativeSpfSolver(graph)
    total = solver.run_many(np.arange(graph.n))
    assert total == graph.n * graph.n  # connected grid: all settle
    solver.close()
