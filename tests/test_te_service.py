"""Differentiable-TE service acceptance + fault-domain suite (ISSUE 7).

The tier-1 acceptance criterion lives here: on the deterministic congested
2-pod Clos fixture, `te-optimize` must propose integer weights whose
hard-SPF routing STRICTLY reduces max link utilization vs the initial
uniform weights — verified independently by replaying the proposed changes
onto the compiled graph and re-scoring with the exact-ECMP hard model.
The fault tests drive the `te.optimize` seam through SolverSupervisor:
an injected device fault degrades the optimization to the CPU backend
(identical proposal, `degraded: true` report) without crashing.
"""

import numpy as np
import pytest

from openr_tpu.lsdb import LinkState
from openr_tpu.ops.graph import compile_graph
from openr_tpu.solver import (
    SolverSupervisor,
    SpfSolver,
    SupervisorConfig,
    TpuSpfSolver,
)
from openr_tpu.te import (
    TeService,
    build_demand_scenarios,
    congested_clos_fixture,
    hard_max_util,
    te_edge_arrays,
    uniform_demand_spec,
)
from openr_tpu.testing.faults import injected
from openr_tpu.topology import build_adj_dbs, grid_edges


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def apply_changes(graph, w0_int, changes):
    """Replay a report's proposed weight_changes onto the edge arrays —
    the operator's `breeze lm set-link-metric` step, done by hand."""
    w = w0_int.copy()
    applied = 0
    for change in changes:
        for link, (fwd, rev) in graph.link_edges.items():
            for pos, node in ((fwd, link.n1), (rev, link.n2)):
                if (
                    node == change["node"]
                    and link.other_node_name(node) == change["neighbor"]
                    and link.iface_from_node(node) == change["iface"]
                ):
                    assert int(w[pos]) == change["metric_before"]
                    w[pos] = change["metric_after"]
                    applied += 1
    assert applied == len(changes), "every proposed change must map back"
    return w


class TestAcceptance:
    def test_clos_fixture_strictly_reduces_max_util(self):
        """The acceptance criterion: skewed elephant demand on the 2-pod
        Clos, uniform initial weights — the proposal must strictly reduce
        the hard-SPF max link utilization, re-verified from scratch."""
        edges, spec = congested_clos_fixture()
        ls = build_ls(edges)
        svc = TeService("l0_0", {"0": ls})
        report = svc.optimize({"demands": spec, "steps": 60, "seed": 0})

        assert report["improved"] is True
        assert report["optimized_max_util"] < report["initial_max_util"]
        assert report["weight_changes"], "an improvement implies changes"
        assert report["degraded"] is False

        # independent re-verification under exact SPF + fractional ECMP
        graph = compile_graph(ls)
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        demands, caps, _ = build_demand_scenarios(graph, spec)
        w0_int = np.rint(w0).astype(np.int64)
        initial = max(
            hard_max_util(w0_int, demands[k], caps, src_e, dst_e, up,
                          graph.n)
            for k in range(demands.shape[0])
        )
        w_best = apply_changes(graph, w0_int, report["weight_changes"])
        optimized = max(
            hard_max_util(w_best, demands[k], caps, src_e, dst_e, up,
                          graph.n)
            for k in range(demands.shape[0])
        )
        assert initial == pytest.approx(report["initial_max_util"])
        assert optimized == pytest.approx(report["optimized_max_util"])
        assert optimized < initial
        # the fixture's designed optimum: the 3-way split of the elephant
        assert optimized == pytest.approx(2.0)

        # counters + histogram recorded through the mixins
        assert svc.counters["decision.te.optimize_runs"] == 1
        assert svc.counters["decision.te.improved_last"] == 1
        assert svc.histograms["decision.te.solve_ms"].count == 1

    def test_report_shape_and_top_links(self):
        edges, spec = congested_clos_fixture()
        svc = TeService("l0_0", {"0": build_ls(edges)})
        report = svc.optimize({"demands": spec, "steps": 30})
        for key in (
            "node", "area", "nodes", "links", "scenarios", "steps",
            "backend", "degraded", "improved", "initial_max_util",
            "optimized_max_util", "max_util_delta", "weight_changes",
            "top_links", "solve_ms",
        ):
            assert key in report, key
        # the congested express link leads the initial hot-link table
        hottest = report["top_links"]["initial"][0]
        assert {hottest["src"], hottest["dst"]} == {"l0_0", "l1_0"}
        assert hottest["util"] == pytest.approx(6.0)
        assert report["max_util_delta"] < 0

    def test_uniform_default_demands_when_no_spec(self):
        # no demand file: the what-if sweep runs over the uniform prior
        svc = TeService("g0_0", {"0": build_ls(grid_edges(3))})
        report = svc.optimize({"steps": 8})
        assert report["scenarios"] == 1
        assert report["initial_max_util"] > 0

    def test_empty_topology_is_a_request_error(self):
        svc = TeService("a", {"0": LinkState("0")})
        with pytest.raises(ValueError):
            svc.optimize({})
        assert svc.counters["decision.te.optimize_errors"] == 1

    def test_unknown_area_is_a_request_error(self):
        svc = TeService("a", {"0": build_ls([("a", "b", 1)])})
        with pytest.raises(ValueError):
            svc.optimize({"area": "nope"})

    def test_drained_node_carries_no_transit_or_demand(self):
        import dataclasses

        # drain the only transit node of a line: the optimization must see
        # a topology where b's out-edges are down and its demands zeroed
        edges = [("a", "b", 1), ("b", "c", 1)]
        dbs = build_adj_dbs(edges)
        dbs["b"] = dataclasses.replace(dbs["b"], is_overloaded=True)
        ls = LinkState("0")
        for db in dbs.values():
            ls.update_adjacency_database(db)
        svc = TeService("a", {"0": ls})
        report = svc.optimize(
            {"demands": {"demands": [["a", "c", 5.0], ["a", "b", 1.0]]},
             "steps": 4}
        )
        # a->c traffic is unroutable without b's transit and the a->b
        # demand is zeroed (a drained node is neither source nor sink of
        # TE traffic): nothing loads any link, and no change can help
        assert report["initial_max_util"] == pytest.approx(0.0)
        assert report["improved"] is False
        assert report["weight_changes"] == []


class TestScenarios:
    def test_spec_parsing_capacities_and_spread(self):
        graph = compile_graph(build_ls(grid_edges(3)))
        spec = {
            "demands": [["g0_0", "g2_2", 4.0], ["ghost", "g0_0", 9.0]],
            "capacities": {"default": 2.0, "links": [["g0_0", "g0_1", 8.0]]},
            "scenarios": 3,
            "scenario_spread": 0.25,
        }
        demands, caps, scenarios = build_demand_scenarios(graph, spec, seed=1)
        assert scenarios == 3 and demands.shape[0] == 3
        i, j = graph.node_index["g0_0"], graph.node_index["g2_2"]
        assert demands[0, i, j] == pytest.approx(4.0)
        assert demands.sum() == pytest.approx(
            demands[:, i, j].sum()
        ), "unknown node rows are dropped"
        # scenario k>0 scales origin rows inside [1-spread, 1+spread]
        assert demands[1, i, j] != demands[0, i, j]
        assert 3.0 <= demands[1, i, j] <= 5.0
        # capacities: default everywhere, the overridden link both ways
        a, b = graph.node_index["g0_0"], graph.node_index["g0_1"]
        for e in range(graph.e):
            expected = (
                8.0
                if {int(graph.src[e]), int(graph.dst[e])} == {a, b}
                else 2.0
            )
            assert caps[e] == pytest.approx(expected)

    def test_scenarios_deterministic_by_seed(self):
        graph = compile_graph(build_ls(grid_edges(3)))
        spec = uniform_demand_spec(list(graph.names))
        spec["scenarios"] = 4
        d1, _, _ = build_demand_scenarios(graph, spec, seed=7)
        d2, _, _ = build_demand_scenarios(graph, spec, seed=7)
        d3, _, _ = build_demand_scenarios(graph, spec, seed=8)
        np.testing.assert_array_equal(d1, d2)
        assert not np.array_equal(d1, d3)


class TestMeshSharding:
    def test_scenario_batch_shards_over_mesh(self):
        """Scenario sweeps ride the SPF source-batch sharding scheme: the
        [B, N, N] demand tensor is row-sharded over the mesh 'batch' axis
        (B=3 pads to the 4-way axis with masked zero-demand scenarios)
        and the optimization still finds the fixture's improvement."""
        from openr_tpu.parallel import resolve_mesh

        mesh = resolve_mesh((4, 2))  # conftest forces 8 host devices
        edges, spec = congested_clos_fixture()
        spec = dict(spec)
        spec["scenarios"] = 3
        spec["scenario_spread"] = 0.2
        svc = TeService("l0_0", {"0": build_ls(edges)}, mesh=mesh)
        report = svc.optimize({"demands": spec, "steps": 40, "seed": 0})
        assert report["scenarios"] == 3
        assert report["improved"] is True
        assert report["optimized_max_util"] < report["initial_max_util"]


class TestFaultDomain:
    def make_supervised(self, me, area_ls, samples=None, **cfg_kw):
        sup = SolverSupervisor(
            TpuSpfSolver(me),
            SpfSolver(me),
            SupervisorConfig(**cfg_kw),
            log_sample_fn=(samples.append if samples is not None else None),
        )
        return TeService(
            me, area_ls, solver=sup,
            log_sample_fn=(samples.append if samples is not None else None),
        ), sup

    def test_injected_fault_degrades_to_cpu_without_crashing(self):
        """The ISSUE acceptance fault test: a persistent device fault at
        the te.optimize seam must yield the identical improving proposal
        from the CPU backend, marked degraded — never an exception."""
        edges, spec = congested_clos_fixture()
        samples = []
        svc, sup = self.make_supervised(
            "l0_0", {"0": build_ls(edges)}, samples=samples, max_attempts=2
        )
        with injected() as inj:
            inj.arm("te.optimize", times=None)  # persistent device fault
            report = svc.optimize({"demands": spec, "steps": 40, "seed": 0})
            assert inj.fired("te.optimize") >= 1
        assert report["degraded"] is True
        assert report["backend"] == "cpu-fallback"
        # the degraded path runs the identical optimization: still a
        # strict improvement on the fixture
        assert report["improved"] is True
        assert report["optimized_max_util"] < report["initial_max_util"]
        assert svc.counters["decision.te.fallback_runs"] == 1
        # the fault fed the shared breaker's failure accounting
        assert sup.counters["decision.spf.solver_failures"] >= 1
        assert any(
            s._values.get("event") == "TE_OPTIMIZE_DEGRADED"
            for s in samples
        )

    def test_transient_fault_is_retried_in_call(self):
        edges, spec = congested_clos_fixture()
        svc, sup = self.make_supervised(
            "l0_0", {"0": build_ls(edges)}, max_attempts=3
        )
        with injected() as inj:
            inj.arm("te.optimize", times=1)  # heals on the retry
            report = svc.optimize({"demands": spec, "steps": 20})
        assert report["degraded"] is False
        assert sup.counters["decision.spf.solver_retries"] >= 1

    def test_open_breaker_serves_fallback_immediately(self):
        edges, spec = congested_clos_fixture()
        svc, sup = self.make_supervised(
            "l0_0", {"0": build_ls(edges)}, failure_threshold=1,
            max_attempts=1,
        )
        with injected() as inj:
            inj.arm("te.optimize", times=None)
            first = svc.optimize({"demands": spec, "steps": 10})
            fired_once = inj.fired("te.optimize")
            second = svc.optimize({"demands": spec, "steps": 10})
            assert inj.fired("te.optimize") == fired_once, (
                "an open breaker must not re-dispatch to the device"
            )
        assert first["degraded"] and second["degraded"]
        assert svc.counters["decision.te.fallback_runs"] == 2

    def test_unsupervised_service_still_degrades(self):
        # no supervisor attached (cpu-backend Decision): the plain
        # try/except fallback path serves, degraded is still reported
        edges, spec = congested_clos_fixture()
        svc = TeService("l0_0", {"0": build_ls(edges)})
        with injected() as inj:
            inj.arm("te.optimize", times=None)
            report = svc.optimize({"demands": spec, "steps": 20})
        assert report["degraded"] is True
        assert report["improved"] is True


class TestDecisionIntegration:
    def make_decision(self, edges, me, backend="tpu"):
        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue

        decision = Decision(
            DecisionConfig(my_node_name=me, solver_backend=backend),
            RQueue(RWQueue()),
            ReplicateQueue(),
        )
        ls = decision.area_link_states["0"]
        for db in build_adj_dbs(edges).values():
            ls.update_adjacency_database(db)
        return decision

    def test_run_te_optimize_through_decision(self):
        edges, spec = congested_clos_fixture()
        decision = self.make_decision(edges, "l0_0")
        report = decision.run_te_optimize(
            {"demands": spec, "steps": 40, "seed": 0}
        )
        assert report["improved"] is True
        assert report["node"] == "l0_0"
        # TE counters land in Decision's monitor-registered dicts
        assert decision.counters["decision.te.optimize_runs"] == 1
        assert "decision.te.solve_ms" in decision.histograms
        # the service is built once and reused
        svc = decision._te_service
        decision.run_te_optimize({"demands": spec, "steps": 4})
        assert decision._te_service is svc
        assert decision.counters["decision.te.optimize_runs"] == 2

    def test_decision_level_fault_degrades(self):
        edges, spec = congested_clos_fixture()
        decision = self.make_decision(edges, "l0_0")
        with injected() as inj:
            inj.arm("te.optimize", times=None)
            report = decision.run_te_optimize(
                {"demands": spec, "steps": 20}
            )
        assert report["degraded"] is True
        assert report["improved"] is True
        # the TE fault fed the same breaker the SPF solves use
        assert decision.solver.counters[
            "decision.spf.solver_failures"
        ] >= 1
