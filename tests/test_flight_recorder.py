"""Solver flight recorder (ISSUE 13): per-solve SolveTraces with sampled
phase timing, bounded per-area rings with exact eviction accounting,
fault-forensics dumps wired into the supervisor's trip/mismatch/deadline
paths, the ctrl/breeze read surfaces, and the on-demand profiling window
— every degraded path driven by the deterministic fault injector."""

import asyncio
import json
import statistics
import threading

import numpy as np
import pytest

from openr_tpu.ctrl import CtrlClient, CtrlServer
from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.monitor import Monitor
from openr_tpu.monitor.profiling import ProfileController
from openr_tpu.solver import (
    SolverSupervisor,
    SpfSolver,
    SupervisorConfig,
    TpuSpfSolver,
)
from openr_tpu.solver.flight_recorder import (
    NULL_CLOCK,
    FlightRecorder,
    PhaseClock,
    SolveTrace,
)
from openr_tpu.testing.faults import FaultInjector, injected
from openr_tpu.topology import build_adj_dbs, grid_edges
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry


def build_ls(edges, area="0", **kwargs):
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def make_prefix_state(announcers, area="0"):
    ps = PrefixState()
    for node, pfxs in announcers.items():
        ps.update_prefix_database(
            PrefixDatabase(
                node, [PrefixEntry(IpPrefix(p)) for p in pfxs], area=area
            )
        )
    return ps


EDGES = grid_edges(3)
ANNOUNCERS = {"g2_2": ["10.1.0.0/16"], "g0_2": ["10.2.0.0/16"]}


def solve_inputs():
    return "g0_0", {"0": build_ls(EDGES)}, make_prefix_state(ANNOUNCERS)


def make_supervisor(samples=None, **cfg_kw):
    cfg_kw.setdefault("trace_sample_every", 1)
    return SolverSupervisor(
        TpuSpfSolver("g0_0"),
        SpfSolver("g0_0"),
        SupervisorConfig(**cfg_kw),
        log_sample_fn=(samples.append if samples is not None else None),
    )


def flap(link_state: LinkState, n: int, metric: int) -> None:
    """One weight event: bump a far-side link metric so the warm path
    serves it (no adjacency incident to g0_0 moves)."""
    import dataclasses

    db = build_adj_dbs(EDGES)["g2_1"]
    db = dataclasses.replace(
        db,
        adjacencies=[
            dataclasses.replace(adj, metric=metric)
            if adj.other_node_name == "g2_2"
            else adj
            for adj in db.adjacencies
        ],
    )
    link_state.update_adjacency_database(db)


# line topology for delta-extraction tests: a far-edge metric move MUST
# change the distance columns (no alternate path can absorb it)
LINE = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]


def line_inputs():
    return (
        "a",
        {"0": build_ls(LINE)},
        make_prefix_state({"d": ["10.9.0.0/16"]}),
    )


def line_flap(link_state: LinkState, metric: int) -> None:
    import dataclasses

    db = build_adj_dbs(LINE)["c"]
    db = dataclasses.replace(
        db,
        adjacencies=[
            dataclasses.replace(adj, metric=metric)
            if adj.other_node_name == "d"
            else adj
            for adj in db.adjacencies
        ],
    )
    link_state.update_adjacency_database(db)


# ---------------------------------------------------------------------------
# ring semantics + eviction accounting
# ---------------------------------------------------------------------------


class TestRingSemantics:
    def test_eviction_accounting_invariant(self):
        """recorded == retained + evicted, exactly, across overflow."""
        rec = FlightRecorder(ring_size=4, sample_every=0, node="n")
        for i in range(11):
            rec.record(_trace(rec, area="0"))
        for i in range(3):
            rec.record(_trace(rec, area="1"))
        stats = rec.stats()
        assert stats["recorded"] == 14
        assert stats["retained"] == 4 + 3
        assert stats["evicted"] == 7
        assert stats["recorded"] == stats["retained"] + stats["evicted"]
        # per-area rings: area 0 kept its newest ring_size seqs
        seqs = [t["seq"] for t in rec.snapshot(area="0")]
        assert seqs == sorted(seqs) and len(seqs) == 4
        assert seqs[0] == 8  # 11 recorded, 4 retained -> oldest is #8

    def test_snapshot_last_n_is_global_order(self):
        rec = FlightRecorder(ring_size=8, sample_every=0)
        for area in ("0", "1", "0"):
            rec.record(_trace(rec, area=area))
        last = rec.snapshot(last_n=2)
        assert [t["seq"] for t in last] == [2, 3]

    def test_solver_ring_records_every_solve(self):
        sup = make_supervisor(trace_ring_size=2)
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)
        for i in range(4):
            flap(states["0"], i, 20 + i)
            sup.build_route_db(me, states, ps)
        stats = sup.recorder.stats()
        assert stats["recorded"] == 5
        assert stats["retained"] == 2  # ring bound enforced
        assert stats["evicted"] == 3
        # the ring/eviction accounting rides the counter registry
        assert sup.counters["decision.spf.traces_recorded"] == 5
        assert sup.counters["decision.spf.traces_evicted"] == 3


def _trace(rec: FlightRecorder, area: str = "0") -> SolveTrace:
    return SolveTrace(
        seq=rec.next_seq(),
        ts=0.0,
        area=area,
        node="n",
        event="solve",
        layout="sell",
        warm=False,
        solve_ms=1.0,
        rounds=1,
        invalidation_rounds=None,
        halo_exchanges=None,
        h2d_bytes=0,
        d2h_bytes=0,
        halo_bytes=0,
        delta_columns=None,
        compile_cache_misses=0,
        breaker_state="closed",
        sampled=False,
    )


# ---------------------------------------------------------------------------
# sampled phase timing + the probe-effect contract
# ---------------------------------------------------------------------------


class TestPhaseSampling:
    def test_sampled_solve_records_phase_split(self):
        sup = make_supervisor(trace_sample_every=1)
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)
        (trace,) = sup.recorder.snapshot()
        assert trace["sampled"] is True
        assert trace["event"] == "solve"
        assert trace["layout"] in ("sell", "bf")
        assert trace["warm"] is False
        # the cold solve splits into prepare/h2d/relax at least
        assert {"prepare", "h2d", "relax"} <= set(trace["phases"])
        assert all(v >= 0.0 for v in trace["phases"].values())
        assert trace["phases"]["relax"] > 0.0
        # phase histograms reached the decision.spf.* registry
        for name in (
            "decision.spf.phase.prepare_ms",
            "decision.spf.phase.h2d_ms",
            "decision.spf.phase.relax_ms",
        ):
            assert sup.histograms[name].count >= 1, name

    def test_warm_solve_phases_include_delta_extract(self):
        sup = SolverSupervisor(
            TpuSpfSolver("a"),
            SpfSolver("a"),
            SupervisorConfig(trace_sample_every=1),
        )
        me, states, ps = line_inputs()
        sup.build_route_db(me, states, ps)
        line_flap(states["0"], 5)
        sup.build_route_db(me, states, ps)
        warm = [t for t in sup.recorder.snapshot() if t["warm"]]
        assert warm, sup.recorder.snapshot()
        trace = warm[-1]
        assert trace["invalidation_rounds"] is not None
        assert trace["delta_columns"] is not None
        assert "delta_extract" in trace["phases"]
        assert sup.histograms[
            "decision.spf.phase.delta_extract_ms"
        ].count >= 1

    def test_unsampled_solves_take_no_barriers(self):
        """The probe-effect contract: solves the sampler skips run with
        the shared NULL_CLOCK — zero block_until_ready calls, no phase
        dict, nothing device-side the solve would not have touched
        anyway."""
        sup = make_supervisor(trace_sample_every=3)
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)  # solve 1: sampled
        barriers_after_first = sup.recorder.barrier_calls
        assert barriers_after_first > 0  # the sampled solve barriered
        for i in range(2):  # solves 2, 3: unsampled
            flap(states["0"], i, 40 + i)
            sup.build_route_db(me, states, ps)
        traces = sup.recorder.snapshot()
        assert [t["sampled"] for t in traces] == [True, False, False]
        for t in traces[1:]:
            assert t["phases"] == {}
        # no barrier was taken by the unsampled solves
        assert sup.recorder.barrier_calls == barriers_after_first
        assert NULL_CLOCK.barriers == 0  # the shared no-op clock is inert
        # solve 4 samples again (every 3rd)
        flap(states["0"], 9, 77)
        sup.build_route_db(me, states, ps)
        assert sup.recorder.snapshot()[-1]["sampled"] is True
        assert sup.recorder.barrier_calls > barriers_after_first

    def test_probe_effect_bound_sampled_vs_unsampled(self):
        """Sampled solves pay barriers mid-dispatch; the bound here is
        deliberately loose (CI jitter) but pins that sampling cannot make
        solves catastrophically slower than the unsampled hot path."""
        sampled = make_supervisor(trace_sample_every=1)
        unsampled = make_supervisor(trace_sample_every=0)
        me, states_a, ps = solve_inputs()
        _, states_b, _ = solve_inputs()
        sampled.build_route_db(me, states_a, ps)  # compile, excluded
        unsampled.build_route_db(me, states_b, ps)
        sampled_ms, unsampled_ms = [], []
        for i in range(4):
            flap(states_a["0"], i, 21 + i)
            flap(states_b["0"], i, 21 + i)
            sampled.build_route_db(me, states_a, ps)
            unsampled.build_route_db(me, states_b, ps)
            sampled_ms.append(sampled.recorder.snapshot()[-1]["solve_ms"])
            unsampled_ms.append(
                unsampled.recorder.snapshot()[-1]["solve_ms"]
            )
        assert all(t["sampled"] for t in sampled.recorder.snapshot()[1:])
        assert not any(
            t["sampled"] for t in unsampled.recorder.snapshot()
        )
        med_s = statistics.median(sampled_ms)
        med_u = statistics.median(unsampled_ms)
        assert med_s <= med_u * 20.0 + 100.0, (sampled_ms, unsampled_ms)

    def test_sample_every_zero_disables_sampling_not_recording(self):
        rec = FlightRecorder(sample_every=0)
        clock = rec.begin()
        assert clock is NULL_CLOCK
        clock.seam("relax")  # no-op, no phases accumulate
        assert clock.phases == {}

    def test_phase_clock_barriers_device_values(self):
        import jax.numpy as jnp

        clock = PhaseClock(True)
        x = jnp.arange(8) * 2
        clock.seam("relax", x, object())  # non-device values are skipped
        assert clock.barriers == 1
        assert clock.phases["relax"] >= 0.0


# ---------------------------------------------------------------------------
# forensics dumps (the fault-domain integration)
# ---------------------------------------------------------------------------


class TestForensics:
    def test_breaker_trip_dump_reconstructs_timeline(self, tmp_path):
        """The acceptance path: a clean solve, then an injected
        solver.tpu.solve fault streak trips the breaker; the dump
        referenced from SOLVER_BREAKER_TRIPPED holds the last-N traces —
        the clean solve WITH its per-phase split plus the classified
        fault records — and round-trips through JSON."""
        samples = []
        sup = make_supervisor(
            samples=samples,
            failure_threshold=2,
            max_attempts=1,
            forensics_dir=str(tmp_path),
        )
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)  # clean solve, traced
        with injected() as inj:
            inj.arm("solver.tpu.solve", times=None)
            flap(states["0"], 0, 50)
            sup.build_route_db(me, states, ps)
            flap(states["0"], 1, 51)
            sup.build_route_db(me, states, ps)
        assert sup.state != "closed"
        trip = next(
            s for s in samples
            if s.get("event") == "SOLVER_BREAKER_TRIPPED"
        )
        forensics_id = trip.get("forensics_id")
        assert forensics_id
        dumped = next(
            s for s in samples
            if s.get("event") == "SOLVER_FORENSICS_DUMPED"
        )
        assert dumped.get("forensics_id") == forensics_id
        dump = next(
            d for d in sup.recorder.dumps if d["id"] == forensics_id
        )
        assert dump["reason"] == "breaker_trip"
        # per-phase timeline of the solves that led to the trip: the
        # clean solve's sampled phase split survives in the dump
        events = [
            t for ts in dump["traces"].values() for t in ts
        ]
        clean = [t for t in events if t["event"] == "solve"]
        faults = [t for t in events if t["event"] == "fault"]
        assert clean and faults
        assert {"prepare", "h2d", "relax"} <= set(clean[0]["phases"])
        assert all(f["fault_kind"] == "runtime" for f in faults)
        assert all(f["breaker_state"] == "closed" for f in faults)
        # context rides along: config + counters + degrade-safe digest
        assert dump["solver_config"]["failure_threshold"] == 2
        assert "decision.spf.solver_failures" in dump["counters"]
        assert "mesh_shape" in dump["mesh_digest"]
        # JSON round-trip, and the artifact landed on disk
        assert json.loads(json.dumps(dump, sort_keys=True))["id"] == (
            forensics_id
        )
        path = tmp_path / f"{forensics_id}.json"
        assert path.exists()
        on_disk = json.loads(path.read_text())
        assert on_disk["reason"] == "breaker_trip"
        assert on_disk["traces"].keys() == dump["traces"].keys()
        # counter + health surfaces
        assert sup.counters["decision.spf.forensics_dumps"] >= 1
        health = sup.health()
        assert health["forensics"]["last_id"] == forensics_id
        assert health["traces"]["recorded"] == sup.recorder.recorded

    def test_deadline_overrun_dumps(self):
        samples = []
        sup = make_supervisor(
            samples=samples,
            solve_deadline_s=0.0,  # every real solve overruns
            failure_threshold=100,
        )
        me, states, ps = solve_inputs()
        db = sup.build_route_db(me, states, ps)
        assert db is not None  # slow-but-correct still serves
        assert sup.recorder.last_dump_reason == "deadline"
        assert any(
            s.get("event") == "SOLVER_FORENSICS_DUMPED"
            and s.get("reason") == "deadline"
            for s in samples
        )

    def test_audit_mismatch_dump_references_id(self):
        samples = []
        sup = make_supervisor(samples=samples, audit_interval=1)
        me, states, ps = solve_inputs()

        def corrupt(solve):
            solve.d  # materialize the host mirror
            solve._d_host[0, 1] += 7

        with injected(FaultInjector()) as inj:
            inj.arm("solver.tpu.warm_d", times=1, action=corrupt)
            sup.build_route_db(me, states, ps)
        mism = next(
            s for s in samples
            if s.get("event") == "WARM_STATE_AUDIT_MISMATCH"
        )
        assert mism.get("forensics_id")
        assert sup.recorder.last_dump_reason == "audit_mismatch"

    def test_dump_index_is_bounded(self):
        rec = FlightRecorder(max_dumps=2)
        ids = [rec.dump(f"r{i}")["id"] for i in range(5)]
        assert [d["id"] for d in rec.dumps] == ids[-2:]
        assert rec.forensics_stats()["dumps"] == 5


# ---------------------------------------------------------------------------
# ctrl + breeze + metrics surfaces
# ---------------------------------------------------------------------------


def run(coro, timeout=15.0):
    async def body():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.new_event_loop().run_until_complete(body())


class _RecorderDecision:
    """Decision stand-in delegating the flight-recorder surfaces to a
    real supervised solver (the shapes the ctrl server serializes)."""

    def __init__(self, sup):
        self.sup = sup

    def get_solver_health(self):
        return self.sup.health()

    def get_solve_traces(self, area=None, last_n=None):
        rec = self.sup.recorder
        return {
            "enabled": True,
            "traces": rec.snapshot(area=area, last_n=last_n),
            "stats": rec.stats(),
            "forensics": rec.dump_summaries(),
        }


class TestCtrlSurfaces:
    def _sup_with_history(self):
        sup = make_supervisor()
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)
        flap(states["0"], 0, 60)
        sup.build_route_db(me, states, ps)
        return sup

    def test_get_solve_traces_over_the_wire(self):
        sup = self._sup_with_history()

        async def body():
            server = CtrlServer(
                "n1", port=0, decision=_RecorderDecision(sup)
            )
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            report = await client.call("getSolveTraces", last_n=1)
            assert report["enabled"] is True
            assert len(report["traces"]) == 1
            assert report["traces"][0]["warm"] is True
            assert report["stats"]["recorded"] == 2
            health = await client.call("getSolverHealth")
            assert health["solve_ms_last"] is not None
            assert health["traces"]["recorded"] == 2
            assert "forensics" in health
            await client.close()
            await server.stop()

        run(body())

    def test_phase_histograms_ride_get_metrics(self):
        sup = self._sup_with_history()

        async def body():
            monitor = Monitor("n1")
            monitor.register_module("decision", sup)
            server = CtrlServer("n1", port=0, monitor=monitor)
            port = await server.start()
            client = await CtrlClient("127.0.0.1", port).connect()
            text = await client.call("getMetricsText")
            assert "openr_decision_spf_phase_relax_ms_count" in text
            assert "openr_decision_spf_phase_h2d_ms_count" in text
            assert "openr_decision_spf_traces_recorded" in text
            # the same bytes over the plain HTTP scrape handler
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port
            )
            writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            assert b"openr_decision_spf_phase_relax_ms_count" in raw
            await client.close()
            await server.stop()

        run(body())

    def test_decision_get_solve_traces_disabled_without_recorder(self):
        from openr_tpu.decision import Decision, DecisionConfig
        from openr_tpu.messaging import ReplicateQueue

        decision = Decision(
            DecisionConfig(my_node_name="n1", solver_backend="cpu"),
            ReplicateQueue().get_reader(),
            ReplicateQueue(),
        )
        report = decision.get_solve_traces()
        assert report["enabled"] is False and report["traces"] == []
        health = decision.get_solver_health()
        assert health["breaker_state"] == "unsupervised"
        assert "solve_ms_last" in health

    def test_start_profile_is_admission_guarded(self):
        from openr_tpu.streaming import AdmissionController

        assert AdmissionController().guards("startProfile")


class TestBreezeCli:
    @pytest.fixture
    def ctrl_endpoint(self):
        started = threading.Event()
        state = {}
        sup = make_supervisor()
        me, states, ps = solve_inputs()
        sup.build_route_db(me, states, ps)
        sup.recorder.dump("breaker_trip")

        def run_server():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            server = CtrlServer(
                "cli-node", port=0, decision=_RecorderDecision(sup)
            )
            state["loop"] = loop
            state["port"] = loop.run_until_complete(server.start())
            started.set()
            loop.run_forever()
            loop.run_until_complete(server.stop())
            loop.close()

        thread = threading.Thread(target=run_server, daemon=True)
        thread.start()
        assert started.wait(10)
        yield "127.0.0.1", state["port"]
        state["loop"].call_soon_threadsafe(state["loop"].stop)
        thread.join(timeout=10)

    def test_solve_traces_renders_table(self, ctrl_endpoint, capsys):
        from openr_tpu.cli.breeze import main as breeze_main

        host, port = ctrl_endpoint
        rc = breeze_main(
            ["--host", host, "--port", str(port),
             "decision", "solve-traces"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "flight recorder: 1 recorded" in out
        assert "sell" in out or "bf" in out
        assert "forensics dumps:" in out
        assert "breaker_trip" in out

    def test_solve_traces_json(self, ctrl_endpoint, capsys):
        from openr_tpu.cli.breeze import main as breeze_main

        host, port = ctrl_endpoint
        rc = breeze_main(
            ["--host", host, "--port", str(port),
             "decision", "solve-traces", "--json"]
        )
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert data["enabled"] is True
        assert data["stats"]["recorded"] == 1

    def test_profile_window_over_the_wire(
        self, ctrl_endpoint, capsys, tmp_path, monkeypatch
    ):
        # the ctrl server runs in-process: stub the profiler backend so
        # this test pins the RPC/CLI plumbing without paying a real
        # capture's process-wide RSS (the real backend is exercised in a
        # subprocess by TestProfileController)
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: calls.append(d)
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append("stop")
        )
        from openr_tpu.cli.breeze import main as breeze_main

        host, port = ctrl_endpoint
        out_dir = str(tmp_path / "prof")
        rc = breeze_main(
            ["--host", host, "--port", str(port), "decision",
             "profile", "--seconds", "0.2", "--out", out_dir]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "profiling window open" in out
        assert calls == [out_dir]
        # drain the window past its deadline so the status poll closes it
        # (the bounded-window contract over the wire)
        import time as _time

        _time.sleep(0.35)
        rc = breeze_main(
            ["--host", host, "--port", str(port),
             "decision", "profile-status"]
        )
        assert rc == 0
        status = json.loads(capsys.readouterr().out)
        assert status["active"] is False  # bounded: the window closed
        assert status["windows"] == 1
        assert calls == [out_dir, "stop"]


# ---------------------------------------------------------------------------
# profiling window state machine
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestProfileController:
    def test_window_is_bounded_and_single_flight(
        self, tmp_path, monkeypatch
    ):
        calls = []
        import jax

        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: calls.append(("start", d))
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        clock = _FakeClock()
        ctl = ProfileController(clock=clock)
        out = str(tmp_path / "prof")
        status = ctl.start(out_dir=out, seconds=2.0)
        assert status["started"] is True and status["active"] is True
        # second start refused while active
        again = ctl.start(out_dir=out, seconds=2.0)
        assert again["started"] is False
        assert "already active" in again["error"]
        # deadline passes: any status poll closes the window
        clock.t = 2.5
        status = ctl.status()
        assert status["active"] is False
        assert calls == [("start", out), ("stop",)]
        # a fresh window may start now
        assert ctl.start(out_dir=out, seconds=1.0)["started"] is True

    def test_duration_clamped(self, tmp_path, monkeypatch):
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        ctl = ProfileController(clock=_FakeClock())
        status = ctl.start(out_dir=str(tmp_path), seconds=10_000)
        assert status["seconds"] == 600.0

    def test_degrade_safe_when_profiler_unavailable(
        self, tmp_path, monkeypatch
    ):
        import jax

        def boom(_):
            raise RuntimeError("profiler backend unavailable")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        ctl = ProfileController()
        status = ctl.start(out_dir=str(tmp_path), seconds=1.0)
        assert status["started"] is False
        assert "unavailable" in status["error"]
        assert ctl.status()["active"] is False
        assert "unavailable" in ctl.status()["last_error"]

    def test_real_cpu_window_writes_trace_dir(self, tmp_path):
        """Degrade-safe contract on the real CPU backend: a tiny window
        either captures a TensorBoard dir or reports in-band. Runs in a
        SUBPROCESS: a real profiler capture permanently grows process
        RSS, which would poison the watchdog memory-limit tests sharing
        this pytest process."""
        import subprocess
        import sys

        out = str(tmp_path / "prof")
        script = (
            "import os; os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import json, sys\n"
            "from openr_tpu.monitor.profiling import ProfileController\n"
            "import jax.numpy as jnp\n"
            f"ctl = ProfileController()\n"
            f"status = ctl.start(out_dir={out!r}, seconds=30.0)\n"
            "if not status['started']:\n"
            "    assert status['error']  # reported, not raised\n"
            "    print(json.dumps({'captured': False})); sys.exit(0)\n"
            "(jnp.arange(16) * 3).block_until_ready()\n"
            "ctl.stop()\n"
            "assert ctl.status()['active'] is False\n"
            f"assert os.path.isdir({out!r})\n"
            "print(json.dumps({'captured': True}))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            timeout=240,
            text=True,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result["captured"] in (True, False)
