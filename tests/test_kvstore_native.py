"""Native KvStore engine tests: the C++ table (native/kvstore) must be
bit-for-bit equivalent to the Python merge_key_values CRDT
(openr/kvstore/KvStore.cpp:261-411 semantics), and a KvStore built on it
must interoperate with a pure-Python peer."""

import asyncio
import random

import pytest

from openr_tpu.kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreParams,
    PeerSpec,
)
from openr_tpu.kvstore.store import merge_key_values
from openr_tpu.types import TTL_INFINITY, Value, generate_hash

native = pytest.importorskip("openr_tpu.kvstore.native")

pytestmark = pytest.mark.skipif(
    not native.native_kv_available(),
    reason="native kvstore library unavailable",
)


def make_table():
    return native.NativeKvTable()


class TestTableAdapter:
    def test_mapping_protocol(self):
        t = make_table()
        t["a"] = Value(3, "n1", b"body", 2000, 1, 77)
        assert t["a"] == Value(3, "n1", b"body", 2000, 1, 77)
        assert "a" in t and "b" not in t
        with pytest.raises(KeyError):
            t["b"]
        t["b"] = Value(1, "n2", None)  # tombstone-style, no body
        assert len(t) == 2
        assert sorted(t) == ["a", "b"]
        del t["b"]
        with pytest.raises(KeyError):
            del t["b"]
        assert len(t) == 1

    def test_non_ascii_and_large_values(self):
        t = make_table()
        body = bytes(range(256)) * 1000
        t["prefix:node-é:0"] = Value(1, "orig", body)
        assert t["prefix:node-é:0"].value == body


class TestMergeSemantics:
    """The four CRDT ordering rules, run against the native engine via the
    merge_key_values dispatch."""

    def test_higher_version_wins(self):
        t = make_table()
        merge_key_values(t, {"k": Value(2, "b", b"old")})
        ups = merge_key_values(t, {"k": Value(1, "z", b"zzz")})
        assert ups == {} and t["k"].value == b"old"
        ups = merge_key_values(t, {"k": Value(3, "a", b"new")})
        assert set(ups) == {"k"} and t["k"].value == b"new"

    def test_same_version_higher_originator_wins(self):
        t = make_table()
        merge_key_values(t, {"k": Value(1, "bbb", b"x")})
        assert merge_key_values(t, {"k": Value(1, "aaa", b"y")}) == {}
        ups = merge_key_values(t, {"k": Value(1, "ccc", b"y")})
        assert set(ups) == {"k"} and t["k"].originator_id == "ccc"

    def test_same_originator_higher_value_wins(self):
        t = make_table()
        merge_key_values(t, {"k": Value(1, "a", b"mmm")})
        assert merge_key_values(t, {"k": Value(1, "a", b"aaa")}) == {}
        ups = merge_key_values(t, {"k": Value(1, "a", b"zzz")})
        assert set(ups) == {"k"} and t["k"].value == b"zzz"

    def test_ttl_refresh_without_body(self):
        t = make_table()
        merge_key_values(t, {"k": Value(1, "a", b"v", 5000, 1)})
        # refresh: no body, higher ttlVersion
        ups = merge_key_values(t, {"k": Value(1, "a", None, 9000, 2)})
        assert set(ups) == {"k"}
        stored = t["k"]
        assert stored.value == b"v"
        assert stored.ttl == 9000 and stored.ttl_version == 2
        # stale refresh ignored
        assert merge_key_values(t, {"k": Value(1, "a", None, 100, 2)}) == {}

    def test_rejects_bad_version_and_ttl(self):
        t = make_table()
        assert merge_key_values(t, {"k": Value(0, "a", b"v")}) == {}
        assert merge_key_values(t, {"k": Value(1, "a", b"v", 0)}) == {}
        assert merge_key_values(t, {"k": Value(1, "a", b"v", -5)}) == {}
        assert len(t) == 0

    def test_hash_filled_on_store(self):
        t = make_table()
        merge_key_values(t, {"k": Value(4, "me", b"data")})
        assert t["k"].hash == generate_hash(4, "me", b"data")


class TestDifferential:
    def test_random_merge_sequences_match_python(self):
        rng = random.Random(1234)
        keys = [f"key-{i}" for i in range(12)]
        origs = ["n1", "n2", "n3"]
        py_store = {}
        nat = make_table()
        for step in range(400):
            batch = {}
            for key in rng.sample(keys, rng.randint(1, 4)):
                has_body = rng.random() < 0.8
                batch[key] = Value(
                    version=rng.randint(0, 5),
                    originator_id=rng.choice(origs),
                    value=(
                        rng.choice([b"a", b"b", b"longer-value"])
                        if has_body
                        else None
                    ),
                    ttl=rng.choice([TTL_INFINITY, 1000, 60000, 0]),
                    ttl_version=rng.randint(0, 3),
                )
            py_ups = merge_key_values(py_store, {
                k: v.copy() for k, v in batch.items()
            })
            nat_ups = nat.native_merge({
                k: v.copy() for k, v in batch.items()
            })
            assert set(py_ups) == set(nat_ups), f"step {step}"
            # final stored state identical (hash presence included: the
            # python path fills hashes when storing, so compare directly)
            nat_state = dict(nat.items())
            assert set(py_store) == set(nat_state), f"step {step}"
            for k in py_store:
                py_v, nat_v = py_store[k], nat_state[k]
                if py_v.hash is None:
                    py_v = py_v.copy()
                    py_v.hash = generate_hash(
                        py_v.version, py_v.originator_id, py_v.value
                    )
                assert py_v == nat_v, f"step {step} key {k}"


def test_cpp_unit_tests_pass():
    """Run the C++-side assert suite (native/kvstore/onl_kvstore_test.cpp)."""
    import os
    import subprocess

    binary = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "openr_tpu",
        "_native",
        "onl_kvstore_test",
    )
    if not os.path.exists(binary):
        pytest.skip("onl_kvstore_test binary not built")
    result = subprocess.run([binary], capture_output=True, timeout=60)
    assert result.returncode == 0, result.stderr.decode()
    assert b"onl_kvstore_test OK" in result.stdout


class TestEndToEnd:
    def test_native_store_syncs_with_python_peer(self):
        async def body():
            transport = InProcessTransport()
            kv_native = KvStore(
                "nat", ["0"], transport,
                params=KvStoreParams(node_id="nat", use_native_store=True),
            )
            kv_py = KvStore(
                "py", ["0"], transport,
                params=KvStoreParams(node_id="py"),
            )
            from openr_tpu.kvstore.native import NativeKvTable

            assert isinstance(kv_native.dbs["0"].store, NativeKvTable)
            kv_native.set_key("from-native", Value(1, "nat", b"hello"))
            kv_py.set_key("from-py", Value(1, "py", b"world"))
            kv_native.add_peers({"py": PeerSpec("py")})
            kv_py.add_peers({"nat": PeerSpec("nat")})

            async def synced():
                while (
                    kv_native.get_key("from-py") is None
                    or kv_py.get_key("from-native") is None
                ):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(synced(), 10)
            assert kv_native.get_key("from-py").value == b"world"
            assert kv_py.get_key("from-native").value == b"hello"

        asyncio.new_event_loop().run_until_complete(body())
