"""CPU SpfSolver — the faithful route-computation oracle.

Behavioral port of openr/decision/Decision.cpp SpfSolver/SpfSolverImpl
(:90-1271): per-prefix best-announcer selection, ECMP (openr + BGP
metric-vector), LFA (RFC 5286), 2-edge-disjoint K-shortest-path routes with
MPLS label stacks, node-label (SWAP/PHP/POP) and adjacency-label routes, and
drained-node filtering. The TPU solver must match this output bit-for-bit on
every topology; tests enforce it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.lsdb.link_state import Link, LinkState, Path, path_a_in_path_b
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.lsdb.prefix_state import PrefixState
from openr_tpu.solver.metric_vector import (
    CompareResult,
    compare_metric_vectors,
    create_igp_cost_entity,
    get_metric_entity_by_type,
    OPENR_IGP_COST_TYPE,
)
from openr_tpu.solver.routes import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    RibMplsEntry,
    RibUnicastEntry,
)
from openr_tpu.types import (
    IpPrefix,
    MetricVector,
    MplsAction,
    MplsActionCode,
    NextHop,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixType,
    is_mpls_label_valid,
)

Metric = int
INF_METRIC = 1 << 62


@dataclass
class BestPathCalResult:
    """Result of best-announcing-node selection (Decision.h BestPathCalResult)."""

    success: bool = False
    nodes: Set[str] = field(default_factory=set)
    best_node: str = ""
    best_area: str = ""
    areas: Set[str] = field(default_factory=set)
    best_vector: Optional[MetricVector] = None
    best_igp_metric: Optional[int] = None


def get_prefix_forwarding_type(
    prefix_entries: Dict[str, Dict[str, PrefixEntry]],
) -> PrefixForwardingType:
    """Minimum forwarding type across advertisements: every announcer must
    support SR_MPLS for it to be used (openr/common/Util.cpp semantics)."""
    result = PrefixForwardingType.SR_MPLS
    for areas in prefix_entries.values():
        for entry in areas.values():
            if entry.forwarding_type == PrefixForwardingType.IP:
                return PrefixForwardingType.IP
    return result


def get_prefix_forwarding_algorithm(
    prefix_entries: Dict[str, Dict[str, PrefixEntry]],
) -> PrefixForwardingAlgorithm:
    """Minimum forwarding algorithm across advertisements."""
    for areas in prefix_entries.values():
        for entry in areas.values():
            if entry.forwarding_algorithm == PrefixForwardingAlgorithm.SP_ECMP:
                return PrefixForwardingAlgorithm.SP_ECMP
    return PrefixForwardingAlgorithm.KSP2_ED_ECMP


class SpfSolver(CountersMixin, HistogramsMixin):
    """Route computation from one node's perspective (Decision.cpp:90)."""

    def __init__(
        self,
        my_node_name: str,
        enable_v4: bool = True,
        compute_lfa_paths: bool = False,
        enable_ordered_fib: bool = False,
        bgp_dry_run: bool = False,
        bgp_use_igp_metric: bool = False,
    ) -> None:
        self.my_node_name = my_node_name
        self.enable_v4 = enable_v4
        self.compute_lfa_paths = compute_lfa_paths
        self.enable_ordered_fib = enable_ordered_fib
        self.bgp_dry_run = bgp_dry_run
        self.bgp_use_igp_metric = bgp_use_igp_metric
        # static MPLS routes pushed from the plugin seam (Decision.cpp:868-907)
        self._static_mpls_routes: Dict[int, Set[NextHop]] = {}
        self._static_updates: List[Tuple[Dict[int, Set[NextHop]], Set[int]]] = []
        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}

    # ------------------------------------------------------------------
    # SPF access seam — the TPU backend overrides these two methods to
    # serve distances/nexthop-sets from the batched device solve while the
    # whole route-assembly pipeline below is shared between backends
    # ------------------------------------------------------------------

    def _spf(self, link_state: LinkState, node: str):
        """SpfResult-like mapping dest -> object with .metric/.next_hops."""
        with self._timer("decision.spf.solve_ms"):
            return link_state.get_spf_result(node)

    def _dist(self, link_state: LinkState, a: str, b: str) -> Optional[Metric]:
        return link_state.get_metric_from_a_to_b(a, b)

    def _kth_paths(
        self, link_state: LinkState, src: str, dest: str, k: int
    ) -> List[Path]:
        """k-th edge-disjoint shortest path set (LinkState.cpp:760-789)."""
        return link_state.get_kth_paths(src, dest, k)

    def _prefetch_kth_paths(
        self, link_state: LinkState, src: str, dests: List[str], k: int
    ) -> None:
        """Batching hook: the TPU backend solves all penalized re-runs for
        `dests` in one device call before the per-dest loop reads them."""

    # ------------------------------------------------------------------
    # static routes (plugin seam)
    # ------------------------------------------------------------------

    def push_static_routes_delta(
        self,
        mpls_to_update: Dict[int, Set[NextHop]],
        mpls_to_delete: Set[int],
    ) -> None:
        self._static_updates.append(
            (
                {label: set(nhs) for label, nhs in mpls_to_update.items()},
                set(mpls_to_delete),
            )
        )

    def static_routes_updated(self) -> bool:
        return bool(self._static_updates)

    def process_static_route_updates(self) -> Optional[DecisionRouteUpdate]:
        to_update: Dict[int, Set[NextHop]] = {}
        to_delete: Set[int] = set()
        for upd, dels in self._static_updates:
            for label, nhs in upd.items():
                to_update[label] = nhs
                to_delete.discard(label)
            for label in dels:
                to_delete.add(label)
                to_update.pop(label, None)
        self._static_updates.clear()
        if not to_update and not to_delete:
            return None
        ret = DecisionRouteUpdate()
        for label, nhs in to_update.items():
            self._static_mpls_routes[label] = nhs
            ret.mpls_routes_to_update.append(RibMplsEntry(label, set(nhs)))
        for label in to_delete:
            self._static_mpls_routes.pop(label, None)
            ret.mpls_routes_to_delete.append(label)
        return ret

    @property
    def static_mpls_routes(self) -> Dict[int, Set[NextHop]]:
        return self._static_mpls_routes

    # ------------------------------------------------------------------
    # main pipeline
    # ------------------------------------------------------------------

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        """Decision.cpp:291-542. None if this node is in no area's graph."""
        if not any(
            ls.has_node(my_node_name) for ls in area_link_states.values()
        ):
            return None

        route_db = DecisionRouteDb()
        self._bump("decision.route_build_runs")

        # ---- unicast best paths (IP and IP2MPLS) ----
        for prefix, prefix_entries in prefix_state.prefixes.items():
            self.build_unicast_route(
                route_db.unicast_entries,
                my_node_name,
                prefix,
                prefix_entries,
                area_link_states,
                prefix_state,
            )

        # ---- MPLS node-label routes (Decision.cpp:415-501) ----
        label_to_node: Dict[int, Tuple[str, RibMplsEntry]] = {}
        for area, link_state in area_link_states.items():
            for adj_db in link_state.get_adjacency_databases().values():
                top_label = adj_db.node_label
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    self._bump("decision.skipped_mpls_route")
                    continue
                # node-label collision: bigger node name keeps the label
                existing = label_to_node.get(top_label)
                if existing is not None:
                    self._bump("decision.duplicate_node_label")
                    if existing[0] < adj_db.this_node_name:
                        continue
                entry = self.build_node_label_route(
                    my_node_name, area, adj_db, area_link_states
                )
                if entry is None:
                    continue
                label_to_node[top_label] = (adj_db.this_node_name, entry)
        for label, (_, entry) in label_to_node.items():
            route_db.mpls_entries[label] = entry

        # ---- MPLS adjacency-label routes (Decision.cpp:503-534) ----
        for link_state in area_link_states.values():
            for link in link_state.ordered_links_from_node(my_node_name):
                top_label = link.adj_label_from_node(my_node_name)
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    self._bump("decision.skipped_mpls_route")
                    continue
                route_db.mpls_entries[top_label] = RibMplsEntry(
                    top_label,
                    {
                        NextHop(
                            address=link.nh_v6_from_node(my_node_name),
                            iface=link.iface_from_node(my_node_name),
                            metric=link.metric_from_node(my_node_name),
                            mpls_action=MplsAction(MplsActionCode.PHP),
                            area=link.area,
                            neighbor_node=link.other_node_name(my_node_name),
                        )
                    },
                )
        return route_db

    def build_unicast_route(
        self,
        unicast_entries: Dict[IpPrefix, RibUnicastEntry],
        my_node_name: str,
        prefix: IpPrefix,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> None:
        """One prefix's best-path selection + nexthop assembly (the body of
        build_route_db's unicast loop), writing the entry — if any — into
        `unicast_entries`. Exposed as a seam so the DeltaPath route build
        (solver/delta.py) can recompute exactly the prefixes a device
        delta names instead of looping the whole table."""
        has_bgp = has_non_bgp = missing_mv = False
        for node, areas in prefix_entries.items():
            for entry in areas.values():
                is_bgp = entry.type == PrefixType.BGP
                has_bgp |= is_bgp
                has_non_bgp |= not is_bgp
                if is_bgp and entry.mv is None:
                    missing_mv = True
        if has_bgp:
            if has_non_bgp or missing_mv:
                # mixed-type or malformed BGP advertisement: skip route
                self._bump("decision.skipped_unicast_route")
                return

        # prefixes advertised by me (non-BGP): no route needed
        if my_node_name in prefix_entries and not has_bgp:
            return

        is_v4 = prefix.is_v4
        if is_v4 and not self.enable_v4:
            self._bump("decision.skipped_unicast_route")
            return

        fwd_algo = get_prefix_forwarding_algorithm(prefix_entries)
        fwd_type = get_prefix_forwarding_type(prefix_entries)

        if fwd_type == PrefixForwardingType.SR_MPLS:
            # SP_ECMP or KSP2 on the MPLS data plane
            nodes = self.get_best_announcing_nodes(
                my_node_name,
                prefix,
                prefix_entries,
                has_bgp,
                True,
                area_link_states,
            )
            if not nodes.success or not nodes.nodes:
                return
            self._select_ksp2(
                unicast_entries,
                prefix,
                my_node_name,
                nodes,
                prefix_entries,
                has_bgp,
                area_link_states,
                prefix_state,
                fwd_algo,
            )
        elif fwd_algo == PrefixForwardingAlgorithm.SP_ECMP:
            if has_bgp:
                self._select_ecmp_bgp(
                    unicast_entries,
                    my_node_name,
                    prefix,
                    prefix_entries,
                    is_v4,
                    area_link_states,
                    prefix_state,
                )
            else:
                self._select_ecmp_openr(
                    unicast_entries,
                    my_node_name,
                    prefix,
                    prefix_entries,
                    is_v4,
                    area_link_states,
                )
        else:
            self._bump("decision.incompatible_forwarding_type")

    def build_node_label_route(
        self,
        my_node_name: str,
        area: str,
        adj_db,
        area_link_states: Dict[str, LinkState],
    ) -> Optional[RibMplsEntry]:
        """One node's MPLS node-label route (POP_AND_LOOKUP for my own
        label, SWAP/PHP nexthops toward everyone else's), or None when the
        node is unreachable. Collision arbitration stays with the caller.
        Shared by build_route_db and the DeltaPath partial rebuild."""
        top_label = adj_db.node_label
        if adj_db.this_node_name == my_node_name:
            # our own label: POP_AND_LOOKUP
            return RibMplsEntry(
                top_label,
                {
                    NextHop(
                        address="::",
                        area=area,
                        mpls_action=MplsAction(
                            MplsActionCode.POP_AND_LOOKUP
                        ),
                    )
                },
            )
        min_metric, nh_nodes = self.get_next_hops_with_metric(
            my_node_name,
            {adj_db.this_node_name},
            False,
            area_link_states,
        )
        if not nh_nodes:
            self._bump("decision.no_route_to_label")
            return None
        return RibMplsEntry(
            top_label,
            self.get_next_hops(
                my_node_name,
                {adj_db.this_node_name},
                False,
                False,
                min_metric,
                nh_nodes,
                top_label,
                area_link_states,
                {area},
            ),
        )

    def poll_device_delta(self, area_link_states) -> Optional[set]:
        """DeltaPath seam: backends without device-resident distance state
        have no device delta to offer — the route build always takes the
        full path (the TPU backend overrides this)."""
        return None

    # ------------------------------------------------------------------
    # best announcing nodes
    # ------------------------------------------------------------------

    def get_best_announcing_nodes(
        self,
        my_node_name: str,
        prefix: IpPrefix,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        has_bgp: bool,
        use_ksp2: bool,
        area_link_states: Dict[str, LinkState],
    ) -> BestPathCalResult:
        """Decision.cpp:544-630."""
        ret = BestPathCalResult()

        if not has_bgp:
            # openr routes: all reachable announcers are "best"
            if my_node_name in prefix_entries:
                return BestPathCalResult()
            for node, areas in sorted(prefix_entries.items()):
                for area in sorted(areas):
                    link_state = area_link_states.get(area)
                    if link_state is None:
                        continue
                    spf = self._spf(link_state, my_node_name)
                    if node not in spf:
                        continue  # unreachable
                    if not ret.best_node or node < ret.best_node:
                        ret.best_node = node
                        ret.best_area = area
                    ret.nodes.add(node)
                    ret.areas.add(area)
            ret.success = True
            return self._maybe_filter_drained_nodes(ret, area_link_states)

        ret = self._run_best_path_selection_bgp(
            my_node_name, prefix, prefix_entries, area_link_states
        )
        if not ret.success:
            self._bump("decision.no_route_to_prefix")
            return BestPathCalResult()

        if not use_ksp2:
            if my_node_name in ret.nodes:
                # best path originated by self: no route
                return BestPathCalResult()
            return self._maybe_filter_drained_nodes(ret, area_link_states)

        # ksp2: self-originated prefixes still get routes when other
        # announcers exist and we have a prepend label (anycast case)
        label_exists_for_me = False
        if my_node_name in prefix_entries:
            label_exists_for_me = any(
                e.prepend_label is not None
                for e in prefix_entries[my_node_name].values()
            )
        if my_node_name not in ret.nodes or (
            len(ret.nodes) > 1 and label_exists_for_me
        ):
            return self._maybe_filter_drained_nodes(ret, area_link_states)
        return BestPathCalResult()

    def _run_best_path_selection_bgp(
        self,
        my_node_name: str,
        prefix: IpPrefix,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        area_link_states: Dict[str, LinkState],
    ) -> BestPathCalResult:
        """Metric-vector tournament across announcers (Decision.cpp:714-800)."""
        ret = BestPathCalResult()
        for node, areas in sorted(prefix_entries.items()):
            for area, entry in sorted(areas.items()):
                link_state = area_link_states.get(area)
                if link_state is None:
                    continue
                spf = self._spf(link_state, my_node_name)
                if node not in spf:
                    continue
                assert entry.mv is not None
                if get_metric_entity_by_type(entry.mv, OPENR_IGP_COST_TYPE):
                    # unexpected pre-existing IGP entity: ignore announcer
                    continue
                metric_vector = entry.mv
                if self.bgp_use_igp_metric:
                    igp_metric = spf[node].metric
                    if ret.best_igp_metric is None or ret.best_igp_metric > igp_metric:
                        ret.best_igp_metric = igp_metric
                    metric_vector = MetricVector(
                        version=entry.mv.version,
                        metrics=entry.mv.metrics
                        + (create_igp_cost_entity(igp_metric),),
                    )
                if ret.best_vector is None:
                    result = CompareResult.WINNER
                else:
                    result = compare_metric_vectors(
                        metric_vector, ret.best_vector
                    )
                if result == CompareResult.WINNER:
                    ret.nodes.clear()
                    ret.best_vector = metric_vector
                    ret.best_node = node
                    ret.best_area = area
                    ret.nodes.add(node)
                    ret.areas.add(area)
                elif result == CompareResult.TIE_WINNER:
                    ret.best_vector = metric_vector
                    ret.best_node = node
                    ret.best_area = area
                    ret.nodes.add(node)
                    ret.areas.add(area)
                elif result == CompareResult.TIE_LOOSER:
                    ret.nodes.add(node)
                    ret.areas.add(area)
                elif result in (CompareResult.TIE, CompareResult.ERROR):
                    # ambiguous ordering: no route (Decision.cpp:784-792)
                    return ret
        ret.success = True
        return self._maybe_filter_drained_nodes(ret, area_link_states)

    def _maybe_filter_drained_nodes(
        self,
        result: BestPathCalResult,
        area_link_states: Dict[str, LinkState],
    ) -> BestPathCalResult:
        """Drop overloaded announcers unless all are overloaded
        (Decision.cpp:651-666)."""
        filtered = set(result.nodes)
        for link_state in area_link_states.values():
            filtered = {
                n for n in filtered if not link_state.is_node_overloaded(n)
            }
        if filtered and filtered != result.nodes:
            out = BestPathCalResult(
                success=result.success,
                nodes=filtered,
                best_node=result.best_node,
                best_area=result.best_area,
                areas=result.areas,
                best_vector=result.best_vector,
                best_igp_metric=result.best_igp_metric,
            )
            return out
        return result

    # ------------------------------------------------------------------
    # ECMP
    # ------------------------------------------------------------------

    def _select_ecmp_openr(
        self,
        unicast_entries: Dict[IpPrefix, RibUnicastEntry],
        my_node_name: str,
        prefix: IpPrefix,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        is_v4: bool,
        area_link_states: Dict[str, LinkState],
    ) -> None:
        """Decision.cpp:668-712."""
        ret = self.get_best_announcing_nodes(
            my_node_name, prefix, prefix_entries, False, False, area_link_states
        )
        if not ret.success:
            return
        per_destination = (
            get_prefix_forwarding_type(prefix_entries)
            == PrefixForwardingType.SR_MPLS
        )
        min_metric, nh_nodes = self.get_next_hops_with_metric(
            my_node_name, ret.nodes, per_destination, area_link_states
        )
        if not nh_nodes:
            self._bump("decision.no_route_to_prefix")
            return
        unicast_entries[prefix] = RibUnicastEntry(
            prefix=prefix,
            nexthops=self.get_next_hops(
                my_node_name,
                ret.nodes,
                is_v4,
                per_destination,
                min_metric,
                nh_nodes,
                None,
                area_link_states,
                ret.areas,
            ),
            best_prefix_entry=prefix_entries[ret.best_node][ret.best_area],
            best_area=ret.best_area,
        )

    def _select_ecmp_bgp(
        self,
        unicast_entries: Dict[IpPrefix, RibUnicastEntry],
        my_node_name: str,
        prefix: IpPrefix,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        is_v4: bool,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> None:
        """Decision.cpp:802-866."""
        dst_info = self.get_best_announcing_nodes(
            my_node_name, prefix, prefix_entries, True, False, area_link_states
        )
        if not dst_info.success:
            return
        if not dst_info.nodes or my_node_name in dst_info.nodes:
            if my_node_name not in dst_info.nodes:
                self._bump("decision.no_route_to_prefix")
            return
        best_next_hop = prefix_state.get_loopback_vias(
            {dst_info.best_node}, is_v4, dst_info.best_igp_metric
        )
        if len(best_next_hop) != 1:
            self._bump("decision.missing_loopback_addr")
            return
        min_metric, nh_nodes = self.get_next_hops_with_metric(
            my_node_name, dst_info.nodes, False, area_link_states
        )
        if not nh_nodes:
            self._bump("decision.no_route_to_prefix")
            return
        unicast_entries[prefix] = RibUnicastEntry(
            prefix=prefix,
            nexthops=self.get_next_hops(
                my_node_name,
                dst_info.nodes,
                is_v4,
                False,
                min_metric,
                nh_nodes,
                None,
                area_link_states,
                dst_info.areas,
            ),
            best_prefix_entry=prefix_entries[dst_info.best_node][
                dst_info.best_area
            ],
            best_area=dst_info.best_area,
            do_not_install=self.bgp_dry_run,
            best_nexthop=best_next_hop[0],
        )

    # ------------------------------------------------------------------
    # KSP2
    # ------------------------------------------------------------------

    def _select_ksp2(
        self,
        unicast_entries: Dict[IpPrefix, RibUnicastEntry],
        prefix: IpPrefix,
        my_node_name: str,
        best_path_result: BestPathCalResult,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
        has_bgp: bool,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
        fwd_algo: PrefixForwardingAlgorithm,
    ) -> None:
        """Decision.cpp:909-1066: shortest + second-shortest edge-disjoint
        paths with MPLS PUSH label stacks."""
        entry = RibUnicastEntry(prefix=prefix)
        self_node_contained = False
        paths: List[List[Link]] = []

        dests = sorted(n for n in best_path_result.nodes if n != my_node_name)
        for link_state in area_link_states.values():
            self._prefetch_kth_paths(link_state, my_node_name, dests, 1)
            for node in sorted(best_path_result.nodes):
                if node == my_node_name:
                    self_node_contained = True
                    continue
                paths.extend(self._kth_paths(link_state, my_node_name, node, 1))

            if fwd_algo == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
                self._prefetch_kth_paths(link_state, my_node_name, dests, 2)
                first_paths_len = len(paths)
                for node in sorted(best_path_result.nodes):
                    if node == my_node_name:
                        continue
                    for sec_path in self._kth_paths(
                        link_state, my_node_name, node, 2
                    ):
                        # avoid double-spray: drop second paths containing a
                        # first path (anycast full-mesh case)
                        if not any(
                            path_a_in_path_b(paths[i], sec_path)
                            for i in range(first_paths_len)
                        ):
                            paths.append(sec_path)

        if not paths:
            return

        for path in paths:
            # walk the path to accumulate cost and the label stack
            area = path[0].area
            link_state = area_link_states[area]
            adj_dbs = link_state.get_adjacency_databases()
            cost = 0
            labels: List[int] = []  # front = bottom of stack
            next_node = my_node_name
            for link in path:
                cost += link.metric_from_node(next_node)
                next_node = link.other_node_name(next_node)
                labels.insert(0, adj_dbs[next_node].node_label)
            labels.pop()  # drop first-hop node's label (PHP)
            dest_entry = prefix_entries.get(next_node, {}).get(area)
            if dest_entry is None:
                # path traced through an area where the destination did not
                # advertise this prefix (multi-area): skip this path
                continue
            if dest_entry.prepend_label is not None:
                labels.insert(0, dest_entry.prepend_label)

            first_link = path[0]
            mpls_action = (
                MplsAction(MplsActionCode.PUSH, push_labels=tuple(labels))
                if labels
                else None
            )
            entry.nexthops.add(
                NextHop(
                    address=(
                        first_link.nh_v4_from_node(my_node_name)
                        if prefix.is_v4
                        else first_link.nh_v6_from_node(my_node_name)
                    ),
                    iface=first_link.iface_from_node(my_node_name),
                    metric=cost,
                    mpls_action=mpls_action,
                    use_non_shortest_route=True,
                    area=first_link.area,
                    neighbor_node=first_link.other_node_name(my_node_name),
                )
            )

        static_nexthops = 0
        if self_node_contained:
            # anycast advertised by us too: include the static nexthops the
            # destination prepared behind our prepend label
            my_entries = prefix_entries[my_node_name]
            my_entry = next(iter(my_entries.values()))
            label = my_entry.prepend_label
            static_nhs = (
                self._static_mpls_routes.get(label) if label is not None else None
            )
            if static_nhs:
                for nh in static_nhs:
                    static_nexthops += 1
                    entry.nexthops.add(
                        NextHop(
                            address=nh.address,
                            metric=0,
                            use_non_shortest_route=True,
                            area=next(iter(my_entries.keys())),
                        )
                    )

        # minNexthop threshold (Decision.cpp:1041-1051)
        min_next_hop = self._get_min_nexthop_threshold(
            best_path_result, prefix_entries
        )
        dynamic = len(entry.nexthops) - static_nexthops
        if min_next_hop is not None and min_next_hop > dynamic:
            return

        if has_bgp:
            best_next_hop = prefix_state.get_loopback_vias(
                {best_path_result.best_node},
                prefix.is_v4,
                best_path_result.best_igp_metric,
            )
            if len(best_next_hop) == 1:
                entry.best_nexthop = best_next_hop[0]
                entry.best_prefix_entry = prefix_entries[
                    best_path_result.best_node
                ][best_path_result.best_area]
                entry.do_not_install = self.bgp_dry_run
        else:
            entry.best_prefix_entry = prefix_entries.get(
                best_path_result.best_node, {}
            ).get(best_path_result.best_area)
            entry.best_area = best_path_result.best_area

        unicast_entries[prefix] = entry

    def _get_min_nexthop_threshold(
        self,
        nodes: BestPathCalResult,
        prefix_entries: Dict[str, Dict[str, PrefixEntry]],
    ) -> Optional[int]:
        """Max of announcers' minNexthop requirements (Decision.cpp:632-649)."""
        result: Optional[int] = None
        for node in nodes.nodes:
            for entry in prefix_entries.get(node, {}).values():
                if entry.min_nexthop is not None and (
                    result is None or entry.min_nexthop > result
                ):
                    result = entry.min_nexthop
        return result

    # ------------------------------------------------------------------
    # nexthop computation
    # ------------------------------------------------------------------

    @staticmethod
    def get_min_cost_nodes(
        spf_result, dst_nodes: Set[str]
    ) -> Tuple[Metric, Set[str]]:
        """Closest subset of dst_nodes (Decision.cpp:1068-1091)."""
        shortest = INF_METRIC
        min_cost_nodes: Set[str] = set()
        for dst in dst_nodes:
            res = spf_result.get(dst)
            if res is None:
                continue
            if shortest >= res.metric:
                if shortest > res.metric:
                    shortest = res.metric
                    min_cost_nodes = set()
                min_cost_nodes.add(dst)
        return shortest, min_cost_nodes

    def get_next_hops_with_metric(
        self,
        my_node_name: str,
        dst_node_names: Set[str],
        per_destination: bool,
        area_link_states: Dict[str, LinkState],
    ) -> Tuple[Metric, Dict[Tuple[str, str], Metric]]:
        """Nexthop-node candidates with their distance-to-destination
        (Decision.cpp:1093-1179): shortest-path neighbors plus, if enabled,
        RFC 5286 loop-free alternates."""
        next_hop_nodes: Dict[Tuple[str, str], Metric] = {}
        shortest_metric = INF_METRIC

        for link_state in area_link_states.values():
            spf_from_here = self._spf(link_state, my_node_name)
            min_metric, min_cost_nodes = self.get_min_cost_nodes(
                spf_from_here, dst_node_names
            )
            # lowest metric wins across areas; ties merge (ECMP across areas)
            if shortest_metric < min_metric:
                continue
            if shortest_metric > min_metric:
                shortest_metric = min_metric
                next_hop_nodes = {}
            if not min_cost_nodes:
                continue

            for dst in min_cost_nodes:
                dst_ref = dst if per_destination else ""
                for nh in spf_from_here[dst].next_hops:
                    next_hop_nodes[(nh, dst_ref)] = (
                        shortest_metric
                        - self._dist(link_state, my_node_name, nh)
                    )

            if self.compute_lfa_paths:
                for link in link_state.ordered_links_from_node(my_node_name):
                    if not link.is_up():
                        continue
                    neighbor = link.other_node_name(my_node_name)
                    spf_from_neighbor = self._spf(link_state, neighbor)
                    if my_node_name not in spf_from_neighbor:
                        continue
                    neighbor_to_here = spf_from_neighbor[my_node_name].metric
                    for dst in dst_node_names:
                        res = spf_from_neighbor.get(dst)
                        if res is None:
                            continue
                        dist_from_neighbor = res.metric
                        # RFC 5286 LFA condition (Decision.cpp:1163)
                        if dist_from_neighbor < shortest_metric + neighbor_to_here:
                            key = (neighbor, dst if per_destination else "")
                            prev = next_hop_nodes.get(key)
                            if prev is None or prev > dist_from_neighbor:
                                next_hop_nodes[key] = dist_from_neighbor
        return shortest_metric, next_hop_nodes

    def get_next_hops(
        self,
        my_node_name: str,
        dst_node_names: Set[str],
        is_v4: bool,
        per_destination: bool,
        min_metric: Metric,
        next_hop_nodes: Dict[Tuple[str, str], Metric],
        swap_label: Optional[int],
        area_link_states: Dict[str, LinkState],
        prefix_areas: Set[str],
    ) -> Set[NextHop]:
        """Resolve nexthop nodes to concrete adjacency nexthops with MPLS
        actions (Decision.cpp:1181-1271)."""
        assert next_hop_nodes
        next_hops: Set[NextHop] = set()
        dst_refs = sorted(dst_node_names) if per_destination else [""]
        for area, link_state in area_link_states.items():
            if area not in prefix_areas:
                continue
            for link in link_state.ordered_links_from_node(my_node_name):
                for dst_node in dst_refs:
                    neighbor = link.other_node_name(my_node_name)
                    dist_to_dst = next_hop_nodes.get((neighbor, dst_node))
                    if dist_to_dst is None or not link.is_up():
                        continue
                    # don't route to dstA via neighbor dstB (both are dests)
                    if (
                        dst_node
                        and neighbor in dst_node_names
                        and neighbor != dst_node
                    ):
                        continue
                    dist_over_link = (
                        link.metric_from_node(my_node_name) + dist_to_dst
                    )
                    # without LFA only shortest-path links qualify
                    if not self.compute_lfa_paths and dist_over_link != min_metric:
                        continue

                    mpls_action: Optional[MplsAction] = None
                    if swap_label is not None:
                        if neighbor in dst_node_names:
                            mpls_action = MplsAction(MplsActionCode.PHP)
                        else:
                            mpls_action = MplsAction(
                                MplsActionCode.SWAP, swap_label=swap_label
                            )
                    if dst_node and dst_node != neighbor:
                        dst_db = link_state.get_adjacency_databases().get(
                            dst_node
                        )
                        if dst_db is None or not is_mpls_label_valid(
                            dst_db.node_label
                        ):
                            continue
                        dst_label = dst_db.node_label
                        assert mpls_action is None
                        mpls_action = MplsAction(
                            MplsActionCode.PUSH, push_labels=(dst_label,)
                        )

                    next_hops.add(
                        NextHop(
                            address=(
                                link.nh_v4_from_node(my_node_name)
                                if is_v4
                                else link.nh_v6_from_node(my_node_name)
                            ),
                            iface=link.iface_from_node(my_node_name),
                            metric=dist_over_link,
                            mpls_action=mpls_action,
                            area=link.area,
                            neighbor_node=neighbor,
                        )
                    )
        return next_hops

    # ------------------------------------------------------------------

