"""BGP-style metric vector comparison.

Behavioral port of openr/common/Util.cpp MetricVectorUtils (:1051-1228):
entities sorted by descending priority are compared pairwise; an entity
present on only one side resolves by its CompareType ("loner" rules); a
tie-breaker entity can only produce TIE_WINNER/TIE_LOOSER, which a later
decisive (non-tiebreak) entity overrides.
"""

from __future__ import annotations

import enum
from typing import List, Optional, Sequence, Tuple

from openr_tpu.types import CompareType, MetricEntity, MetricVector


class CompareResult(enum.Enum):
    WINNER = 4
    TIE_WINNER = 3
    TIE = 2
    TIE_LOOSER = 1
    LOOSER = 0
    ERROR = -1


def invert(r: CompareResult) -> CompareResult:
    return {
        CompareResult.WINNER: CompareResult.LOOSER,
        CompareResult.TIE_WINNER: CompareResult.TIE_LOOSER,
        CompareResult.TIE: CompareResult.TIE,
        CompareResult.TIE_LOOSER: CompareResult.TIE_WINNER,
        CompareResult.LOOSER: CompareResult.WINNER,
        CompareResult.ERROR: CompareResult.ERROR,
    }[r]


def is_decisive(r: CompareResult) -> bool:
    return r in (CompareResult.WINNER, CompareResult.LOOSER, CompareResult.ERROR)


def _sorted_metrics(mv: MetricVector) -> List[MetricEntity]:
    return sorted(mv.metrics, key=lambda e: -e.priority)


def compare_metrics(
    l: Sequence[int], r: Sequence[int], tie_breaker: bool
) -> CompareResult:
    if len(l) != len(r):
        return CompareResult.ERROR
    for lv, rv in zip(l, r):
        if lv > rv:
            return (
                CompareResult.TIE_WINNER if tie_breaker else CompareResult.WINNER
            )
        if lv < rv:
            return (
                CompareResult.TIE_LOOSER if tie_breaker else CompareResult.LOOSER
            )
    return CompareResult.TIE


def result_for_loner(entity: MetricEntity) -> CompareResult:
    if entity.op == CompareType.WIN_IF_PRESENT:
        return (
            CompareResult.TIE_WINNER
            if entity.is_best_path_tiebreaker
            else CompareResult.WINNER
        )
    if entity.op == CompareType.WIN_IF_NOT_PRESENT:
        return (
            CompareResult.TIE_LOOSER
            if entity.is_best_path_tiebreaker
            else CompareResult.LOOSER
        )
    return CompareResult.TIE  # IGNORE_IF_NOT_PRESENT


def _maybe_update(target: CompareResult, update: CompareResult) -> CompareResult:
    if is_decisive(update) or target == CompareResult.TIE:
        return update
    return target


def compare_metric_vectors(
    l: Optional[MetricVector], r: Optional[MetricVector]
) -> CompareResult:
    if l is None or r is None:
        return CompareResult.ERROR
    if l.version != r.version:
        return CompareResult.ERROR

    lm, rm = _sorted_metrics(l), _sorted_metrics(r)
    result = CompareResult.TIE
    i = j = 0
    while not is_decisive(result) and i < len(lm) and j < len(rm):
        le, re = lm[i], rm[j]
        if le.id == re.id:
            if le.is_best_path_tiebreaker != re.is_best_path_tiebreaker:
                result = _maybe_update(result, CompareResult.ERROR)
            else:
                result = _maybe_update(
                    result,
                    compare_metrics(
                        le.metric, re.metric, le.is_best_path_tiebreaker
                    ),
                )
            i += 1
            j += 1
        elif le.priority > re.priority:
            result = _maybe_update(result, result_for_loner(le))
            i += 1
        elif le.priority < re.priority:
            result = _maybe_update(result, invert(result_for_loner(re)))
            j += 1
        else:
            # same priority, different entity types
            result = _maybe_update(result, CompareResult.ERROR)
    while not is_decisive(result) and i < len(lm):
        result = _maybe_update(result, result_for_loner(lm[i]))
        i += 1
    while not is_decisive(result) and j < len(rm):
        result = _maybe_update(result, invert(result_for_loner(rm[j])))
        j += 1
    return result


def get_metric_entity_by_type(
    mv: MetricVector, entity_id: int
) -> Optional[MetricEntity]:
    for e in mv.metrics:
        if e.id == entity_id:
            return e
    return None


# Entity ids/priorities used when augmenting BGP vectors with IGP cost
# (thrift::MetricEntityType::OPENR_IGP_COST / MetricEntityPriority)
OPENR_IGP_COST_TYPE = 1
OPENR_IGP_COST_PRIORITY = 100


def create_igp_cost_entity(igp_metric: int) -> MetricEntity:
    """OPENR_IGP_COST entity: lower IGP metric wins (Decision.cpp:757-763)."""
    return MetricEntity(
        id=OPENR_IGP_COST_TYPE,
        priority=OPENR_IGP_COST_PRIORITY,
        op=CompareType.WIN_IF_NOT_PRESENT,
        is_best_path_tiebreaker=False,
        metric=(-igp_metric,),
    )
