"""TPU batched SPF solver backend.

Drop-in replacement for the CPU oracle: inherits the entire route-assembly
pipeline from SpfSolver and overrides the SPF access seam so that distances
and ECMP nexthop sets come from one batched min-plus solve on device
(openr_tpu.ops.spf) instead of per-source Dijkstra runs.

Per (area, topology-version, node) the solver compiles the LinkState to
padded arrays and solves for sources = {me} ∪ neighbors(me) in a single
device call — exactly the rows the route pipeline consumes:
  - reachability/metric from me (best-announcer selection, min-cost nodes)
  - dist(neighbor, t) for the triangle-condition ECMP nexthops and for the
    RFC 5286 LFA inequality
Nexthop sets are materialized lazily per queried destination via the triangle
condition w(me,n) + D[n,t] == D[me,t], which reproduces Dijkstra's
nexthop-union semantics (LinkState.cpp:855-871) without tracing paths.

KSP2 path enumeration stays on the LinkState host path (get_kth_paths);
fusing it on device is tracked for the ops layer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.lsdb.link_state import LinkState
from openr_tpu.ops.graph import INF, CompiledGraph, compile_graph
from openr_tpu.ops.spf import batched_spf
from openr_tpu.solver.cpu import Metric, SpfSolver


class _NodeView:
    """NodeSpfResult-compatible view over the device distance matrix."""

    __slots__ = ("metric", "_result", "_dest")

    def __init__(self, metric: Metric, result: "_TpuSpfResult", dest: str):
        self.metric = metric
        self._result = result
        self._dest = dest

    @property
    def next_hops(self) -> Set[str]:
        return self._result.next_hops_of(self._dest)


class _TpuSpfResult:
    """SpfResult-compatible mapping dest -> _NodeView, backed by D rows."""

    def __init__(self, area: "_AreaSolve", source: str):
        self._area = area
        self._source = source
        self._src_row = area.row_map[source]
        self._nh_cache: Dict[str, Set[str]] = {}

    def __contains__(self, dest: str) -> bool:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return False
        return self._area.d[self._src_row, col] < INF

    def get(self, dest: str) -> Optional[_NodeView]:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return None
        metric = int(self._area.d[self._src_row, col])
        if metric >= INF:
            return None
        return _NodeView(metric, self, dest)

    def __getitem__(self, dest: str) -> _NodeView:
        view = self.get(dest)
        if view is None:
            raise KeyError(dest)
        return view

    def next_hops_of(self, dest: str) -> Set[str]:
        """ECMP nexthop node set for source -> dest via triangle condition.

        Only valid when source is the solve's primary node: neighbor rows for
        other sources are not in the batch, so a silent partial answer here
        would corrupt routes — fail fast instead (the pipeline only reads
        nexthop sets from my_node_name's perspective).
        """
        if self._source != self._area.sources[0]:
            raise RuntimeError(
                f"nexthop sets are only solved for {self._area.sources[0]}, "
                f"requested for {self._source}"
            )
        cached = self._nh_cache.get(dest)
        if cached is not None:
            return cached
        area = self._area
        me = self._source
        nhs: Set[str] = set()
        if dest != me:
            col = area.graph.node_index.get(dest)
            if col is not None:
                d_me = area.d[self._src_row, col]
                if d_me < INF:
                    ls = area.link_state
                    for link in ls.ordered_links_from_node(me):
                        if not link.is_up():
                            continue
                        n = link.other_node_name(me)
                        n_row = area.row_map.get(n)
                        if n_row is None:
                            continue
                        if ls.is_node_overloaded(n) and n != dest:
                            continue
                        w = link.metric_from_node(me)
                        if w + area.d[n_row, col] == d_me:
                            nhs.add(n)
        self._nh_cache[dest] = nhs
        return nhs


class _AreaSolve:
    """One batched device solve: sources = [me] + up-neighbors(me)."""

    def __init__(self, link_state: LinkState, me: str) -> None:
        self.link_state = link_state
        self.me = me
        self.graph: CompiledGraph = compile_graph(link_state)
        neighbors = sorted(
            {
                link.other_node_name(me)
                for link in link_state.links_from_node(me)
                if link.is_up()
            }
        )
        self.sources: List[str] = [me] + neighbors
        rows = np.array(
            [self.graph.node_index[s] for s in self.sources], dtype=np.int32
        )
        # one device call for the whole batch; copy back once
        self.d = np.asarray(batched_spf(self.graph, rows))
        self.row_map: Dict[str, int] = {
            name: i for i, name in enumerate(self.sources)
        }


class TpuSpfSolver(SpfSolver):
    """SpfSolver with the batched TPU distance backend."""

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        # (area name, node) -> (LinkState identity, topology version, solve);
        # keyed by the stable area name so a replaced LinkState object for the
        # same area overwrites its predecessor instead of leaking it
        self._solves: Dict[
            Tuple[str, str], Tuple[int, int, _AreaSolve]
        ] = {}
        self.device_solves = 0  # counter: batched device calls

    def _area_solve(
        self, link_state: LinkState, node: str
    ) -> Optional[_AreaSolve]:
        """The cached device solve for this area, or None when the node is
        not present in this area's graph (multi-area: fall back to CPU)."""
        if not link_state.has_node(node) and not link_state.links_from_node(
            node
        ):
            return None
        key = (link_state.area, node)
        cached = self._solves.get(key)
        if (
            cached is not None
            and cached[0] == id(link_state)
            and cached[1] == link_state.version
        ):
            return cached[2]
        solve = _AreaSolve(link_state, node)
        self.device_solves += 1
        self._solves[key] = (id(link_state), link_state.version, solve)
        return solve

    # -- SPF access seam -------------------------------------------------

    def _spf(self, link_state: LinkState, node: str):
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is not None and node in solve.row_map:
            return _TpuSpfResult(solve, node)
        # node outside the solved batch (not me / my neighbor), or an area
        # this node does not participate in: CPU oracle fallback
        return link_state.get_spf_result(node)

    def _dist(self, link_state: LinkState, a: str, b: str) -> Optional[Metric]:
        if a == b:
            return 0
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is not None:
            row = solve.row_map.get(a)
            col = solve.graph.node_index.get(b)
            if row is not None and col is not None:
                metric = int(solve.d[row, col])
                return metric if metric < INF else None
        return link_state.get_metric_from_a_to_b(a, b)
